"""Numeric-anomaly defense: skip, blame, quarantine
(docs/resilience.md "Numeric anomalies").

The reference's NanTensorHook only *detects* — and detection alone
loses: with deterministic data (batches are pure functions of
``(seed, index)``, the property recovery relies on for bit-identical
re-seek), a poisoned batch NaNs again on every restarted attempt until
the restart budget burns out. This module turns detection into a
defense with three tiers:

1. **Skip** — the in-graph guard (``train/step.StepOptions(
   skip_nonfinite=True)``) makes a non-finite step a device-side no-op:
   the old state survives bit-identically (step counter included) and a
   per-step ``nonfinite`` flag rides the metrics. ``AnomalyPolicy``
   consumes the flag on the host and lets the run continue under a
   bounded skip budget.
2. **Blame** — every skip records the exact raw ``(seed, index)`` it
   consumed into an atomically-written quarantine file next to the
   checkpoints; when poisoning is only discovered late (NaNGuard
   cadence, a poisoned restart with the guard off, a spent budget),
   ``bisect_blame`` finds the index by bisection over deterministic
   re-seek replay from the last-good checkpoint, and ``blame_hook``
   runs that search at the Supervisor's ``poisoned`` restart boundary.
3. **Quarantine** — ``data/pipeline.QuarantineFilter`` re-seeks the
   stream *around* quarantined indices, so the surviving trajectory is
   a pure function of ``(seed, quarantine set)``: same-seed recovery
   stays bit-identical, and a poisoned restart provably converges (each
   round either finishes or permanently removes one bad index) instead
   of replaying the same batch until ``SupervisorExhausted``.

Nothing here imports jax — the policy reads already-computed host
scalars, the file format is plain JSON, and the bisection is arithmetic
— so the module is usable from pure-host tests and tools.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Callable

import numpy as np

from ..obs import flightrec as flightrec_lib
from ..obs.registry import Registry, default_registry
from .supervisor import POISONED

logger = logging.getLogger(__name__)

__all__ = [
    "SKIPPED_TOTAL",
    "SPIKES_TOTAL",
    "CAUSE_NONFINITE",
    "CAUSE_QUARANTINED",
    "CAUSE_BISECT",
    "QUARANTINE_FILE",
    "AnomalyConfig",
    "AnomalyPolicy",
    "SkipBudgetExhausted",
    "quarantine_path",
    "read_quarantine",
    "load_quarantine",
    "quarantine_index",
    "bisect_blame",
    "blame_hook",
]

#: metric names (docs/observability.md "Recovery metrics")
SKIPPED_TOTAL = "anomaly_skipped_batches_total"
SPIKES_TOTAL = "anomaly_spikes_total"

#: blame causes recorded in the quarantine file / skip-counter labels
CAUSE_NONFINITE = "nonfinite"    # live in-graph flag, exact index known
CAUSE_QUARANTINED = "quarantined"  # stream re-seek around a known hole
CAUSE_BISECT = "bisect"          # found by restart-time replay bisection

#: file name next to the checkpoints (same directory the .corrupt/
#: checkpoint quarantine lives under — one place to look after a run)
QUARANTINE_FILE = "quarantine.json"


class SkipBudgetExhausted(FloatingPointError):
    """The AnomalyPolicy's skip budget ran out: too many non-finite
    batches for "drop and continue" to be a defensible recovery. A
    FloatingPointError subclass so ``classify_failure`` maps it to the
    ``poisoned`` class unchanged; carries the blamed raw batch
    ``index`` (and the step that consumed it) so restart-time blame can
    shortcut the bisection."""

    def __init__(self, step: int, index: int, budget: int):
        super().__init__(
            f"anomaly skip budget exhausted: non-finite step {step} "
            f"(raw batch index {index}) would be skip #{budget + 1} "
            f"of {budget} allowed"
        )
        self.step = step
        self.index = index
        self.budget = budget


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    #: non-finite batches the policy may skip before raising
    #: SkipBudgetExhausted (per policy instance — i.e. per supervised
    #: attempt when the builder constructs one per attempt)
    skip_budget: int = 8
    #: >0 enables the EWMA loss-spike detector: a fetched loss above
    #: ``spike_factor × ewma`` emits ``anomaly_spike`` + counts
    #: ``anomaly_spikes_total``. Detection only: a finite-but-spiking
    #: step's update is already applied on device — the guard can only
    #: veto non-finite updates — so a spike is evidence for operators
    #: (and ``fail_on_spike``), not a skip.
    spike_factor: float = 0.0
    #: EWMA smoothing for the spike baseline
    spike_ewma_alpha: float = 0.1
    #: steps observed before the baseline is trusted (loss at init is
    #: arbitrary; comparing against it would page on step 2)
    spike_warmup_steps: int = 20
    #: escalate a detected spike to FloatingPointError (the Supervisor's
    #: ``poisoned`` path) instead of recording it
    fail_on_spike: bool = False

    def __post_init__(self):
        if self.skip_budget < 0:
            raise ValueError("skip_budget must be >= 0")
        if self.spike_factor < 0:
            raise ValueError("spike_factor must be >= 0 (0 disables)")


# ---------------------------------------------------------------------------
# Quarantine file: atomically-written blame record next to the checkpoints
# ---------------------------------------------------------------------------


def quarantine_path(directory: str) -> str:
    return os.path.join(
        os.path.abspath(os.path.expanduser(directory)), QUARANTINE_FILE)


def read_quarantine(directory: str) -> dict:
    """The full quarantine document: ``{"version": 1, "indices": [...],
    "entries": [{index, step, cause, note, t}, ...]}``. Missing file ==
    empty document (a fresh run has nothing quarantined)."""
    path = quarantine_path(directory)
    if not os.path.exists(path):
        return {"version": 1, "indices": [], "entries": []}
    with open(path) as f:
        doc = json.load(f)
    doc.setdefault("indices", [])
    doc.setdefault("entries", [])
    return doc


def load_quarantine(directory: str) -> frozenset[int]:
    """Just the condemned raw batch indices — what
    ``data/pipeline.QuarantineFilter`` consumes."""
    return frozenset(int(i) for i in read_quarantine(directory)["indices"])


def quarantine_index(directory: str, index: int, *, step: int | None = None,
                     cause: str = CAUSE_NONFINITE, note: str = "",
                     flightrec=None, clock: Callable[[], float] = time.time,
                     ) -> bool:
    """Blame raw batch ``index``: append it to the quarantine file via
    tmp + fsync + rename (a torn write must not look complete — the
    file steers every future incarnation's data stream) and emit
    ``anomaly_blame``. The entry's ``t`` stamp reads the injectable
    ``clock`` seam (wall time by default) — informational metadata,
    but the blame path is replayed by the bisector, so even its
    timestamps route through a seam rather than an ambient read.
    Returns False when the index was already
    quarantined (idempotent: Supervisor hooks re-run on hook failure)."""
    doc = read_quarantine(directory)
    index = int(index)
    if index in set(int(i) for i in doc["indices"]):
        return False
    doc["indices"] = sorted({*map(int, doc["indices"]), index})
    doc["entries"].append({
        "index": index,
        "step": None if step is None else int(step),
        "cause": cause,
        "note": str(note)[:200],
        "t": clock(),
    })
    path = quarantine_path(directory)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    rec = flightrec if flightrec is not None else flightrec_lib.default_recorder()
    rec.emit("anomaly_blame", step=step, index=index, cause=cause)
    logger.warning(
        "quarantined batch index %d (cause=%s, step=%s) -> %s",
        index, cause, step, path,
    )
    return True


# ---------------------------------------------------------------------------
# Policy: host-side consumer of the in-graph nonfinite flag
# ---------------------------------------------------------------------------


class AnomalyPolicy:
    """Decides what a raised ``nonfinite`` flag means for the run.

    Wire as ``Trainer(anomaly_policy=...)`` together with
    ``StepOptions(skip_nonfinite=True)``: the loop calls ``observe``
    after every compiled step and *does not count* steps the policy
    skips (the device already kept the old state, so the skipped batch
    simply vanishes from the trajectory). ``observe`` fetches the flag
    scalar, which synchronizes the host with the just-dispatched step —
    the cost of per-step exactness; the guard itself stays pure device
    work, and runs that only want lazy detection use ``NaNGuard``
    without a policy.

    ``index_fn`` returns the raw ``(seed, index)`` of the batch the
    current step consumed — ``lambda: stream.raw`` for a
    ``QuarantineFilter`` (or ``lambda: it.index`` for a bare
    ``RetryingIterator``). Without one the policy counts deliveries
    itself from ``start_index``, which is only correct when no
    quarantine holes exist mid-run.
    """

    def __init__(self, directory: str, cfg: AnomalyConfig = AnomalyConfig(),
                 *, index_fn: Callable[[], int] | None = None,
                 start_index: int = 0, registry: Registry | None = None,
                 flightrec=None):
        self.directory = directory
        self.cfg = cfg
        self.index_fn = index_fn
        self.registry = registry if registry is not None else default_registry()
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        #: batches skipped by this policy (== budget consumed)
        self.skipped = 0
        self.spikes = 0
        self._count = int(start_index)
        self._ewma: float | None = None
        self._seen = 0
        self._m_skip = self.registry.counter(
            SKIPPED_TOTAL, "batches dropped by the numeric-anomaly defense",
            cause=CAUSE_NONFINITE)
        self._m_spike = self.registry.counter(
            SPIKES_TOTAL, "loss spikes detected against the EWMA baseline")

    def _index(self) -> int:
        if self.index_fn is not None:
            return int(self.index_fn())
        return self._count

    def observe(self, step: int, metrics: dict) -> bool:
        """Consume one step's metrics; True means the step was a
        device-side no-op and the loop must not count it. Raises
        ``SkipBudgetExhausted`` (a FloatingPointError → ``poisoned``)
        when the budget is spent, after blaming the index."""
        if "nonfinite" not in metrics:
            raise RuntimeError(
                "AnomalyPolicy needs the per-step 'nonfinite' flag — build "
                "the step with StepOptions(skip_nonfinite=True)"
            )
        # lazy: the shared read-side contract lives next to the flag's
        # producer; importing it at call time keeps this module free of
        # train/ at import (resilience package init order)
        from ..train.step import step_nonfinite

        self._count += 1
        index = self._index()
        if step_nonfinite(metrics):
            if self.skipped >= self.cfg.skip_budget:
                # the index is still blamed — restart-time recovery can
                # then re-seek around it instead of rediscovering it by
                # bisection
                quarantine_index(self.directory, index, step=step,
                                 cause=CAUSE_NONFINITE,
                                 note="skip budget exhausted",
                                 flightrec=self.flightrec)
                raise SkipBudgetExhausted(step, index, self.cfg.skip_budget)
            self.skipped += 1
            self._m_skip.inc()
            self.flightrec.emit("anomaly_skip", step=step, index=index,
                                cause=CAUSE_NONFINITE)
            quarantine_index(self.directory, index, step=step,
                             cause=CAUSE_NONFINITE, flightrec=self.flightrec)
            logger.warning(
                "anomaly: non-finite step %d skipped in-graph (batch index "
                "%d quarantined; %d/%d budget used)",
                step, index, self.skipped, self.cfg.skip_budget,
            )
            return True
        if self.cfg.spike_factor > 0 and "loss" in metrics:
            self._observe_loss(step, index,
                               float(np.asarray(metrics["loss"])))
        return False

    def _observe_loss(self, step: int, index: int, loss: float) -> None:
        self._seen += 1
        ewma = self._ewma
        if (ewma is not None and self._seen > self.cfg.spike_warmup_steps
                and loss > self.cfg.spike_factor * ewma):
            self.spikes += 1
            self._m_spike.inc()
            self.flightrec.emit("anomaly_spike", step=step, index=index,
                                loss=round(loss, 6), ewma=round(ewma, 6))
            logger.warning(
                "anomaly: loss spike at step %d (loss=%g vs ewma=%g, "
                "factor %g)", step, loss, ewma, self.cfg.spike_factor,
            )
            if self.cfg.fail_on_spike:
                raise FloatingPointError(
                    f"loss spike at step {step}: {loss:g} > "
                    f"{self.cfg.spike_factor:g} x ewma {ewma:g}"
                )
            return  # a spike must not drag the baseline up toward itself
        a = self.cfg.spike_ewma_alpha
        self._ewma = loss if ewma is None else (1 - a) * ewma + a * loss


# ---------------------------------------------------------------------------
# Blame bisection: find the poisoning index by deterministic re-seek replay
# ---------------------------------------------------------------------------


def bisect_blame(probe: Callable[[int], bool], lo: int, hi: int) -> int | None:
    """First effective step ``k`` in ``(lo, hi]`` whose replay poisons
    the run, by bisection: ``probe(m)`` answers "is the state poisoned
    after replaying effective steps ``(lo, m]`` from the last-good
    checkpoint?" — monotone in ``m`` because non-finites propagate
    through every optax update, which is what makes bisection sound.
    Returns None when ``probe(hi)`` is clean (no poison in the window).
    O(log(hi−lo)) replays, each a deterministic re-seek — no state from
    the poisoned attempt is needed, only the checkpoint and the seed."""
    if hi <= lo:
        return None
    if not probe(hi):
        return None
    good, bad = lo, hi
    while bad - good > 1:
        mid = (good + bad) // 2
        if probe(mid):
            bad = mid
        else:
            good = mid
    return bad


def blame_hook(directory: str, probe: Callable[[int, int], bool], *,
               window: int, flightrec=None) -> Callable[[int, str], None]:
    """A ``Supervisor(on_restart=...)`` hook closing the poisoned loop:
    on a ``poisoned`` restart it bisects the window since the last-good
    checkpoint with ``probe(last_good_step, m) -> bool`` (deterministic
    re-seek replay — the caller owns rebuilding state + step fn), maps
    the found *effective* step back to the raw batch index through the
    current quarantine set, and quarantines it. The next attempt's
    ``QuarantineFilter`` then re-seeks around the bad index: each
    poisoned restart permanently removes one index, so the loop
    converges instead of replaying the same batch until exhaustion.
    Idempotent (re-runs after a hook failure re-blame the same index
    at most once) — the Supervisor hook contract."""
    from ..data.pipeline import quarantined_raw_start
    from .faults import _newest_step_on_disk

    rec = flightrec if flightrec is not None else flightrec_lib.default_recorder()

    def hook(restart_index: int, cause: str) -> None:
        if cause != POISONED:
            return
        last_good = _newest_step_on_disk(directory) or 0
        quarantined = load_quarantine(directory)
        step = bisect_blame(lambda m: probe(last_good, m),
                            last_good, last_good + window)
        if step is None:
            logger.warning(
                "anomaly: poisoned restart %d but replay of (%d, %d] is "
                "clean — nothing to quarantine (transient poison?)",
                restart_index, last_good, last_good + window,
            )
            return
        # effective step -> raw index: the k-th surviving batch sits past
        # every already-quarantined index at or before it. The skip
        # itself is counted by the next attempt's QuarantineFilter
        # (cause=quarantined) — blame here is an event, not a skip.
        raw = quarantined_raw_start(step, quarantined)
        quarantine_index(directory, raw, step=step, cause=CAUSE_BISECT,
                         note=f"restart {restart_index} bisection over "
                              f"({last_good}, {last_good + window}]",
                         flightrec=rec)

    return hook
