"""Fleet supervision — multi-process gang orchestration over heartbeats.

The reference's control plane was `tf.train.ClusterSpec` +
`MonitoredTrainingSession`: a chief that watched worker liveness and
restarted the session when one died. Our elasticity model is
checkpoint-restart (train/checkpoint.py: "TPU slices fail whole"), and
PRs 3-6 built every *in-process* piece of it — fault injection, retry
budgets, the in-process Supervisor, fallback restore, the flight
recorder. This module is the missing *cluster-level* layer: a
collective-free control plane that supervises a fleet of worker
PROCESSES, so it runs unchanged on the CPU test rig where jaxlib has no
multiprocess collectives: the control plane uses no collectives and no
device code — liveness, classification, and the common-checkpoint
computation are files, signals, and manifest reads.

Protocol (docs/resilience.md "Fleet"):

- **Heartbeats.** Each worker owns one heartbeat file under the fleet
  dir and rewrites it atomically (tmp + rename — a reader never sees a
  torn record) with a monotonically increasing ``seq`` plus
  ``{pid, step, attempt, incarnation, phase}``. Beats come from the
  production seams that prove real progress: the in-process
  ``Supervisor`` beats at each attempt boundary and
  ``train.callbacks.HeartbeatCallback`` beats from the step seam — a
  hung loop therefore *stops beating*, which is the signal. An optional
  pulse thread (``pulse_interval_s``) keeps ``seq`` ticking from a
  daemon thread so the fleet can tell a live-but-stalled process
  (seq advances, step frozen → ``stalled``) from a dead one (seq frozen
  → ``dead``).
- **Incarnations.** The fleet bumps an on-disk incarnation counter
  before every (re)launch; workers read it at startup and stamp every
  beat with it. A heartbeat from an older incarnation — freshly written
  by a straggler the gang-stop hasn't reaped yet — is treated as
  *absent*, never as liveness.
- **Gang restart.** Any classified failure (missed heartbeats,
  exit-code death, stall) tears the whole gang down: SIGTERM the
  survivors (exercising the coordinated preemption-save path), SIGKILL
  whatever outlives the grace period, compute the newest checkpoint
  step EVERY worker can restore (``newest_common_valid_step``, manifest
  verified), write it as the restore ceiling, bump the incarnation, and
  relaunch everyone — under a restart budget with the same seeded
  escalating backoff the in-process Supervisor uses. Exhaustion raises
  ``FleetExhausted`` and dumps a flight-recorder postmortem.

Failure classification reuses ``classify_failure``: observed failures
are materialized as the exceptions they represent (``WorkerDead`` for
liveness/exit deaths → ``transient``, ``StalledError`` for frozen
steps → ``stalled``) so the fleet and the in-process Supervisor can
never disagree about taxonomy.

Clocks and sleeps are injectable (``FaultClock`` drop-in) so every
liveness edge case — stale-but-ticking vs absent vs stale-incarnation —
is deterministically testable without real processes.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal as signal_lib
import threading
import time
from typing import Any, Callable, Sequence

from ..obs import flightrec as flightrec_lib
from ..obs import goodput
from ..obs.flightrec import FlightRecorder
from ..obs.registry import Registry, default_registry
from .retry import RetryPolicy
from .supervisor import (
    FATAL, POISONED, PREEMPTION, STALLED, TRANSIENT, classify_failure,
)

logger = logging.getLogger(__name__)

#: worker exit-code protocol (tests/chaos_worker.py --fleet speaks it):
#: 0 = reached the target step; EXIT_PREEMPTED = clean coordinated
#: preemption save (gang-stop SIGTERM, or an injected one); EXIT_FAILED
#: = the worker's in-process supervision exhausted — the classified
#: cause rides in the final heartbeat.
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: try again (from a checkpoint)
EXIT_FAILED = 76

#: metric names (documented in docs/observability.md)
FLEET_RESTARTS_TOTAL = "fleet_restarts_total"
FLEET_WORKER_DEATHS_TOTAL = "fleet_worker_deaths_total"

#: every failure class the fleet may carry / restart on
_KNOWN_CAUSES = frozenset({TRANSIENT, POISONED, FATAL, PREEMPTION, STALLED})

#: heartbeat phases a worker moves through; "train"/"done"/"preempted"/
#: "failed" mean the attempt got past build+restore (the gate
#: fleet_restart waits on before declaring the new gang live)
_PAST_BUILD_PHASES = ("train", "done", "preempted", "failed")

_INCARNATION_FILE = "INCARNATION"
_RESTORE_FILE = "RESTORE_STEP"


class WorkerDead(OSError):
    """A fleet worker died without a classified exit: SIGKILL'd,
    crashed, or stopped heartbeating. Subclasses OSError so
    ``classify_failure`` maps it to ``transient`` — the process is
    gone, the state on disk is fine, restart and resume."""


class FleetExhausted(RuntimeError):
    """The fleet restart budget ran out (or the failure class was not
    restartable). ``cause`` is the classified failure class of the last
    gang failure."""

    def __init__(self, cause: str, restarts: int, detail: str = ""):
        super().__init__(
            f"fleet restart budget exhausted after {restarts} gang "
            f"restart(s); last failure class {cause!r}"
            + (f": {detail}" if detail else "")
        )
        self.cause = cause
        self.restarts = restarts


# ---------------------------------------------------------------------------
# On-disk control files (incarnation, restore ceiling)
# ---------------------------------------------------------------------------


def _atomic_write(path: str, text: str) -> None:
    """tmp + rename so a reader never sees a torn record; no fsync —
    these files trade durability for freshness (a record lost to a
    crash IS the signal the protocol detects: a heartbeat that didn't
    reach disk reads as a missed beat, which is the truth)."""
    tmp = f"{path}.tmp"
    # reviewed: deliberately NOT the fsync idiom — see docstring; an
    # fsync per beat would put a disk flush on the liveness hot path
    with open(tmp, "w") as f:  # dtflint: disable=atomic-durable-write
        f.write(text)
    os.replace(tmp, path)


def heartbeat_path(fleet_dir: str, worker: int) -> str:
    """The one heartbeat file of worker ``worker`` under the fleet dir —
    the single definition of the layout, shared by writer and monitor."""
    return os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)),
        f"heartbeat-{worker}.json",
    )


def read_incarnation(fleet_dir: str) -> int:
    """Current fleet incarnation (0 when no fleet has ever run here).
    Workers call this at startup and stamp every heartbeat with it."""
    path = os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), _INCARNATION_FILE)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except FileNotFoundError:
        return 0
    except (OSError, ValueError) as e:
        logger.warning("unreadable incarnation file %s (%s); assuming 0",
                       path, e)
        return 0


def write_incarnation(fleet_dir: str, incarnation: int) -> None:
    d = os.path.abspath(os.path.expanduser(fleet_dir))
    os.makedirs(d, exist_ok=True)
    _atomic_write(os.path.join(d, _INCARNATION_FILE), f"{int(incarnation)}\n")


def read_restore_step(fleet_dir: str) -> int | None:
    """Restore ceiling for the current incarnation: workers restore the
    newest valid step <= this (``init_or_restore(step=...)``), so the
    whole gang resumes from the same — latest COMMON — checkpoint.
    None = no ceiling (first incarnation; restore your newest)."""
    path = os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), _RESTORE_FILE)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        logger.warning("unreadable restore-step file %s (%s); no ceiling",
                       path, e)
        return None


def write_restore_step(fleet_dir: str, step: int) -> None:
    d = os.path.abspath(os.path.expanduser(fleet_dir))
    os.makedirs(d, exist_ok=True)
    _atomic_write(os.path.join(d, _RESTORE_FILE), f"{int(step)}\n")


def clear_restore_step(fleet_dir: str) -> None:
    """Remove the restore ceiling. Every fresh fleet run starts here: a
    ceiling left behind by a PREVIOUS run in the same workdir would
    silently roll a longer continuation run back to an old step."""
    path = os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), _RESTORE_FILE)
    if os.path.exists(path):
        os.remove(path)


# ---------------------------------------------------------------------------
# Newest common valid checkpoint (fleet side, jax-free)
# ---------------------------------------------------------------------------


def valid_steps(ckpt_dir: str) -> list[int]:
    """Every step under ``ckpt_dir`` whose MANIFEST.dtf verifies
    (CRC-trailered read + per-shard size check — the same invariants
    ``Checkpointer.verify_manifest`` enforces, reimplemented over
    runtime/io so the control plane never stands up a Checkpointer or
    an orbax manager).
    Steps without a manifest count as valid (pre-manifest checkpoints
    restore unchecked, by design). Ascending; bounded by the worker's
    retention (``max_to_keep``), so verifying all of them is cheap."""
    d = os.path.abspath(os.path.expanduser(ckpt_dir))
    if not os.path.isdir(d):
        return []
    steps = sorted(
        int(n) for n in os.listdir(d)
        if n.isdigit() and os.path.isdir(os.path.join(d, n)))
    return [s for s in steps if _step_dir_valid(os.path.join(d, str(s)), s)]


def newest_valid_step(ckpt_dir: str) -> int | None:
    """Newest restorable step under ``ckpt_dir`` (None when nothing
    is)."""
    steps = valid_steps(ckpt_dir)
    return steps[-1] if steps else None


def _step_dir_valid(step_dir: str, step: int) -> bool:
    manifest = os.path.join(step_dir, "MANIFEST.dtf")
    if not os.path.exists(manifest):
        return True  # pre-manifest checkpoint: allowed, unchecked
    from ..runtime import io as io_lib

    try:
        entries = json.loads(io_lib.read_payload(manifest))["files"]
        for entry in entries:
            p = os.path.join(step_dir, entry["path"])
            if not os.path.exists(p) or os.path.getsize(p) != entry["bytes"]:
                logger.warning(
                    "fleet: checkpoint step %d shard %s missing/resized; "
                    "step not restorable", step, entry["path"])
                return False
    except (OSError, ValueError, KeyError, TypeError) as e:
        logger.warning("fleet: checkpoint step %d manifest unreadable (%s); "
                       "step not restorable", step, e)
        return False
    return True


def evict_steps_above(ckpt_dir: str, ceiling: int) -> list[int]:
    """Move every step dir ABOVE ``ceiling`` to ``<dir>/.abandoned/`` —
    called at a gang restart, where the whole gang rolls back to
    ``ceiling``: anything newer is abandoned history. Left in place it
    would (a) shadow the re-trained state at the same step numbers
    (``Checkpointer.save`` skips steps already on disk, so a corrupt or
    stale above-ceiling step would stay the newest forever) and (b) be
    resurrected by a later restore — e.g. an in-process Supervisor
    restart inside the new incarnation restoring the PREVIOUS
    incarnation's newest step. Returns the evicted steps."""
    d = os.path.abspath(os.path.expanduser(ckpt_dir))
    if not os.path.isdir(d):
        return []
    base = os.path.join(d, ".abandoned")
    evicted: list[int] = []
    for name in sorted(os.listdir(d)):
        if not (name.isdigit() and os.path.isdir(os.path.join(d, name))):
            continue
        step = int(name)
        if step <= ceiling:
            continue
        os.makedirs(base, exist_ok=True)
        dst = os.path.join(base, name)
        k = 0
        while os.path.exists(dst):
            k += 1
            dst = os.path.join(base, f"{name}-{k}")
        os.rename(os.path.join(d, name), dst)
        evicted.append(step)
        logger.warning("fleet: abandoned above-ceiling checkpoint step %d "
                       "-> %s", step, dst)
    return evicted


def newest_common_valid_step(ckpt_dirs: Sequence[str]) -> int | None:
    """The newest step EVERY worker retains AND can verify — the gang
    restart point. The intersection matters, not min-of-newest: a
    worker whose retention already evicted the others' newest step must
    not be handed a ceiling it cannot restore (it would silently
    fresh-init at 0 while the rest of the gang resumes — the exact
    inconsistency the ceiling exists to prevent). An empty intersection
    pins the common step to 0: the whole gang fresh-starts, which with
    deterministic data is correct, just maximally conservative. None
    when no dirs given."""
    if not ckpt_dirs:
        return None
    common = set(valid_steps(ckpt_dirs[0]))
    for d in ckpt_dirs[1:]:
        common &= set(valid_steps(d))
    return max(common) if common else 0


# ---------------------------------------------------------------------------
# Heartbeats: writer (worker side) and monitor (fleet side)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Heartbeat:
    """One decoded heartbeat record. ``t`` is the WRITER's clock —
    informational only; staleness is judged by the monitor observing
    ``seq`` changes on its OWN clock, because monotonic clocks are not
    comparable across processes."""

    pid: int
    seq: int
    t: float
    step: int
    attempt: int
    incarnation: int
    phase: str
    cause: str | None = None
    restore_step: int | None = None
    restore_fallback: bool | None = None


def read_heartbeat(path: str) -> Heartbeat | None:
    """Decode the heartbeat at ``path``; None when absent or unreadable
    (an unreadable heartbeat is indistinguishable from a missing one —
    both mean 'no proof of life')."""
    try:
        with open(path) as f:
            data = json.load(f)
        return Heartbeat(
            pid=int(data["pid"]), seq=int(data["seq"]),
            t=float(data["t"]), step=int(data.get("step", 0)),
            attempt=int(data.get("attempt", 0)),
            incarnation=int(data.get("incarnation", 0)),
            phase=str(data.get("phase", "init")),
            cause=data.get("cause"),
            restore_step=data.get("restore_step"),
            restore_fallback=data.get("restore_fallback"),
        )
    except FileNotFoundError:
        return None
    except (OSError, ValueError, KeyError, TypeError) as e:
        logger.warning("unreadable heartbeat %s (%s); treating as absent",
                       path, e)
        return None


class HeartbeatWriter:
    """Worker-side heartbeat emitter: every ``beat()`` bumps ``seq`` and
    atomically rewrites the file with the latest known
    ``{step, attempt, phase, restore...}``. Fields persist across beats,
    so a fleet that only samples the newest record still sees the
    restore note from an earlier one. Thread-safe (the optional pulse
    thread and the train loop both beat)."""

    def __init__(self, path: str, incarnation: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 pulse_interval_s: float | None = None):
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        self.path = path
        self.incarnation = int(incarnation)
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._step = 0
        self._attempt = 0
        self._phase = "init"
        self._cause: str | None = None
        self._restore: tuple[int, bool] | None = None
        self._stop = threading.Event()
        self._pulse: threading.Thread | None = None
        if pulse_interval_s is not None:
            if pulse_interval_s <= 0:
                raise ValueError("pulse_interval_s must be positive")
            self._pulse = threading.Thread(
                target=self._pulse_loop, args=(pulse_interval_s,),
                daemon=True, name="fleet-heartbeat-pulse")
            self._pulse.start()

    def beat(self, step: int | None = None, attempt: int | None = None,
             phase: str | None = None) -> None:
        """Write one heartbeat; omitted fields keep their last value."""
        with self._lock:
            if step is not None:
                self._step = int(step)
            if attempt is not None:
                self._attempt = int(attempt)
            if phase is not None:
                self._phase = str(phase)
            self._seq += 1
            rec = {
                "pid": os.getpid(), "seq": self._seq,
                "t": float(self.clock()), "step": self._step,
                "attempt": self._attempt, "incarnation": self.incarnation,
                "phase": self._phase, "cause": self._cause,
            }
            if self._restore is not None:
                rec["restore_step"], rec["restore_fallback"] = self._restore
            # write INSIDE the lock: beats from the pulse thread and the
            # train loop serialize, so seq order on disk == write order
            _atomic_write(self.path, json.dumps(rec))

    def note_restore(self, step: int, fallback: bool) -> None:
        """Record which checkpoint this incarnation restored from — the
        fleet relays it into its timeline as the gang's ``ckpt_restore``
        evidence."""
        with self._lock:
            self._restore = (int(step), bool(fallback))
        self.beat()

    def finish(self, phase: str, cause: str | None = None) -> None:
        """Terminal beat (``done`` / ``preempted`` / ``failed``) — the
        record the fleet reads after the process exits."""
        with self._lock:
            self._cause = cause
        self.close()
        self.beat(phase=phase)

    def _pulse_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.beat()

    def close(self) -> None:
        """Stop the pulse thread (idempotent; the file is left behind —
        its staleness is the death signal)."""
        self._stop.set()
        if self._pulse is not None:
            self._pulse.join(timeout=5.0)
            self._pulse = None


#: HeartbeatMonitor.check() statuses
WAITING = "waiting"   # no beat yet, launch grace not exceeded
LIVE = "live"
DEAD = "dead"         # no (current-incarnation) beat within the budget
STALLED_HB = "stalled"  # beats ticking, no progress past the budget

#: phases after which a frozen step is expected (the process is exiting)
_TERMINAL_PHASES = ("done", "preempted", "failed")


class HeartbeatMonitor:
    """Fleet-side liveness judgment for ONE worker's heartbeat file.

    Staleness is measured on the MONITOR's clock from the moments it
    *observes* the heartbeat change — never from the heartbeat's own
    timestamp (monotonic clocks don't compare across processes). A
    heartbeat stamped with a different incarnation is ignored entirely:
    a straggler from the previous gang writing right up until its
    SIGKILL must read as *absent*, not alive.

    Stall = ``seq`` still ticking (the pulse thread, or any beat
    source) while (step, attempt, phase) make NO progress past the
    stall budget, outside the terminal phases — so a pulsed worker hung
    in build/restore (phase ``init``) is just as detectable as one hung
    mid-train. Size ``stall_timeout_s`` above the longest legitimate
    restore + first-step compile.
    """

    def __init__(self, path: str, incarnation: int,
                 clock: Callable[[], float] = time.monotonic,
                 heartbeat_timeout_s: float = 30.0,
                 stall_timeout_s: float = 120.0,
                 launch_grace_s: float = 120.0):
        if heartbeat_timeout_s <= 0 or stall_timeout_s <= 0 \
                or launch_grace_s <= 0:
            raise ValueError("liveness budgets must be positive")
        self.path = path
        self.incarnation = int(incarnation)
        self.clock = clock
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.stall_timeout_s = stall_timeout_s
        self.launch_grace_s = launch_grace_s
        self.heartbeat: Heartbeat | None = None  # last ACCEPTED record
        self._t0 = clock()
        self._last_seq: int | None = None
        self._t_seq = self._t0
        self._last_progress: tuple | None = None  # (step, attempt, phase)
        self._t_progress = self._t0

    def check(self) -> str:
        """One liveness poll: WAITING / LIVE / DEAD / STALLED_HB."""
        now = self.clock()
        hb = read_heartbeat(self.path)
        if hb is not None and hb.incarnation == self.incarnation:
            self.heartbeat = hb
            if hb.seq != self._last_seq:
                self._last_seq, self._t_seq = hb.seq, now
            progress = (hb.step, hb.attempt, hb.phase)
            if progress != self._last_progress:
                self._last_progress, self._t_progress = progress, now
        if self._last_seq is None:
            # nothing (of this incarnation) ever beat: grant the launch
            # grace — process spawn + interpreter + framework import
            return DEAD if now - self._t0 > self.launch_grace_s else WAITING
        if now - self._t_seq > self.heartbeat_timeout_s:
            return DEAD
        if (self.heartbeat is not None
                and self.heartbeat.phase not in _TERMINAL_PHASES
                and now - self._t_progress > self.stall_timeout_s):
            return STALLED_HB
        return LIVE


# ---------------------------------------------------------------------------
# Fleet supervisor
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    #: gang restarts allowed (launches = max_restarts + 1)
    max_restarts: int = 3
    #: failure classes that earn a gang restart; others raise immediately
    restart_on: tuple[str, ...] = (TRANSIENT, POISONED, PREEMPTION, STALLED)
    #: escalating backoff between gang restarts (seeded jitter — the
    #: same schedule the in-process Supervisor escalates on)
    backoff: RetryPolicy = RetryPolicy(
        base_s=0.2, multiplier=2.0, max_backoff_s=60.0)
    #: liveness poll cadence
    poll_s: float = 0.25
    #: no heartbeat within this budget after the first one → dead.
    #: SIZE ABOVE the longest legitimate silent window between step-seam
    #: beats (ceiling restore + first-step compile) — or give workers a
    #: HeartbeatWriter pulse thread and let stall detection carry hangs
    heartbeat_timeout_s: float = 30.0
    #: heartbeats ticking but step frozen this long → stalled
    stall_timeout_s: float = 120.0
    #: budget for a launched worker's FIRST beat (interpreter + imports)
    launch_grace_s: float = 120.0
    #: SIGTERM → SIGKILL grace during a gang stop (must cover one
    #: coordinated preemption save)
    term_grace_s: float = 10.0

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        unknown = set(self.restart_on) - (_KNOWN_CAUSES - {FATAL})
        if unknown:
            raise ValueError(f"unknown restart_on classes: {sorted(unknown)}")
        if self.poll_s <= 0 or self.term_grace_s <= 0:
            raise ValueError("poll_s and term_grace_s must be positive")


@dataclasses.dataclass
class _Worker:
    index: int
    handle: Any                      # Popen-shaped: poll/terminate/kill/wait
    monitor: HeartbeatMonitor
    done: bool = False               # exited 0 this incarnation
    ready: bool = False              # heartbeat got past build+restore
    exit_code: int | None = None


class FleetSupervisor:
    """Launch, watch, and gang-restart a fleet of worker processes.

    ``launch(worker_index, incarnation)`` must start worker
    ``worker_index`` and return a process handle with the
    ``subprocess.Popen`` control surface (``poll`` / ``terminate`` /
    ``kill`` / ``wait`` / ``pid``) — tests drive the whole state machine
    with fakes. Each worker heartbeats to
    ``heartbeat_path(workdir, index)``; ``ckpt_dirs`` (one per worker,
    optional) enables the common-checkpoint ceiling at restart.

    ``clock`` and ``sleep`` are injectable (FaultClock / scripted sleeps
    make liveness deterministic); with the default sleep the poll wait
    is an ``Event.wait`` that ``interrupt()`` — or a SIGTERM aimed at
    the fleet process itself — wakes immediately, so a preemption never
    waits out a backoff interval.
    """

    def __init__(
        self,
        launch: Callable[[int, int], Any],
        num_workers: int,
        workdir: str,
        cfg: FleetConfig = FleetConfig(),
        ckpt_dirs: Sequence[str] | None = None,
        registry: Registry | None = None,
        flightrec: FlightRecorder | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] | None = None,
        postmortem_dir: str | None = None,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if ckpt_dirs is not None and len(ckpt_dirs) != num_workers:
            raise ValueError("ckpt_dirs must have one entry per worker")
        self.launch = launch
        self.num_workers = num_workers
        self.workdir = os.path.abspath(os.path.expanduser(workdir))
        self.cfg = cfg
        self.ckpt_dirs = list(ckpt_dirs) if ckpt_dirs is not None else None
        self.registry = registry if registry is not None else default_registry()
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.clock = clock
        self.sleep = sleep
        self.postmortem_dir = postmortem_dir or self.workdir
        self._wake = threading.Event()
        self._stop_signal: list[int] = []
        #: gang restarts performed by the last run() (test observability)
        self.restarts = 0
        self.incarnation = 0
        #: restore ceiling written for the CURRENT incarnation (None =
        #: no ceiling; every checked-in worker must have restored it)
        self._ceiling: int | None = None
        self._workers: list[_Worker] = []
        self._m_deaths = self.registry.counter(
            FLEET_WORKER_DEATHS_TOTAL,
            "fleet worker deaths detected (exit, missed heartbeat, stall)")

    # -- interruptible waiting --------------------------------------------

    def interrupt(self) -> None:
        """Wake the in-progress (or next) poll/backoff wait immediately.
        One-shot: the wakeup is consumed by that wait, so later waits
        pace normally — a durable stop signal lives in ``_stop_signal``,
        not in the event."""
        self._wake.set()

    def _wait(self, delay: float) -> None:
        if self.sleep is not None:
            self.sleep(delay)
            return
        if self._wake.wait(delay):
            # consume the wakeup: a sticky event would turn every later
            # poll/grace loop into a hot spin
            self._wake.clear()

    def _sigterm(self, signum, frame) -> None:
        self._stop_signal.append(signum)
        self._wake.set()

    # -- lifecycle ---------------------------------------------------------

    def _launch_all(self) -> None:
        self._workers = []
        for i in range(self.num_workers):
            handle = self.launch(i, self.incarnation)
            self._workers.append(_Worker(
                index=i, handle=handle,
                monitor=HeartbeatMonitor(
                    heartbeat_path(self.workdir, i), self.incarnation,
                    clock=self.clock,
                    heartbeat_timeout_s=self.cfg.heartbeat_timeout_s,
                    stall_timeout_s=self.cfg.stall_timeout_s,
                    launch_grace_s=self.cfg.launch_grace_s,
                ),
            ))
            self.flightrec.emit(
                "fleet_launch", worker=i, incarnation=self.incarnation,
                pid=getattr(handle, "pid", None))
            logger.info("fleet: launched worker %d (incarnation %d, pid %s)",
                        i, self.incarnation, getattr(handle, "pid", None))

    def run(self) -> dict:
        """Supervise until every worker reaches a clean ``done`` exit.

        Returns ``{"restarts": n, "incarnation": k}``. Raises
        ``FleetExhausted`` when the restart budget runs out or the
        failure class is not restartable (postmortem dumped first).
        """
        os.makedirs(self.workdir, exist_ok=True)
        # new fleet run == new incarnation: stale heartbeats from any
        # previous fleet in this dir can never read as liveness — and no
        # inherited restore ceiling: a previous run's RESTORE_STEP would
        # cap this run's restores at an old step
        self.incarnation = read_incarnation(self.workdir) + 1
        write_incarnation(self.workdir, self.incarnation)
        clear_restore_step(self.workdir)
        self.restarts = 0
        self._ceiling = None
        main = threading.current_thread() is threading.main_thread()
        prev_handler = (signal_lib.signal(signal_lib.SIGTERM, self._sigterm)
                        if main else None)
        self.flightrec.emit("fleet_start", workers=self.num_workers,
                            incarnation=self.incarnation)
        self._launch_all()
        #: (restart_index, cause) whose gang-live confirmation is pending
        pending_restart: tuple[int, str] | None = None
        relayed = False  # restore note relayed for this incarnation
        try:
            while True:
                self._wait(self.cfg.poll_s)
                if self._stop_signal:
                    self._preempted_teardown()
                failure = self._poll_round(pending_restart, relayed)
                pending_restart, relayed, failed = failure
                if failed is not None:
                    worker, cause, detail = failed
                    self._m_deaths.inc()
                    self.flightrec.emit("fleet_worker_dead", worker=worker,
                                        cause=cause, detail=detail[:200])
                    logger.error("fleet: worker %d dead [%s]: %s",
                                 worker, cause, detail)
                    self._gang_stop(cause)
                    if cause not in self.cfg.restart_on \
                            or self.restarts >= self.cfg.max_restarts:
                        self.flightrec.emit("fleet_exhausted", cause=cause,
                                            restarts=self.restarts)
                        self._dump_postmortem(f"fleet_exhausted:{cause}")
                        raise FleetExhausted(cause, self.restarts, detail)
                    pending_restart = self._gang_restart(cause)
                    relayed = False
                elif all(w.done for w in self._workers):
                    self.flightrec.emit("fleet_done",
                                        incarnation=self.incarnation)
                    logger.info("fleet: all %d workers done (incarnation %d,"
                                " %d restart(s))", self.num_workers,
                                self.incarnation, self.restarts)
                    return {"restarts": self.restarts,
                            "incarnation": self.incarnation}
        finally:
            # no worker may outlive its supervisor: on every normal path
            # (done, exhausted, preempted teardown) the gang is already
            # down, so this only fires on an unexpected escape — e.g. a
            # launch() that raised mid-gang — where live workers would
            # otherwise keep training, unsupervised, in this workdir
            for w in self._workers:
                if w.handle.poll() is None:
                    logger.error(
                        "fleet: killing worker %d still alive at "
                        "supervisor exit", w.index)
                    w.handle.kill()
            self._reap_all()
            if main:
                signal_lib.signal(signal_lib.SIGTERM, prev_handler)
            if self._stop_signal:
                # processed a fleet-level SIGTERM: the gang is down; put
                # the original handler back and re-deliver so the outer
                # process sees the signal without the backoff delay
                os.kill(os.getpid(), self._stop_signal[0])

    # -- one poll round ----------------------------------------------------

    def _poll_round(
        self, pending_restart: tuple[int, str] | None, relayed: bool,
    ) -> tuple[tuple[int, str] | None, bool,
               tuple[int, str, str] | None]:
        """Poll every worker once. Returns the updated
        ``(pending_restart, relayed, failure)`` where ``failure`` is
        ``(worker, cause, detail)`` for the first failed worker."""
        failed: tuple[int, str, str] | None = None
        for w in self._workers:
            if w.done:
                continue
            rc = w.handle.poll()
            status = w.monitor.check()
            hb = w.monitor.heartbeat  # refreshed by check()
            # relay the gang's restore evidence BEFORE fleet_restart can
            # be emitted, so the postmortem chain reads causally:
            # gang_stop -> ckpt_restore{fallback} -> fleet_restart
            if (pending_restart is not None and not relayed
                    and hb is not None and hb.restore_step is not None):
                self.flightrec.emit(
                    "ckpt_restore", step=hb.restore_step,
                    fallback=bool(hb.restore_fallback), worker=w.index,
                    relayed=True)
                relayed = True
            if rc is not None:
                w.exit_code = rc
                div = (self._restore_divergence(hb)
                       if pending_restart is not None and not w.done
                       else None)
                cause_detail = self._classify_exit(w, rc, hb)
                if cause_detail is None:
                    if div is not None and failed is None:
                        failed = (w.index, TRANSIENT, div)
                    w.done = w.ready = True
                elif failed is None:
                    failed = (w.index, *cause_detail)
            else:
                if hb is not None and hb.phase in _PAST_BUILD_PHASES:
                    if pending_restart is not None and not w.ready:
                        div = self._restore_divergence(hb)
                        if div is not None and failed is None:
                            failed = (w.index, TRANSIENT, div)
                    w.ready = True
                if status == DEAD and failed is None:
                    failed = (w.index,
                              classify_failure(WorkerDead("missed heartbeats")),
                              f"no heartbeat within "
                              f"{w.monitor.heartbeat_timeout_s}s "
                              f"(pid {getattr(w.handle, 'pid', None)})")
                elif status == STALLED_HB and failed is None:
                    # lazy: StalledError lives in train/callbacks (a
                    # jax-importing module) — keep the hot control-plane
                    # imports light, mirroring classify_failure itself
                    from ..train.callbacks import StalledError

                    failed = (w.index, classify_failure(StalledError()),
                              f"heartbeats ticking but no progress past "
                              f"{w.monitor.stall_timeout_s}s (step "
                              f"{hb.step if hb else '?'})")
        if (pending_restart is not None and failed is None
                and all(w.ready or w.done for w in self._workers)):
            restart_index, cause = pending_restart
            self.flightrec.emit("fleet_restart", restart=restart_index,
                                cause=cause, incarnation=self.incarnation)
            logger.warning("fleet: gang live after restart %d (cause=%s, "
                           "incarnation %d)", restart_index, cause,
                           self.incarnation)
            pending_restart = None
        return pending_restart, relayed, failed

    def _restore_divergence(self, hb: Heartbeat | None) -> str | None:
        """The gang-consistency check behind the restore ceiling: a
        relaunched worker that restored a DIFFERENT step than the one
        written (e.g. its copy of that step was quarantined at read
        time and fallback landed lower, or it fresh-inited) has
        silently diverged from the gang. Classified transient: another
        gang restart recomputes the intersection without the bad step
        and converges."""
        if self._ceiling is None or hb is None:
            return None
        expect = self._ceiling if self._ceiling > 0 else None  # 0 = fresh
        if hb.restore_step != expect:
            return (f"gang divergence: worker restored step "
                    f"{hb.restore_step}, gang ceiling is {self._ceiling}")
        return None

    def _classify_exit(self, w: _Worker, rc: int,
                       hb: Heartbeat | None) -> tuple[str, str] | None:
        """Map a worker exit to (cause, detail), or None for a clean
        'done' completion."""
        if rc == 0:
            if hb is not None and hb.phase == "preempted":
                return (PREEMPTION,
                        f"worker exited 0 after a preemption save "
                        f"(step {hb.step})")
            if hb is not None and hb.phase not in ("done",):
                logger.warning(
                    "fleet: worker %d exited 0 in phase %r; counting as "
                    "done", w.index, hb.phase)
            return None
        if rc == EXIT_PREEMPTED:
            return (PREEMPTION, "worker exited via coordinated "
                                "preemption save")
        if rc == EXIT_FAILED:
            cause = hb.cause if hb is not None and hb.cause else None
            if cause not in _KNOWN_CAUSES:
                cause = FATAL
            return (cause, f"worker's in-process supervision exhausted "
                           f"[{cause}]")
        return (classify_failure(WorkerDead(f"exit code {rc}")),
                f"worker exited with code {rc}")

    # -- gang stop / restart ----------------------------------------------

    def _alive(self) -> list[_Worker]:
        return [w for w in self._workers if w.handle.poll() is None]

    def _gang_stop(self, cause: str) -> None:
        """SIGTERM the survivors (coordinated preemption save), SIGKILL
        whatever outlives the grace period."""
        survivors = self._alive()
        for w in survivors:
            logger.warning("fleet: SIGTERM worker %d (gang stop, cause=%s)",
                           w.index, cause)
            w.handle.terminate()
        deadline = self.clock() + self.cfg.term_grace_s
        while self._alive() and self.clock() < deadline:
            self._wait(min(self.cfg.poll_s, self.cfg.term_grace_s / 4))
        killed = 0
        for w in self._alive():
            logger.error("fleet: SIGKILL worker %d (outlived the %.1fs "
                         "gang-stop grace)", w.index, self.cfg.term_grace_s)
            w.handle.kill()
            killed += 1
        self._reap_all()
        self.flightrec.emit("fleet_gang_stop", cause=cause,
                            survivors=len(survivors), killed=killed)

    def _gang_restart(self, cause: str) -> tuple[int, str]:
        delay = self.cfg.backoff.backoff_s(self.restarts)
        self.restarts += 1
        self.registry.counter(
            FLEET_RESTARTS_TOTAL, "fleet gang restarts by failure class",
            cause=cause,
        ).inc()
        logger.warning("fleet: gang restart %d/%d (cause=%s) after %.2fs "
                       "backoff", self.restarts, self.cfg.max_restarts,
                       cause, delay)
        t0 = self.clock()
        self._wait(delay)
        slept = self.clock() - t0
        if slept > 0:
            # ELAPSED, not nominal: injected no-op sleeps waste nothing
            goodput.note_wasted(goodput.WASTE_RESTART_RECOVERY, slept,
                                registry=self.registry)
        self._ceiling = None
        if self.ckpt_dirs is not None:
            common = newest_common_valid_step(self.ckpt_dirs)
            if common is not None:
                write_restore_step(self.workdir, common)
                self._ceiling = common
                for d in self.ckpt_dirs:
                    evict_steps_above(d, common)
                logger.warning("fleet: restore ceiling for incarnation %d "
                               "is step %d", self.incarnation + 1, common)
        self.incarnation += 1
        write_incarnation(self.workdir, self.incarnation)
        self._launch_all()
        return (self.restarts, cause)

    def _preempted_teardown(self) -> None:
        """The fleet process itself was SIGTERMed: stop the gang (the
        workers take their coordinated preemption saves) and surface the
        signal to run()'s finally for re-delivery."""
        logger.warning("fleet: SIGTERM received; stopping the gang")
        self._gang_stop(PREEMPTION)
        raise FleetExhausted(
            PREEMPTION, self.restarts,
            "fleet process preempted; gang stopped with coordinated saves")

    def _reap_all(self) -> None:
        """Wait on every worker handle. Called only after the gang is
        terminated/killed, so the waits are short — and they must cover
        the just-SIGKILLed children whose ``poll()`` still reads None
        (the kernel hasn't finished tearing them down): skipping those
        leaks one zombie per escalated gang stop."""
        for w in self._workers:
            try:
                w.handle.wait(timeout=5.0)
            except Exception as e:  # reap is best-effort bookkeeping
                logger.warning("fleet: reaping worker %d failed: %r",
                               w.index, e)

    def _dump_postmortem(self, reason: str) -> None:
        flightrec_lib.dump_postmortem(self.flightrec, self.postmortem_dir,
                                      reason=reason)
