"""Fleet supervision — multi-process gang orchestration over heartbeats.

The reference's control plane was `tf.train.ClusterSpec` +
`MonitoredTrainingSession`: a chief that watched worker liveness and
restarted the session when one died. Our elasticity model is
checkpoint-restart (train/checkpoint.py: "TPU slices fail whole"), and
PRs 3-6 built every *in-process* piece of it — fault injection, retry
budgets, the in-process Supervisor, fallback restore, the flight
recorder. This module is the missing *cluster-level* layer: a
collective-free control plane that supervises a fleet of worker
PROCESSES, so it runs unchanged on the CPU test rig where jaxlib has no
multiprocess collectives: the control plane uses no collectives and no
device code — liveness, classification, and the common-checkpoint
computation are files, signals, and manifest reads.

Protocol (docs/resilience.md "Fleet"):

- **Heartbeats.** Each worker owns one heartbeat file under the fleet
  dir and rewrites it atomically (tmp + rename — a reader never sees a
  torn record) with a monotonically increasing ``seq`` plus
  ``{pid, step, attempt, incarnation, phase}``. Beats come from the
  production seams that prove real progress: the in-process
  ``Supervisor`` beats at each attempt boundary and
  ``train.callbacks.HeartbeatCallback`` beats from the step seam — a
  hung loop therefore *stops beating*, which is the signal. An optional
  pulse thread (``pulse_interval_s``) keeps ``seq`` ticking from a
  daemon thread so the fleet can tell a live-but-stalled process
  (seq advances, step frozen → ``stalled``) from a dead one (seq frozen
  → ``dead``).
- **Incarnations.** The fleet bumps an on-disk incarnation counter
  before every (re)launch; workers read it at startup and stamp every
  beat with it. A heartbeat from an older incarnation — freshly written
  by a straggler the gang-stop hasn't reaped yet — is treated as
  *absent*, never as liveness.
- **Gang restart.** Any classified failure (missed heartbeats,
  exit-code death, stall) tears the whole gang down: SIGTERM the
  survivors (exercising the coordinated preemption-save path), SIGKILL
  whatever outlives the grace period, compute the newest checkpoint
  step EVERY worker can restore (``newest_common_valid_step``, manifest
  verified), write it as the restore ceiling, bump the incarnation, and
  relaunch everyone — under a restart budget with the same seeded
  escalating backoff the in-process Supervisor uses. Exhaustion raises
  ``FleetExhausted`` and dumps a flight-recorder postmortem.

Failure classification reuses ``classify_failure``: observed failures
are materialized as the exceptions they represent (``WorkerDead`` for
liveness/exit deaths → ``transient``, ``StalledError`` for frozen
steps → ``stalled``) so the fleet and the in-process Supervisor can
never disagree about taxonomy.

Clocks and sleeps are injectable (``FaultClock`` drop-in) so every
liveness edge case — stale-but-ticking vs absent vs stale-incarnation —
is deterministically testable without real processes.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import signal as signal_lib
import threading
import time
from typing import Any, Callable, Sequence

from ..obs import fleetview as fleetview_lib
from ..obs import flightrec as flightrec_lib
from ..obs import goodput
from ..obs.flightrec import FlightRecorder
from ..obs.registry import Registry, default_registry
from . import liveness
from .liveness import (
    DEAD,
    HOLD_PHASES as _HOLD_PHASES,
    INCARNATION_FILE as _INCARNATION_FILE,
    LIVE,
    STALLED_HB,
    TERMINAL_PHASES as _TERMINAL_PHASES,
    WAITING,
    Heartbeat,
    HeartbeatMonitor,
    HeartbeatWriter,
    atomic_write as _atomic_write,
    heartbeat_path,
    read_heartbeat,
    read_incarnation,
    write_incarnation,
)
from .retry import RetryPolicy
from .supervisor import (
    FATAL, POISONED, PREEMPTION, STALLED, TRANSIENT, classify_failure,
)

logger = logging.getLogger(__name__)

#: worker exit-code protocol (tests/chaos_worker.py --fleet speaks it):
#: 0 = reached the target step; EXIT_PREEMPTED = clean coordinated
#: preemption save (gang-stop SIGTERM, or an injected one); EXIT_FAILED
#: = the worker's in-process supervision exhausted — the classified
#: cause rides in the final heartbeat.
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: try again (from a checkpoint)
EXIT_FAILED = 76

#: metric names (documented in docs/observability.md)
FLEET_RESTARTS_TOTAL = "fleet_restarts_total"
FLEET_WORKER_DEATHS_TOTAL = "fleet_worker_deaths_total"

#: every failure class the fleet may carry / restart on
_KNOWN_CAUSES = frozenset({TRANSIENT, POISONED, FATAL, PREEMPTION, STALLED})

#: heartbeat phases a worker moves through; "train"/"done"/"preempted"/
#: "failed" mean the attempt got past build+restore (the gate
#: fleet_restart waits on before declaring the new gang live)
_PAST_BUILD_PHASES = ("train", "done", "preempted", "failed")

#: metric names for the elastic path (documented in docs/observability.md)
FLEET_SIZE = "fleet_size"
FLEET_RESIZES_TOTAL = "fleet_resizes_total"

#: failure classes a death may carry and still be absorbed elastically:
#: the dead worker's state is on disk and the survivors' is healthy.
#: POISONED/FATAL stay gang failures — they indict the trajectory, not
#: one process.
_ELASTIC_CAUSES = frozenset({TRANSIENT, STALLED, PREEMPTION})

_RESTORE_FILE = "RESTORE_STEP"
_SHARD_PLAN_FILE = "SHARD_PLAN"

#: ShardPlan phases
PLAN_STEADY = "steady"
PLAN_HOLD = "hold"


class WorkerDead(OSError):
    """A fleet worker died without a classified exit: SIGKILL'd,
    crashed, or stopped heartbeating. Subclasses OSError so
    ``classify_failure`` maps it to ``transient`` — the process is
    gone, the state on disk is fine, restart and resume."""


class FleetExhausted(RuntimeError):
    """The fleet restart budget ran out (or the failure class was not
    restartable). ``cause`` is the classified failure class of the last
    gang failure."""

    def __init__(self, cause: str, restarts: int, detail: str = ""):
        super().__init__(
            f"fleet restart budget exhausted after {restarts} gang "
            f"restart(s); last failure class {cause!r}"
            + (f": {detail}" if detail else "")
        )
        self.cause = cause
        self.restarts = restarts


# ---------------------------------------------------------------------------
# On-disk control files (restore ceiling; the incarnation file, the
# atomic-write idiom, and the heartbeat layout live in .liveness — the
# ONE implementation shared with the serve fleet)
# ---------------------------------------------------------------------------


def read_restore_step(fleet_dir: str) -> int | None:
    """Restore ceiling for the current incarnation: workers restore the
    newest valid step <= this (``init_or_restore(step=...)``), so the
    whole gang resumes from the same — latest COMMON — checkpoint.
    None = no ceiling (first incarnation; restore your newest)."""
    path = os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), _RESTORE_FILE)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        logger.warning("unreadable restore-step file %s (%s); no ceiling",
                       path, e)
        return None


def write_restore_step(fleet_dir: str, step: int) -> None:
    d = os.path.abspath(os.path.expanduser(fleet_dir))
    os.makedirs(d, exist_ok=True)
    _atomic_write(os.path.join(d, _RESTORE_FILE), f"{int(step)}\n")


def clear_restore_step(fleet_dir: str) -> None:
    """Remove the restore ceiling. Every fresh fleet run starts here: a
    ceiling left behind by a PREVIOUS run in the same workdir would
    silently roll a longer continuation run back to an old step."""
    path = os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), _RESTORE_FILE)
    if os.path.exists(path):
        os.remove(path)


# ---------------------------------------------------------------------------
# Shard plan (elastic resize control file)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """One on-disk data-sharding epoch of the elastic fleet
    (docs/resilience.md "Elastic fleet"). ``ranks`` maps worker index →
    shard rank over ``world``; the sharding applies to global batch
    indices > ``barrier_step``. ``phase == PLAN_HOLD`` is the resize
    handshake: every worker listed in ``hold`` pauses at its next step
    boundary (heartbeat phase ``barrier``) until a newer PLAN_STEADY
    release names the barrier and the post-resize sharding. Versions
    are strictly increasing; workers apply each version exactly once."""

    version: int
    phase: str
    world: int
    ranks: dict[int, int]
    barrier_step: int
    incarnation: int = 0
    hold: tuple[int, ...] = ()
    #: the NOMINAL fleet size (what the run was configured for) —
    #: consumers rescaling N-sized resources to ``world`` (the runner's
    #: mesh respec) need the denominator; 0 = unknown (older plans)
    fleet_size: int = 0

    def __post_init__(self):
        if self.phase not in (PLAN_STEADY, PLAN_HOLD):
            raise ValueError(f"unknown plan phase {self.phase!r}")
        if self.world < 1 or self.version < 1:
            raise ValueError("plan world and version must be >= 1")
        if sorted(self.ranks.values()) != list(range(len(self.ranks))):
            raise ValueError(
                f"plan ranks must be a bijection onto 0..{len(self.ranks)-1},"
                f" got {self.ranks}")
        if self.world != len(self.ranks):
            # an unserved rank would silently drop a slice of every
            # batch — the union-over-ranks invariant is the whole point
            raise ValueError(
                f"plan world={self.world} != {len(self.ranks)} ranks: "
                f"every rank of the world must be served by a worker")


def _shard_plan_path(fleet_dir: str) -> str:
    return os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), _SHARD_PLAN_FILE)


def read_shard_plan(fleet_dir: str) -> ShardPlan | None:
    """Current shard plan (None when no elastic fleet has written one,
    or the file is unreadable — a worker that cannot read the plan keeps
    its last applied sharding, which is the conservative choice)."""
    try:
        with open(_shard_plan_path(fleet_dir)) as f:
            d = json.load(f)
        return ShardPlan(
            version=int(d["version"]), phase=str(d["phase"]),
            world=int(d["world"]),
            ranks={int(k): int(v) for k, v in d["ranks"].items()},
            barrier_step=int(d["barrier_step"]),
            incarnation=int(d.get("incarnation", 0)),
            hold=tuple(int(i) for i in d.get("hold", ())),
            fleet_size=int(d.get("fleet_size", 0)),
        )
    except FileNotFoundError:
        return None
    except (OSError, ValueError, KeyError, TypeError) as e:
        logger.warning("unreadable shard plan in %s (%s); treating as absent",
                       fleet_dir, e)
        return None


def write_shard_plan(fleet_dir: str, plan: ShardPlan) -> None:
    d = os.path.abspath(os.path.expanduser(fleet_dir))
    os.makedirs(d, exist_ok=True)
    _atomic_write(os.path.join(d, _SHARD_PLAN_FILE), json.dumps({
        "version": plan.version, "phase": plan.phase, "world": plan.world,
        "ranks": {str(k): v for k, v in plan.ranks.items()},
        "barrier_step": plan.barrier_step, "incarnation": plan.incarnation,
        "hold": list(plan.hold), "fleet_size": plan.fleet_size,
    }))


def clear_shard_plan(fleet_dir: str) -> None:
    """Remove the shard plan — every fresh fleet run starts here, like
    ``clear_restore_step``: a previous run's plan must not assign this
    run's workers stale shards."""
    path = _shard_plan_path(fleet_dir)
    if os.path.exists(path):
        os.remove(path)


# ---------------------------------------------------------------------------
# Newest common valid checkpoint (fleet side, jax-free)
# ---------------------------------------------------------------------------


def valid_steps(ckpt_dir: str) -> list[int]:
    """Every step under ``ckpt_dir`` whose MANIFEST.dtf verifies
    (CRC-trailered read + per-shard size check — the same invariants
    ``Checkpointer.verify_manifest`` enforces, reimplemented over
    runtime/io so the control plane never stands up a Checkpointer or
    an orbax manager).
    Steps without a manifest count as valid (pre-manifest checkpoints
    restore unchecked, by design). Ascending; bounded by the worker's
    retention (``max_to_keep``), so verifying all of them is cheap."""
    d = os.path.abspath(os.path.expanduser(ckpt_dir))
    if not os.path.isdir(d):
        return []
    steps = sorted(
        int(n) for n in os.listdir(d)
        if n.isdigit() and os.path.isdir(os.path.join(d, n)))
    return [s for s in steps if _step_dir_valid(os.path.join(d, str(s)), s)]


def newest_valid_step(ckpt_dir: str) -> int | None:
    """Newest restorable step under ``ckpt_dir`` (None when nothing
    is)."""
    steps = valid_steps(ckpt_dir)
    return steps[-1] if steps else None


def _step_dir_valid(step_dir: str, step: int) -> bool:
    manifest = os.path.join(step_dir, "MANIFEST.dtf")
    if not os.path.exists(manifest):
        return True  # pre-manifest checkpoint: allowed, unchecked
    from ..runtime import io as io_lib

    try:
        entries = json.loads(io_lib.read_payload(manifest))["files"]
        for entry in entries:
            p = os.path.join(step_dir, entry["path"])
            if not os.path.exists(p) or os.path.getsize(p) != entry["bytes"]:
                logger.warning(
                    "fleet: checkpoint step %d shard %s missing/resized; "
                    "step not restorable", step, entry["path"])
                return False
    except (OSError, ValueError, KeyError, TypeError) as e:
        logger.warning("fleet: checkpoint step %d manifest unreadable (%s); "
                       "step not restorable", step, e)
        return False
    return True


def evict_steps_above(ckpt_dir: str, ceiling: int) -> list[int]:
    """Move every step dir ABOVE ``ceiling`` to ``<dir>/.abandoned/`` —
    called at a gang restart, where the whole gang rolls back to
    ``ceiling``: anything newer is abandoned history. Left in place it
    would (a) shadow the re-trained state at the same step numbers
    (``Checkpointer.save`` skips steps already on disk, so a corrupt or
    stale above-ceiling step would stay the newest forever) and (b) be
    resurrected by a later restore — e.g. an in-process Supervisor
    restart inside the new incarnation restoring the PREVIOUS
    incarnation's newest step. Returns the evicted steps."""
    d = os.path.abspath(os.path.expanduser(ckpt_dir))
    if not os.path.isdir(d):
        return []
    base = os.path.join(d, ".abandoned")
    evicted: list[int] = []
    for name in sorted(os.listdir(d)):
        if not (name.isdigit() and os.path.isdir(os.path.join(d, name))):
            continue
        step = int(name)
        if step <= ceiling:
            continue
        os.makedirs(base, exist_ok=True)
        dst = os.path.join(base, name)
        k = 0
        while os.path.exists(dst):
            k += 1
            dst = os.path.join(base, f"{name}-{k}")
        os.rename(os.path.join(d, name), dst)
        evicted.append(step)
        logger.warning("fleet: abandoned above-ceiling checkpoint step %d "
                       "-> %s", step, dst)
    return evicted


def newest_common_valid_step(ckpt_dirs: Sequence[str]) -> int | None:
    """The newest step EVERY worker retains AND can verify — the gang
    restart point. The intersection matters, not min-of-newest: a
    worker whose retention already evicted the others' newest step must
    not be handed a ceiling it cannot restore (it would silently
    fresh-init at 0 while the rest of the gang resumes — the exact
    inconsistency the ceiling exists to prevent). An empty intersection
    pins the common step to 0: the whole gang fresh-starts, which with
    deterministic data is correct, just maximally conservative. None
    when no dirs given."""
    if not ckpt_dirs:
        return None
    common = set(valid_steps(ckpt_dirs[0]))
    for d in ckpt_dirs[1:]:
        common &= set(valid_steps(d))
    return max(common) if common else 0


# ---------------------------------------------------------------------------
# Peer-to-peer joiner catch-up (file control plane)
#
# A rejoining worker restores from its OWN newest valid step and replays
# the deterministic stream — correct, but the replay grows linearly with
# how far behind the joiner's retention left it. Catch-up shortcuts the
# replay: the joiner posts a request under <fleet_dir>/catchup/, a live
# survivor claims it (atomic rename — first claimer wins), exports a
# verified copy of its newest valid step, and publishes it as an offer
# (also by rename, so the joiner never sees a half-copied export). The
# joiner verifies the offer with the SAME manifest CRC + per-shard size
# discipline as the restore ceiling (``_step_dir_valid``), imports it
# atomically into its own checkpoint dir, and restores from it — every
# shard then passes through the CRC-trailered ``read_payload`` at
# restore time, so a corrupted transfer quarantines instead of loading.
#
# Incarnation-fenced end to end: requests carry the joiner's
# incarnation, survivors ignore requests from any other incarnation,
# and offers echo it back — a stale offer from a previous gang can
# never be imported. No survivor answering within ``budget_s`` is not
# an error: the joiner falls back to deterministic replay, which is the
# pre-catchup behavior. Trajectory identity is preserved either way:
# in the collective-free rig every worker steps the full global batch,
# so a survivor's step-S state IS the straight run's step-S state.
# ---------------------------------------------------------------------------

CATCHUP_DIRNAME = "catchup"

#: metric name (documented in docs/observability.md)
REJOIN_CATCHUP_SECONDS = "rejoin_catchup_seconds"


def _catchup_dir(fleet_dir: str) -> str:
    return os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), CATCHUP_DIRNAME)


def clear_catchup(fleet_dir: str) -> None:
    """Drop the whole catch-up exchange — every fresh fleet run starts
    here (like ``clear_shard_plan``): a previous incarnation's offers
    must never be importable by this run's joiners."""
    shutil.rmtree(_catchup_dir(fleet_dir), ignore_errors=True)


def clear_catchup_for(fleet_dir: str, worker: int) -> None:
    """Drop any stale request/claim/offer addressed to ``worker`` —
    called before launching its replacement, so the new joiner's
    exchange starts clean."""
    cdir = _catchup_dir(fleet_dir)
    for name in (f"req-{worker}.json", f"claim-{worker}.json"):
        try:
            os.remove(os.path.join(cdir, name))
        # reviewed: sound drop — the file usually does not exist, and
        # absence IS the clean state this helper establishes
        except OSError:  # dtflint: disable=exception-hygiene
            pass
    shutil.rmtree(os.path.join(cdir, f"offer-{worker}"), ignore_errors=True)
    shutil.rmtree(os.path.join(cdir, f".export-{worker}"), ignore_errors=True)


def _read_offer(offer_dir: str) -> dict | None:
    try:
        with open(os.path.join(offer_dir, "OFFER.json")) as f:
            d = json.load(f)
        return {"step": int(d["step"]), "incarnation": int(d["incarnation"]),
                "from_worker": int(d["from_worker"])}
    except FileNotFoundError:
        return None
    except (OSError, ValueError, KeyError, TypeError) as e:
        logger.warning("catchup: unreadable offer in %s (%s); ignoring",
                       offer_dir, e)
        return None


def request_catchup(
    fleet_dir: str, worker: int, incarnation: int, ckpt_dir: str, *,
    budget_s: float = 15.0, poll_s: float = 0.2,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    flightrec: FlightRecorder | None = None,
    registry: Registry | None = None,
) -> int | None:
    """Joiner side: ask a live survivor for a newer valid step than this
    worker's own retention holds, import it into ``ckpt_dir``, and
    return the imported step — or None after ``budget_s`` with no usable
    offer (the caller restores its own newest step and replays, exactly
    as before catch-up existed)."""
    rec = flightrec if flightrec is not None else flightrec_lib.default_recorder()
    reg = registry if registry is not None else default_registry()
    cdir = _catchup_dir(fleet_dir)
    os.makedirs(cdir, exist_ok=True)
    d = os.path.abspath(os.path.expanduser(ckpt_dir))
    have = newest_valid_step(d)
    # a previous incarnation of this slot may have left a half-finished
    # exchange behind; start clean so its offer can't race ours
    clear_catchup_for(fleet_dir, worker)
    offer_dir = os.path.join(cdir, f"offer-{worker}")
    _atomic_write(os.path.join(cdir, f"req-{worker}.json"), json.dumps({
        "worker": int(worker), "incarnation": int(incarnation),
        "have_step": have}))
    t0 = clock()
    deadline = t0 + budget_s
    while True:
        meta = _read_offer(offer_dir)
        if meta is not None:
            if meta["incarnation"] != int(incarnation):
                # previous gang's leftovers — discard, keep waiting
                shutil.rmtree(offer_dir, ignore_errors=True)
            else:
                step = meta["step"]
                src = os.path.join(offer_dir, str(step))
                if ((have is None or step > have) and os.path.isdir(src)
                        and _step_dir_valid(src, step)):
                    dst = os.path.join(d, str(step))
                    tmp = os.path.join(d, f".catchup-{step}")
                    shutil.rmtree(tmp, ignore_errors=True)
                    shutil.copytree(src, tmp)
                    if os.path.isdir(dst):
                        # a torn/invalid local dir at this step (it can't
                        # be valid: step > our newest valid) — replace it
                        shutil.rmtree(dst)
                    os.rename(tmp, dst)
                    seconds = max(clock() - t0, 0.0)
                    rec.emit("catchup_restore", step=step,
                             peer=meta["from_worker"],
                             seconds=round(seconds, 6))
                    reg.histogram(
                        REJOIN_CATCHUP_SECONDS,
                        "joiner catch-up wall seconds, request to import",
                    ).observe(seconds)
                    logger.warning(
                        "catchup: worker %d imported step %d from peer %d "
                        "in %.2fs", worker, step, meta["from_worker"],
                        seconds)
                    shutil.rmtree(offer_dir, ignore_errors=True)
                    clear_catchup_for(fleet_dir, worker)
                    return step
                # the survivor's newest is no better than ours, or the
                # export failed verification — replay is the answer
                logger.warning(
                    "catchup: worker %d discarding unusable offer of step "
                    "%d (have %s)", worker, step, have)
                shutil.rmtree(offer_dir, ignore_errors=True)
                break
        if clock() >= deadline:
            break
        sleep(poll_s)
    # fallback: withdraw the request so no survivor exports into the void
    clear_catchup_for(fleet_dir, worker)
    rec.emit("catchup_fallback", worker=worker, budget_s=budget_s)
    logger.warning("catchup: worker %d got no usable offer within %.1fs; "
                   "falling back to deterministic replay", worker, budget_s)
    return None


# ---------------------------------------------------------------------------
# Heartbeats: writer (worker side) and monitor (fleet side) — factored
# into .liveness (shared with serve/fleet.py) and re-exported above:
# Heartbeat, read_heartbeat, HeartbeatWriter, HeartbeatMonitor, the
# WAITING/LIVE/DEAD/STALLED_HB statuses, and the terminal/hold phase
# tuples. The protocol semantics are documented there.
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Elastic worker client (worker side)
# ---------------------------------------------------------------------------


class ElasticWorker:
    """Worker-side elastic resize client — polled from the step seam
    (train/callbacks.ElasticCallback), jax-free like the rest of the
    control plane.

    ``poll(step)`` reads the SHARD_PLAN control file and applies any
    version newer than the last one applied:

    - ``PLAN_STEADY``: schedule ``on_reshard(rank, world, barrier_step)``
      (rank None when this worker is not a member — a replacement still
      catching up). The reshard binds to the barrier INDEX, so applying
      it early is exact.
    - ``PLAN_HOLD`` naming this worker: pause HERE — beat heartbeat
      phase ``barrier`` (with the hold version acknowledged via
      ``note_plan``) and block, beating for liveness, until the fleet
      releases with a newer PLAN_STEADY. The pause is what makes the
      barrier step the fleet picks an upper bound for every member.

    A hold abandoned past ``hold_timeout_s`` (fleet died mid-resize)
    raises OSError — classified transient, so the in-process Supervisor
    restarts the attempt instead of hanging forever.

    ``on_reshard(rank | None, world, at_index)`` rewires the data
    stream — typically ``ElasticStream.reshard`` (data/pipeline.py)
    through a WorkerShard. Plain ints cross the seam so this module
    never imports the (jax-importing) data package.

    With ``ckpt_dir`` given, every poll (and every spin of a hold
    barrier — survivors are usually HELD while a joiner catches up)
    also serves peer catch-up requests: this worker claims a pending
    request and exports its newest valid step as an offer (see the
    catch-up protocol above). ``ckpt_dir=None`` disables serving.
    """

    def __init__(self, fleet_dir: str, worker: int, writer: HeartbeatWriter,
                 on_reshard: Callable[[int | None, int, int], None]
                 | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 poll_s: float = 0.05, hold_timeout_s: float = 120.0,
                 flightrec: FlightRecorder | None = None,
                 ckpt_dir: str | None = None):
        if poll_s <= 0 or hold_timeout_s <= 0:
            raise ValueError("poll_s and hold_timeout_s must be positive")
        self.fleet_dir = fleet_dir
        self.worker = int(worker)
        self.writer = writer
        self.on_reshard = on_reshard
        self.clock = clock
        self.sleep = sleep
        self.poll_s = poll_s
        self.hold_timeout_s = hold_timeout_s
        #: worker-side half of the resize handshake in the causal record
        #: (elastic_hold / elastic_release — the clock anchors the merged
        #: cross-worker timeline aligns on, obs/fleetview.py)
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        #: newest plan version applied (or held at)
        self.applied_version = 0
        #: (rank | None, world) from the newest applied steady plan
        self.assignment: tuple[int | None, int] | None = None
        #: checkpoint dir served to catching-up peers (None = don't)
        self.ckpt_dir = ckpt_dir

    def poll(self, step: int | None = None) -> None:
        """One step-seam poll; blocks only while the fleet holds this
        worker at a resize barrier."""
        self.serve_catchup()
        plan = read_shard_plan(self.fleet_dir)
        if plan is None or plan.version <= self.applied_version:
            return
        if plan.phase == PLAN_HOLD:
            if self.worker in plan.hold:
                self._hold(step, plan)
            # a hold not naming us (we are the joiner the gang is about
            # to absorb) is applied by the release that follows it
            return
        self._apply(plan)
        self.writer.beat(step=step)

    def _hold(self, step: int | None, plan: ShardPlan) -> None:
        self.applied_version = plan.version
        self.writer.note_plan(plan.version, plan.world)
        prev_phase = self.writer.phase
        if prev_phase in ("save", "barrier"):
            # never re-instate a transient phase after the release: a
            # 'save' whose async commit landed during the hold (its
            # restore thread refuses to clobber our barrier) would
            # otherwise stick forever and force every later death down
            # the mid-checkpoint gang-stop path
            prev_phase = "train"
        # emitted AFTER reading the hold plan (the fleet wrote it first)
        # and BEFORE the barrier beat makes the ack observable: the
        # fleet's release therefore strictly follows this event — both
        # sides of the merged timeline's hold anchor hold by
        # construction, never by racing the fleet's heartbeat poll
        self.flightrec.emit("elastic_hold", step=step, version=plan.version)
        self.writer.beat(step=step, phase="barrier")
        logger.warning("elastic: worker %d holding at step %s for resize "
                       "(plan v%d)", self.worker, step, plan.version)
        deadline = self.clock() + self.hold_timeout_s
        while True:
            self.sleep(self.poll_s)
            nxt = read_shard_plan(self.fleet_dir)
            if (nxt is not None and nxt.version > plan.version
                    and nxt.phase == PLAN_STEADY):
                self._apply(nxt)
                self.writer.beat(phase=prev_phase)
                return
            if self.clock() > deadline:
                # surface as transient: the supervisor restarts the
                # attempt, which re-reads whatever plan exists by then
                raise OSError(
                    f"elastic hold abandoned: no release within "
                    f"{self.hold_timeout_s}s of plan v{plan.version}")
            self.writer.beat()  # liveness while paused
            # serve catch-up from inside the barrier too: on a rejoin
            # hold, the SURVIVORS are exactly the workers parked here
            # while the joiner asks for a step
            self.serve_catchup()

    def serve_catchup(self) -> None:
        """Answer at most one pending peer catch-up request (see the
        protocol comment above ``request_catchup``). No-op without a
        ``ckpt_dir`` or when no request is pending."""
        if self.ckpt_dir is None:
            return
        cdir = _catchup_dir(self.fleet_dir)
        try:
            names = os.listdir(cdir)
        except FileNotFoundError:
            return
        for name in sorted(names):
            if name.startswith("req-") and name.endswith(".json"):
                if self._serve_one(os.path.join(cdir, name)):
                    return

    def _serve_one(self, req_path: str) -> bool:
        try:
            with open(req_path) as f:
                req = json.load(f)
            peer = int(req["worker"])
            inc = int(req["incarnation"])
            have = req.get("have_step")
        except (OSError, ValueError, KeyError, TypeError):
            return False  # torn/claimed under us — someone else's problem
        if peer == self.worker:
            return False
        my_inc = getattr(self.writer, "incarnation", None)
        if my_inc is not None and inc != int(my_inc):
            # a previous gang's request: drop it so it can never trigger
            # an export nobody of this incarnation will import
            try:
                os.remove(req_path)
            # reviewed: sound drop — a concurrent survivor already
            # removed or claimed the stale request; either way it is gone
            except OSError:  # dtflint: disable=exception-hygiene
                pass
            return False
        step = newest_valid_step(self.ckpt_dir)
        if step is None or (have is not None and step <= int(have)):
            # nothing better than the joiner already holds: leave the
            # request for a peer with a newer step (or the budget)
            return False
        cdir = os.path.dirname(req_path)
        claim = os.path.join(cdir, f"claim-{peer}.json")
        try:
            os.rename(req_path, claim)  # first claimer wins
        except OSError:
            return False
        tmp = os.path.join(cdir, f".export-{peer}")
        offer = os.path.join(cdir, f"offer-{peer}")
        src = os.path.join(
            os.path.abspath(os.path.expanduser(self.ckpt_dir)), str(step))
        try:
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.copytree(src, os.path.join(tmp, str(step)))
            # re-verify the COPY: retention racing the export could have
            # truncated it mid-copytree
            if not _step_dir_valid(os.path.join(tmp, str(step)), step):
                raise OSError(f"export of step {step} failed verification")
            _atomic_write(os.path.join(tmp, "OFFER.json"), json.dumps({
                "step": step, "incarnation": inc,
                "from_worker": self.worker}))
            shutil.rmtree(offer, ignore_errors=True)
            os.rename(tmp, offer)  # publish: rename makes it whole-or-absent
        except OSError as e:
            logger.warning("catchup: worker %d failed exporting step %d for "
                           "peer %d (%s)", self.worker, step, peer, e)
            shutil.rmtree(tmp, ignore_errors=True)
            try:
                os.rename(claim, req_path)  # another survivor may succeed
            # reviewed: sound drop — the joiner withdrew its request
            # (clear_catchup_for) or gave up while we exported; the
            # export failure itself was logged above
            except OSError:  # dtflint: disable=exception-hygiene
                pass
            return False
        self.flightrec.emit("catchup_offer", step=step, peer=peer,
                            worker=self.worker)
        logger.warning("catchup: worker %d exported step %d for joiner %d",
                       self.worker, step, peer)
        return True

    def _apply(self, plan: ShardPlan) -> None:
        self.applied_version = plan.version
        rank = plan.ranks.get(self.worker)
        self.assignment = (rank, plan.world)
        self.writer.note_plan(plan.version, plan.world)
        self.flightrec.emit("elastic_release", version=plan.version,
                            world=plan.world, barrier=plan.barrier_step,
                            rank=rank)
        if self.on_reshard is not None:
            self.on_reshard(rank, plan.world, plan.barrier_step)
        logger.info("elastic: worker %d applied plan v%d (rank %s of %d, "
                    "from batch %d)", self.worker, plan.version, rank,
                    plan.world, plan.barrier_step + 1)


# ---------------------------------------------------------------------------
# Fleet supervisor
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    #: gang restarts allowed (launches = max_restarts + 1)
    max_restarts: int = 3
    #: failure classes that earn a gang restart; others raise immediately
    restart_on: tuple[str, ...] = (TRANSIENT, POISONED, PREEMPTION, STALLED)
    #: escalating backoff between gang restarts (seeded jitter — the
    #: same schedule the in-process Supervisor escalates on)
    backoff: RetryPolicy = RetryPolicy(
        base_s=0.2, multiplier=2.0, max_backoff_s=60.0)
    #: liveness poll cadence
    poll_s: float = 0.25
    #: no heartbeat within this budget after the first one → dead.
    #: SIZE ABOVE the longest legitimate silent window between step-seam
    #: beats (ceiling restore + first-step compile) — or give workers a
    #: HeartbeatWriter pulse thread and let stall detection carry hangs
    heartbeat_timeout_s: float = 30.0
    #: heartbeats ticking but step frozen this long → stalled
    stall_timeout_s: float = 120.0
    #: budget for a launched worker's FIRST beat (interpreter + imports)
    launch_grace_s: float = 120.0
    #: SIGTERM → SIGKILL grace during a gang stop (must cover one
    #: coordinated preemption save)
    term_grace_s: float = 10.0
    #: elastic resize (docs/resilience.md "Elastic fleet"): a worker
    #: death SHRINKS the gang to the survivors at a barrier step instead
    #: of gang-stopping everyone, and a relaunched replacement REJOINS
    #: at the next barrier. Gang-stop remains the fallback (below
    #: min_workers, death mid-checkpoint, resize already in flight,
    #: poisoned/fatal causes).
    elastic: bool = False
    #: survivor floor: a death that would leave fewer members than this
    #: falls back to the gang-stop → common-checkpoint restart path
    min_workers: int = 1
    #: budget for a relaunched replacement's FIRST heartbeat (its
    #: launch grace); after it proves life past build+restore it rejoins
    #: at the next barrier
    rejoin_grace_s: float = 120.0
    #: budget for every member to reach (and be released from) a resize
    #: barrier; an overrun falls back to the gang-stop path
    hold_timeout_s: float = 60.0
    #: fleet-observatory cadence (obs/fleetview.py): every this many
    #: seconds the supervisor folds the workers' telemetry snapshots
    #: into the merged fleet view (fleet_goodput_fraction, per-worker
    #: staleness gauges, fleetsnap_merge timeline anchors). None
    #: disables aggregation (workers may still export).
    snapshot_poll_s: float | None = None

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        unknown = set(self.restart_on) - (_KNOWN_CAUSES - {FATAL})
        if unknown:
            raise ValueError(f"unknown restart_on classes: {sorted(unknown)}")
        if self.poll_s <= 0 or self.term_grace_s <= 0:
            raise ValueError("poll_s and term_grace_s must be positive")
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1 (a gang cannot shrink to "
                f"nothing), got {self.min_workers}")
        if self.rejoin_grace_s <= 0:
            raise ValueError(
                f"rejoin_grace_s must be > 0 (a replacement needs a "
                f"liveness budget covering spawn + imports + restore), "
                f"got {self.rejoin_grace_s}")
        if self.hold_timeout_s <= 0:
            raise ValueError(
                f"hold_timeout_s must be > 0 (members must be released "
                f"from a barrier or the gang falls back), got "
                f"{self.hold_timeout_s}")
        if self.snapshot_poll_s is not None and self.snapshot_poll_s <= 0:
            raise ValueError(
                f"snapshot_poll_s must be > 0 when set (None disables "
                f"aggregation), got {self.snapshot_poll_s}")


@dataclasses.dataclass
class _Worker:
    index: int
    handle: Any                      # Popen-shaped: poll/terminate/kill/wait
    monitor: HeartbeatMonitor
    done: bool = False               # exited 0 this incarnation
    ready: bool = False              # heartbeat got past build+restore
    exit_code: int | None = None
    #: False while this slot is a catching-up replacement (launched by
    #: an elastic shrink, not yet absorbed by a rejoin barrier)
    member: bool = True


class FleetSupervisor:
    """Launch, watch, and gang-restart a fleet of worker processes.

    ``launch(worker_index, incarnation)`` must start worker
    ``worker_index`` and return a process handle with the
    ``subprocess.Popen`` control surface (``poll`` / ``terminate`` /
    ``kill`` / ``wait`` / ``pid``) — tests drive the whole state machine
    with fakes. Each worker heartbeats to
    ``heartbeat_path(workdir, index)``; ``ckpt_dirs`` (one per worker,
    optional) enables the common-checkpoint ceiling at restart.

    ``clock`` and ``sleep`` are injectable (FaultClock / scripted sleeps
    make liveness deterministic); with the default sleep the poll wait
    is an ``Event.wait`` that ``interrupt()`` — or a SIGTERM aimed at
    the fleet process itself — wakes immediately, so a preemption never
    waits out a backoff interval.
    """

    def __init__(
        self,
        launch: Callable[[int, int], Any],
        num_workers: int,
        workdir: str,
        cfg: FleetConfig = FleetConfig(),
        ckpt_dirs: Sequence[str] | None = None,
        registry: Registry | None = None,
        flightrec: FlightRecorder | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] | None = None,
        postmortem_dir: str | None = None,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if ckpt_dirs is not None and len(ckpt_dirs) != num_workers:
            raise ValueError("ckpt_dirs must have one entry per worker")
        if cfg.elastic and num_workers == 1:
            raise ValueError(
                "elastic=True is incompatible with num_workers=1: a "
                "1-worker gang has no survivors to shrink to — use the "
                "gang-restart path (elastic=False), which restarts the "
                "single worker from its newest valid checkpoint")
        if cfg.elastic and cfg.min_workers > num_workers:
            raise ValueError(
                f"min_workers={cfg.min_workers} exceeds the fleet size "
                f"{num_workers}: every death would bypass the elastic "
                f"path — lower min_workers or grow the fleet")
        self.launch = launch
        self.num_workers = num_workers
        self.workdir = os.path.abspath(os.path.expanduser(workdir))
        self.cfg = cfg
        self.ckpt_dirs = list(ckpt_dirs) if ckpt_dirs is not None else None
        self.registry = registry if registry is not None else default_registry()
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.clock = clock
        self.sleep = sleep
        self.postmortem_dir = postmortem_dir or self.workdir
        self._wake = threading.Event()
        self._stop_signal: list[int] = []
        #: gang restarts performed by the last run() (test observability)
        self.restarts = 0
        #: elastic resizes completed (shrinks + rejoins) by the last run()
        self.resizes = 0
        self.incarnation = 0
        #: restore ceiling written for the CURRENT incarnation (None =
        #: no ceiling; every checked-in worker must have restored it)
        self._ceiling: int | None = None
        self._workers: list[_Worker] = []
        #: current shard plan (elastic mode only)
        self._plan: ShardPlan | None = None
        #: in-flight resize state machine (None = steady):
        #: {kind: shrink|rejoin, stage: hold|released, t0, worker,
        #:  hold: [indices], version: plan version of the current stage}
        self._resize: dict | None = None
        #: relaunches spent on replacements that died before rejoining
        self._joiner_relaunches = 0
        #: start of the current gang outage (gang stop → gang live) —
        #: the window booked as restart_recovery waste
        self._t_outage: float | None = None
        self._m_deaths = self.registry.counter(
            FLEET_WORKER_DEATHS_TOTAL,
            "fleet worker deaths detected (exit, missed heartbeat, stall)")
        self._m_size = self.registry.gauge(
            FLEET_SIZE, "current gang size (members sharing the data "
            "stream; drops on an elastic shrink, recovers on rejoin)")
        #: fleet observatory (obs/fleetview.py): merged per-worker
        #: telemetry view, rebuilt every cfg.snapshot_poll_s
        self.aggregator: fleetview_lib.FleetAggregator | None = None
        self._t_agg: float | None = None
        if cfg.snapshot_poll_s is not None:
            self.aggregator = fleetview_lib.FleetAggregator(
                self.workdir, range(num_workers), registry=self.registry,
                flightrec=self.flightrec, clock=self.clock)

    # -- interruptible waiting --------------------------------------------

    def interrupt(self) -> None:
        """Wake the in-progress (or next) poll/backoff wait immediately.
        One-shot: the wakeup is consumed by that wait, so later waits
        pace normally — a durable stop signal lives in ``_stop_signal``,
        not in the event."""
        self._wake.set()

    def _wait(self, delay: float) -> None:
        if self.sleep is not None:
            self.sleep(delay)
            return
        if self._wake.wait(delay):
            # consume the wakeup: a sticky event would turn every later
            # poll/grace loop into a hot spin
            self._wake.clear()

    def _sigterm(self, signum, frame) -> None:
        self._stop_signal.append(signum)
        self._wake.set()

    # -- lifecycle ---------------------------------------------------------

    def _launch_all(self) -> None:
        self._workers = []
        for i in range(self.num_workers):
            handle = self.launch(i, self.incarnation)
            self._workers.append(_Worker(
                index=i, handle=handle,
                monitor=HeartbeatMonitor(
                    heartbeat_path(self.workdir, i), self.incarnation,
                    clock=self.clock,
                    heartbeat_timeout_s=self.cfg.heartbeat_timeout_s,
                    stall_timeout_s=self.cfg.stall_timeout_s,
                    launch_grace_s=self.cfg.launch_grace_s,
                ),
            ))
            self.flightrec.emit(
                "fleet_launch", worker=i, incarnation=self.incarnation,
                pid=getattr(handle, "pid", None))
            logger.info("fleet: launched worker %d (incarnation %d, pid %s)",
                        i, self.incarnation, getattr(handle, "pid", None))
        self._m_size.set(self.num_workers)

    def run(self) -> dict:
        """Supervise until every worker reaches a clean ``done`` exit.

        Returns ``{"restarts": n, "incarnation": k, "resizes": m}``.
        Raises ``FleetExhausted`` when the restart budget runs out or
        the failure class is not restartable (postmortem dumped first).
        """
        os.makedirs(self.workdir, exist_ok=True)
        # new fleet run == new incarnation: stale heartbeats from any
        # previous fleet in this dir can never read as liveness — and no
        # inherited restore ceiling or shard plan: a previous run's
        # RESTORE_STEP would cap this run's restores at an old step, and
        # its SHARD_PLAN would hand this run's workers stale shards
        self.incarnation = read_incarnation(self.workdir) + 1
        write_incarnation(self.workdir, self.incarnation)
        clear_restore_step(self.workdir)
        clear_shard_plan(self.workdir)
        clear_catchup(self.workdir)
        self.restarts = 0
        self.resizes = 0
        self._ceiling = None
        self._resize = None
        self._plan = None
        self._joiner_relaunches = 0
        self._t_outage = None
        if self.cfg.elastic:
            self._write_plan(ShardPlan(
                version=1, phase=PLAN_STEADY, world=self.num_workers,
                ranks={i: i for i in range(self.num_workers)},
                barrier_step=0, incarnation=self.incarnation,
                fleet_size=self.num_workers))
        main = threading.current_thread() is threading.main_thread()
        prev_handler = (signal_lib.signal(signal_lib.SIGTERM, self._sigterm)
                        if main else None)
        self.flightrec.emit("fleet_start", workers=self.num_workers,
                            incarnation=self.incarnation)
        self._launch_all()
        #: (restart_index, cause) whose gang-live confirmation is pending
        pending_restart: tuple[int, str] | None = None
        relayed = False  # restore note relayed for this incarnation
        try:
            while True:
                self._wait(self.cfg.poll_s)
                if self._stop_signal:
                    self._preempted_teardown()
                self._maybe_aggregate()
                failure = self._poll_round(pending_restart, relayed)
                pending_restart, relayed, failed = failure
                if failed is not None:
                    worker, cause, detail = failed
                    self._m_deaths.inc()
                    self.flightrec.emit(
                        "fleet_worker_dead", worker=worker, cause=cause,
                        detail=detail[:200],
                        incarnation=self.incarnation,
                        pid=getattr(self._workers[worker].handle, "pid",
                                    None))
                    logger.error("fleet: worker %d dead [%s]: %s",
                                 worker, cause, detail)
                    if self._absorb_elastically(
                            worker, cause,
                            pending=pending_restart is not None):
                        continue
                    pending_restart = self._gang_path(cause, detail)
                    relayed = False
                    continue
                if pending_restart is None:
                    # tick BEFORE the done check: a replacement that
                    # finished between polls must still be absorbed (the
                    # timeline owes a fleet_rejoin before fleet_done)
                    stuck = self._elastic_tick()
                    if stuck is not None:
                        pending_restart = self._gang_path(*stuck)
                        relayed = False
                        continue
                if (self._resize is None
                        and all(w.done for w in self._workers)):
                    if self.aggregator is not None:
                        # fold the workers' final snapshots before the
                        # fleet_done marker: the merged view's last state
                        # covers the whole run, and every final
                        # fleetsnap_merge anchor precedes fleet_done
                        self.aggregator.poll()
                    self.flightrec.emit("fleet_done",
                                        incarnation=self.incarnation)
                    logger.info("fleet: all %d workers done (incarnation %d,"
                                " %d restart(s), %d resize(s))",
                                self.num_workers, self.incarnation,
                                self.restarts, self.resizes)
                    return {"restarts": self.restarts,
                            "incarnation": self.incarnation,
                            "resizes": self.resizes}
        finally:
            # no worker may outlive its supervisor: on every normal path
            # (done, exhausted, preempted teardown) the gang is already
            # down, so this only fires on an unexpected escape — e.g. a
            # launch() that raised mid-gang — where live workers would
            # otherwise keep training, unsupervised, in this workdir
            for w in self._workers:
                if w.handle.poll() is None:
                    logger.error(
                        "fleet: killing worker %d still alive at "
                        "supervisor exit", w.index)
                    w.handle.kill()
            self._reap_all()
            if main:
                signal_lib.signal(signal_lib.SIGTERM, prev_handler)
            if self._stop_signal:
                # processed a fleet-level SIGTERM: the gang is down; put
                # the original handler back and re-deliver so the outer
                # process sees the signal without the backoff delay
                os.kill(os.getpid(), self._stop_signal[0])

    # -- one poll round ----------------------------------------------------

    def _maybe_aggregate(self) -> None:
        """Fold worker telemetry snapshots on the cfg.snapshot_poll_s
        cadence (no-op when aggregation is disabled)."""
        if self.aggregator is None:
            return
        now = self.clock()
        if self._t_agg is None \
                or now - self._t_agg >= self.cfg.snapshot_poll_s:
            self._t_agg = now
            self.aggregator.poll()

    def _poll_round(
        self, pending_restart: tuple[int, str] | None, relayed: bool,
    ) -> tuple[tuple[int, str] | None, bool,
               tuple[int, str, str] | None]:
        """Poll every worker once. Returns the updated
        ``(pending_restart, relayed, failure)`` where ``failure`` is
        ``(worker, cause, detail)`` for the first failed worker."""
        failed: tuple[int, str, str] | None = None
        for w in self._workers:
            if w.done:
                continue
            rc = w.handle.poll()
            status = w.monitor.check()
            hb = w.monitor.heartbeat  # refreshed by check()
            # relay the gang's restore evidence BEFORE fleet_restart can
            # be emitted, so the postmortem chain reads causally:
            # gang_stop -> ckpt_restore{fallback} -> fleet_restart
            if (pending_restart is not None and not relayed
                    and hb is not None and hb.restore_step is not None):
                self.flightrec.emit(
                    "ckpt_restore", step=hb.restore_step,
                    fallback=bool(hb.restore_fallback), worker=w.index,
                    relayed=True, incarnation=self.incarnation)
                relayed = True
            if rc is not None:
                w.exit_code = rc
                div = (self._restore_divergence(hb)
                       if pending_restart is not None and not w.done
                       else None)
                cause_detail = self._classify_exit(w, rc, hb)
                if cause_detail is None:
                    if div is not None and failed is None:
                        failed = (w.index, TRANSIENT, div)
                    w.done = w.ready = True
                elif failed is None:
                    failed = (w.index, *cause_detail)
            else:
                if hb is not None and hb.phase in _PAST_BUILD_PHASES:
                    if pending_restart is not None and not w.ready:
                        div = self._restore_divergence(hb)
                        if div is not None and failed is None:
                            failed = (w.index, TRANSIENT, div)
                    w.ready = True
                if status == DEAD and failed is None:
                    failed = (w.index,
                              classify_failure(WorkerDead("missed heartbeats")),
                              f"no heartbeat within "
                              f"{w.monitor.heartbeat_timeout_s}s "
                              f"(pid {getattr(w.handle, 'pid', None)})")
                elif status == STALLED_HB and failed is None:
                    # lazy: StalledError lives in train/callbacks (a
                    # jax-importing module) — keep the hot control-plane
                    # imports light, mirroring classify_failure itself
                    from ..train.callbacks import StalledError

                    failed = (w.index, classify_failure(StalledError()),
                              f"heartbeats ticking but no progress past "
                              f"{w.monitor.stall_timeout_s}s (step "
                              f"{hb.step if hb else '?'})")
        if (pending_restart is not None and failed is None
                and all(w.ready or w.done for w in self._workers)):
            restart_index, cause = pending_restart
            self.flightrec.emit("fleet_restart", restart=restart_index,
                                cause=cause, incarnation=self.incarnation)
            logger.warning("fleet: gang live after restart %d (cause=%s, "
                           "incarnation %d)", restart_index, cause,
                           self.incarnation)
            if self._t_outage is not None:
                # the WHOLE outage — gang stop, backoff, relaunch,
                # restore, first-beat — is recovery waste: N workers
                # trained nothing from the death to this moment. This is
                # the number the elastic path shrinks by ~an order of
                # magnitude (docs/resilience.md "Elastic fleet").
                slept = self.clock() - self._t_outage
                if slept > 0:
                    goodput.note_wasted(goodput.WASTE_RESTART_RECOVERY,
                                        slept, registry=self.registry)
                self._t_outage = None
            pending_restart = None
        return pending_restart, relayed, failed

    def _restore_divergence(self, hb: Heartbeat | None) -> str | None:
        """The gang-consistency check behind the restore ceiling: a
        relaunched worker that restored a DIFFERENT step than the one
        written (e.g. its copy of that step was quarantined at read
        time and fallback landed lower, or it fresh-inited) has
        silently diverged from the gang. Classified transient: another
        gang restart recomputes the intersection without the bad step
        and converges."""
        if self._ceiling is None or hb is None:
            return None
        expect = self._ceiling if self._ceiling > 0 else None  # 0 = fresh
        if hb.restore_step != expect:
            return (f"gang divergence: worker restored step "
                    f"{hb.restore_step}, gang ceiling is {self._ceiling}")
        return None

    def _classify_exit(self, w: _Worker, rc: int,
                       hb: Heartbeat | None) -> tuple[str, str] | None:
        """Map a worker exit to (cause, detail), or None for a clean
        'done' completion."""
        if rc == 0:
            if hb is not None and hb.phase == "preempted":
                return (PREEMPTION,
                        f"worker exited 0 after a preemption save "
                        f"(step {hb.step})")
            if hb is not None and hb.phase not in ("done",):
                logger.warning(
                    "fleet: worker %d exited 0 in phase %r; counting as "
                    "done", w.index, hb.phase)
            return None
        if rc == EXIT_PREEMPTED:
            return (PREEMPTION, "worker exited via coordinated "
                                "preemption save")
        if rc == EXIT_FAILED:
            cause = hb.cause if hb is not None and hb.cause else None
            if cause not in _KNOWN_CAUSES:
                cause = FATAL
            return (cause, f"worker's in-process supervision exhausted "
                           f"[{cause}]")
        return (classify_failure(WorkerDead(f"exit code {rc}")),
                f"worker exited with code {rc}")

    # -- gang stop / restart ----------------------------------------------

    def _alive(self) -> list[_Worker]:
        return [w for w in self._workers if w.handle.poll() is None]

    def _gang_stop(self, cause: str) -> None:
        """SIGTERM the survivors (coordinated preemption save), SIGKILL
        whatever outlives the grace period."""
        survivors = self._alive()
        for w in survivors:
            logger.warning("fleet: SIGTERM worker %d (gang stop, cause=%s)",
                           w.index, cause)
            w.handle.terminate()
        deadline = self.clock() + self.cfg.term_grace_s
        while self._alive() and self.clock() < deadline:
            self._wait(min(self.cfg.poll_s, self.cfg.term_grace_s / 4))
        killed = 0
        for w in self._alive():
            logger.error("fleet: SIGKILL worker %d (outlived the %.1fs "
                         "gang-stop grace)", w.index, self.cfg.term_grace_s)
            w.handle.kill()
            killed += 1
        self._reap_all()
        self.flightrec.emit("fleet_gang_stop", cause=cause,
                            survivors=len(survivors), killed=killed)

    def _gang_path(self, cause: str, detail: str) -> tuple[int, str]:
        """The non-elastic failure path: tear the whole gang down and
        either schedule a restart (returned as ``pending_restart``) or
        raise ``FleetExhausted``. The window from here to the restarted
        gang's liveness confirmation is booked as ``restart_recovery``
        waste."""
        t0 = self.clock()
        self._resize = None  # any in-flight resize is moot: everyone dies
        self._gang_stop(cause)
        if cause not in self.cfg.restart_on \
                or self.restarts >= self.cfg.max_restarts:
            # book the recovery waste spent so far: an exhausted chain
            # never reaches the gang-live booking in _poll_round, and an
            # unbooked outage would under-report exactly the ledger the
            # postmortem of a dead run is read against
            start = self._t_outage if self._t_outage is not None else t0
            slept = self.clock() - start
            if slept > 0:
                goodput.note_wasted(goodput.WASTE_RESTART_RECOVERY, slept,
                                    registry=self.registry)
            self._t_outage = None
            self.flightrec.emit("fleet_exhausted", cause=cause,
                                restarts=self.restarts)
            self._dump_postmortem(f"fleet_exhausted:{cause}")
            raise FleetExhausted(cause, self.restarts, detail)
        pending = self._gang_restart(cause)
        if self._t_outage is None:
            # a second death during a still-pending restart must not
            # restart the outage clock: the window runs from the FIRST
            # gang stop to the first gang that confirms live
            self._t_outage = t0
        return pending

    def _gang_restart(self, cause: str) -> tuple[int, str]:
        delay = self.cfg.backoff.backoff_s(self.restarts)
        self.restarts += 1
        self.registry.counter(
            FLEET_RESTARTS_TOTAL, "fleet gang restarts by failure class",
            cause=cause,
        ).inc()
        logger.warning("fleet: gang restart %d/%d (cause=%s) after %.2fs "
                       "backoff", self.restarts, self.cfg.max_restarts,
                       cause, delay)
        # the backoff sleep needs no waste booking of its own: it sits
        # inside the gang-stop → gang-live outage window booked when the
        # restarted gang confirms liveness (_poll_round)
        self._wait(delay)
        self._ceiling = None
        if self.ckpt_dirs is not None:
            common = newest_common_valid_step(self.ckpt_dirs)
            if common is not None:
                write_restore_step(self.workdir, common)
                self._ceiling = common
                for d in self.ckpt_dirs:
                    evict_steps_above(d, common)
                logger.warning("fleet: restore ceiling for incarnation %d "
                               "is step %d", self.incarnation + 1, common)
        self.incarnation += 1
        write_incarnation(self.workdir, self.incarnation)
        if self.cfg.elastic:
            # the restarted gang is N-wide again: fresh steady plan, the
            # sharding applying from the restore ceiling forward
            self._joiner_relaunches = 0
            self._write_plan(ShardPlan(
                version=(self._plan.version + 1) if self._plan else 1,
                phase=PLAN_STEADY, world=self.num_workers,
                ranks={i: i for i in range(self.num_workers)},
                barrier_step=self._ceiling or 0,
                incarnation=self.incarnation,
                fleet_size=self.num_workers))
        self._launch_all()
        return (self.restarts, cause)

    # -- elastic resize (shrink at N-1, rejoin at N) -----------------------

    def _write_plan(self, plan: ShardPlan) -> None:
        write_shard_plan(self.workdir, plan)
        self._plan = plan

    def _absorb_elastically(self, worker: int, cause: str,
                            pending: bool = False) -> bool:
        """Decide whether this death shrinks the gang instead of
        stopping it. True = handled (shrink begun, or a dead replacement
        relaunched); False = take the gang-stop path."""
        if not self.cfg.elastic:
            return False
        if pending:
            # a gang restart is still confirming: members may not have
            # read their restore ceiling yet, and a hold would name
            # workers still in build/restore — another gang pass is the
            # only consistent answer
            logger.warning(
                "elastic: worker %d died while a gang restart is "
                "pending; falling back to another gang restart", worker)
            return False
        w = self._workers[worker]
        if not w.member:
            return self._relaunch_joiner(w)
        if self._resize is not None:
            logger.warning(
                "elastic: worker %d died during an in-flight %s; falling "
                "back to gang restart", worker, self._resize["kind"])
            return False
        if cause not in _ELASTIC_CAUSES:
            logger.warning(
                "elastic: cause %r indicts the trajectory, not one "
                "process; falling back to gang restart", cause)
            return False
        hb = w.monitor.heartbeat
        if hb is not None and hb.phase == "save":
            logger.warning(
                "elastic: worker %d died mid-checkpoint; its newest step "
                "dir may be torn — falling back to gang restart", worker)
            return False
        survivors = [x for x in self._workers
                     if x.member and not x.done and x.index != worker]
        members_after = sum(
            1 for x in self._workers if x.member and x.index != worker)
        if members_after < self.cfg.min_workers or not survivors:
            logger.warning(
                "elastic: shrink would leave %d member(s), below "
                "min_workers=%d (or none still training); falling back "
                "to gang restart", members_after, self.cfg.min_workers)
            return False
        self._begin_shrink(w, survivors, cause)
        return True

    def _begin_shrink(self, w: _Worker, survivors: list[_Worker],
                      cause: str) -> None:
        """Survivors pause at a barrier (hold plan), the dead worker's
        slot is relaunched as a catching-up replacement, and the release
        (written by ``_elastic_tick`` once every survivor acknowledges
        the hold) reshards the stream across N-1."""
        self._ensure_dead(w)
        w.member = False
        hold = tuple(sorted(x.index for x in survivors))
        self._resize = {
            "kind": "shrink", "stage": "hold", "t0": self.clock(),
            "worker": w.index, "cause": cause, "hold": hold,
            "version": self._plan.version + 1,
        }
        plan = dataclasses.replace(
            self._plan, version=self._plan.version + 1, phase=PLAN_HOLD,
            hold=hold)
        # anchor BEFORE the plan write: a holder's elastic_hold can only
        # follow its read of the plan file, so this event strictly
        # precedes it — the hold anchor of the merged timeline
        self.flightrec.emit("fleet_hold", version=plan.version,
                            hold=list(hold), resize="shrink")
        self._write_plan(plan)
        logger.warning(
            "elastic: shrink begun — worker %d out, holding %s at the "
            "next step boundary (plan v%d)", w.index, list(hold),
            self._plan.version)
        self._launch_joiner(w.index)

    def _launch_joiner(self, index: int) -> None:
        """Relaunch worker ``index``'s slot as a non-member replacement.
        It restores from its own newest valid checkpoint and replays the
        deterministic stream to catch up; once it proves life past
        build+restore (within ``rejoin_grace_s``) the next barrier
        absorbs it back into the gang."""
        path = heartbeat_path(self.workdir, index)
        if os.path.exists(path):
            # a corpse's last beat must never satisfy the replacement's
            # launch grace (same incarnation, so the monitor would
            # otherwise accept it)
            os.remove(path)
        # an earlier gang restart's RESTORE_STEP was consumed when that
        # gang came live; left behind it would cap a joiner's restore at
        # the old ceiling and force a needless long replay
        clear_restore_step(self.workdir)
        # ... and the dead worker's half-finished catch-up exchange must
        # not be mistaken by its replacement for an answer to ITS request
        clear_catchup_for(self.workdir, index)
        handle = self.launch(index, self.incarnation)
        self._workers[index] = _Worker(
            index=index, handle=handle,
            monitor=HeartbeatMonitor(
                path, self.incarnation, clock=self.clock,
                heartbeat_timeout_s=self.cfg.heartbeat_timeout_s,
                stall_timeout_s=self.cfg.stall_timeout_s,
                launch_grace_s=self.cfg.rejoin_grace_s,
            ),
            member=False)
        self.flightrec.emit("fleet_launch", worker=index,
                            incarnation=self.incarnation,
                            pid=getattr(handle, "pid", None), rejoin=True)
        logger.warning("fleet: launched replacement for worker %d "
                       "(incarnation %d, pid %s)", index, self.incarnation,
                       getattr(handle, "pid", None))

    def _relaunch_joiner(self, w: _Worker) -> bool:
        """A replacement died before rejoining. Relaunch it (bounded by
        the restart budget); an in-flight rejoin hold is released at the
        CURRENT sharding so the members never wait on a corpse."""
        if self._joiner_relaunches >= self.cfg.max_restarts:
            logger.error(
                "elastic: replacement for worker %d died %d time(s); "
                "falling back to gang restart", w.index,
                self._joiner_relaunches + 1)
            return False
        self._joiner_relaunches += 1
        self._ensure_dead(w)
        if self._resize is not None and self._resize["kind"] == "rejoin":
            self._write_plan(dataclasses.replace(
                self._plan, version=self._plan.version + 1,
                phase=PLAN_STEADY, hold=()))
            self._resize = None
        self._launch_joiner(w.index)
        return True

    def _elastic_tick(self) -> tuple[str, str] | None:
        """Advance the resize state machine one poll round. Returns a
        ``(cause, detail)`` gang-stop escalation when a resize overran
        its budget, else None."""
        if not self.cfg.elastic:
            return None
        st = self._resize
        if st is None:
            joiner = next((w for w in self._workers if not w.member), None)
            if joiner is not None and joiner.ready:
                self._begin_rejoin(joiner)
            return None
        if self.clock() - st["t0"] > self.cfg.hold_timeout_s:
            logger.error("elastic: %s overran hold_timeout_s=%.1f in stage "
                         "%s; falling back to gang restart", st["kind"],
                         self.cfg.hold_timeout_s, st["stage"])
            return (TRANSIENT,
                    f"elastic {st['kind']} timed out in stage {st['stage']}")
        if st["stage"] == "hold":
            acked: list[int] = []
            for i in st["hold"]:
                w = self._workers[i]
                if w.done:
                    continue
                hb = w.monitor.heartbeat
                if (hb is None or hb.plan_version != st["version"]
                        or hb.phase != "barrier"):
                    return None  # keep waiting for this member
                acked.append(hb.step)
            self._release(st, acked)
        else:  # released: wait for every member to apply the new plan
            for w in self._workers:
                if not w.member or w.done:
                    continue
                hb = w.monitor.heartbeat
                if hb is None or (hb.plan_version or 0) < st["version"]:
                    return None
            waste = self.clock() - st["t0"]
            if waste > 0:
                goodput.note_wasted(goodput.WASTE_ELASTIC_RESIZE, waste,
                                    registry=self.registry)
            logger.warning("elastic: %s complete in %.2fs (world %d)",
                           st["kind"], waste, self._plan.world)
            self._resize = None
        return None

    def _begin_rejoin(self, joiner: _Worker) -> None:
        """The replacement proved life: absorb it at the next barrier,
        restoring N-way sharding. With no member left training (they
        finished while it caught up) the release is immediate."""
        holders = tuple(sorted(
            w.index for w in self._workers if w.member and not w.done))
        st = {
            "kind": "rejoin", "stage": "hold", "t0": self.clock(),
            "worker": joiner.index, "cause": None, "hold": holders,
            "version": self._plan.version + 1,
        }
        self._resize = st
        if holders:
            plan = dataclasses.replace(
                self._plan, version=self._plan.version + 1, phase=PLAN_HOLD,
                hold=holders)
            # anchor BEFORE the plan write (see _begin_shrink)
            self.flightrec.emit("fleet_hold", version=plan.version,
                                hold=list(holders), resize="rejoin")
            self._write_plan(plan)
            logger.warning("elastic: rejoin begun — worker %d back, "
                           "holding %s (plan v%d)", joiner.index,
                           list(holders), self._plan.version)
        else:
            self._release(st, [])

    def _release(self, st: dict, acked_steps: list[int]) -> None:
        """Write the post-resize steady plan. The barrier is the highest
        step any holder paused at — holders pause BEFORE fetching their
        next batch, so every member's stream cursor is <= barrier and
        the new sharding binds exactly to batches > barrier."""
        members = sorted(w.index for w in self._workers if w.member)
        if st["kind"] == "rejoin":
            members = sorted(set(members) | {st["worker"]})
        # the barrier must bound every cursor in the gang: holders'
        # paused steps, but also members that already FINISHED (their
        # consumed range may exceed the holders') and the joiner — the
        # switch may never rewrite a batch anyone already consumed
        steps = list(acked_steps)
        steps += [hb.step for w in self._workers
                  if (w.member or w.index == st["worker"])
                  and (hb := w.monitor.heartbeat) is not None]
        barrier = max(steps) if steps else 0
        plan = ShardPlan(
            version=self._plan.version + 1, phase=PLAN_STEADY,
            world=len(members),
            ranks={idx: r for r, idx in enumerate(members)},
            barrier_step=barrier, incarnation=self.incarnation,
            fleet_size=self.num_workers)
        # release anchor BEFORE the plan write: a worker's
        # elastic_release can only follow its read of the steady plan,
        # so this event strictly precedes every post-barrier reshard
        if st["kind"] == "shrink":
            self.flightrec.emit("fleet_shrink", worker=st["worker"],
                                world=plan.world, barrier=barrier,
                                cause=st["cause"], version=plan.version)
        else:
            self._workers[st["worker"]].member = True
            self.flightrec.emit("fleet_rejoin", worker=st["worker"],
                                world=plan.world, barrier=barrier,
                                version=plan.version)
        self._write_plan(plan)
        st["stage"], st["version"] = "released", plan.version
        self.resizes += 1
        self.registry.counter(
            FLEET_RESIZES_TOTAL, "elastic gang resizes by direction",
            direction=st["kind"],
        ).inc()
        self._m_size.set(plan.world)
        logger.warning("elastic: %s released at barrier step %d "
                       "(world %d, plan v%d)", st["kind"], barrier,
                       plan.world, plan.version)

    def _ensure_dead(self, w: _Worker) -> None:
        """Make one worker's death final before its slot is rewired:
        terminate (grace for a coordinated save), kill past the grace,
        reap (liveness.ensure_dead, on the fleet's interruptible wait)."""
        liveness.ensure_dead(w.handle, self.cfg.term_grace_s,
                             self.cfg.poll_s, clock=self.clock,
                             sleep=self._wait)

    def _preempted_teardown(self) -> None:
        """The fleet process itself was SIGTERMed: stop the gang (the
        workers take their coordinated preemption saves) and surface the
        signal to run()'s finally for re-delivery."""
        logger.warning("fleet: SIGTERM received; stopping the gang")
        self._gang_stop(PREEMPTION)
        raise FleetExhausted(
            PREEMPTION, self.restarts,
            "fleet process preempted; gang stopped with coordinated saves")

    def _reap_all(self) -> None:
        """Wait on every worker handle. Called only after the gang is
        terminated/killed, so the waits are short — and they must cover
        the just-SIGKILLed children whose ``poll()`` still reads None
        (the kernel hasn't finished tearing them down): skipping those
        leaks one zombie per escalated gang stop."""
        for w in self._workers:
            liveness.reap(w.handle)

    def _dump_postmortem(self, reason: str) -> None:
        flightrec_lib.dump_postmortem(self.flightrec, self.postmortem_dir,
                                      reason=reason)
