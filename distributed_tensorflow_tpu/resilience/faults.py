"""Deterministic fault injection — the chaos half of the durability story.

PR 0-2 rebuilt the reference's save/restore machinery ($TF
failure_handling.py:337 PreemptionCheckpointHandler → train/checkpoint.py:
coordinated preemption saves, CRC manifests, validate-before-save); this
module *exercises* it. Every fault is injected through a seam the
production code already has — the callback list, the wrapping data
iterator, the injectable clock, the checkpoint directory on disk — so a
chaos run executes the exact code paths a real failure would, with no
test-only hooks inside the train or serve loops.

The fault vocabulary (docs/resilience.md maps each to the recovery path
it drives):

- ``Sigterm(step)``       — the process SIGTERMs itself after step N:
  PreemptionWatcher → coordinated save → ``PreemptionSaved`` clean exit.
- ``DataError(batch)``    — the data iterator raises ``IOError`` fetching
  batch M: unhandled step exception → Trainer emergency checkpoint →
  re-raise (restart-and-resume covers the gap).
- ``NaNBatch(batch)``     — one batch is poisoned with NaN, so that
  step's loss/grads go non-finite: NaNGuard aborts and
  ``validate_before_save`` refuses to checkpoint the poisoned params.
- ``ClockStall(step, dt)``— the injectable ``FaultClock`` jumps forward
  after step N: drives the Watchdog budget and serve deadlines without
  real waiting.
- ``TransientIOError(batch, times)`` — the data iterator raises
  ``IOError`` fetching batch M, ``times`` times in total, then succeeds:
  the retryable fault class ``RetryingIterator`` (data/pipeline.py)
  absorbs by re-seeking; ``times`` past the retry budget models a
  *permanent* IO failure and drives retry exhaustion instead.
- ``CorruptCheckpoint(restart)`` — truncates the newest saved checkpoint
  at the Nth supervisor restart boundary (``FaultPlan.restart_hook``
  seam): the torn-write-discovered-at-restore fault that
  ``Checkpointer.restore(fallback=True)`` must quarantine and fall past.
- ``AsyncCommitKill(step)`` — SIGKILLs the process from INSIDE the
  background async-save writer, after the step's shards are on disk but
  BEFORE the manifest publish/rename (``FaultPlan.save_hook`` seam →
  ``Checkpointer.save_hooks``): the death-mid-background-write fault the
  snapshot-then-commit layout must make invisible — the torn write stays
  in ``.pending/`` and no restore path may land on it.
- ``SlowWriter(step, delay_s)`` — stalls the background writer at the
  start of step N's commit (same seam): drives the bounded
  wait()/close() join, the save-phase heartbeat window, and the
  retention-vs-slow-writer ordering tests.
- ``PodOutage(step)`` — SIGKILLs the process after step N; every worker
  of the victim pod carries the same fault, so the pod dies as a UNIT —
  the whole-fault-domain loss only a hierarchy can express
  (resilience/podfleet.py restarts the pod at its own quorum ceiling
  while the other pods keep stepping).
- ``ControlPlanePartition(step, steps)`` — redirects heartbeat writes
  into a shadow file for a bounded window while training continues
  (``FaultPlan.callback(writer=...)`` seam → ``HeartbeatWriter.
  redirect``): the worker's control-plane record goes stale with the
  process demonstrably alive — the partition the pod supervisor must
  FENCE on, never restart on (a relaunch would double-train the batch
  ranges the partitioned original is still training).
- ``SlowControlPlane(step, delay_s, steps)`` — delays every heartbeat
  write by a bounded amount for a window (``FaultPlan.beat_pace`` seam
  → ``HeartbeatCallback(pace=...)``): the gray failure — beats slow
  but regular, steps advancing — that neither the liveness budget nor
  the stall detector may convert into a death.

Checkpoint corruption is a disk-level fault, not a run-level one, so it
is a pair of standalone helpers (``truncate_shard`` / ``corrupt_shard``)
aimed at a saved step dir; ``verify_manifest`` must reject the result at
restore time. ``CorruptCheckpoint`` is the plan-scheduled wrapper over
``truncate_shard`` for supervised runs.

Everything is deterministic: faults fire at exact step/batch indices,
and ``FaultPlan.seeded`` derives those indices from a seed so a chaos
sweep is reproducible run-to-run. Fired-state lives ON THE PLAN (not the
callback/iterator instance), so a fault fires at most once per plan even
when the Supervisor rebuilds the callback list and re-wraps the data
stream on every restart — a SIGTERM injected at step 3 does not re-fire
after the restart resumes past step 3.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal as signal_lib

import numpy as np

from ..obs import flightrec as flightrec_lib
from ..train.callbacks import Callback

logger = logging.getLogger(__name__)


def _record_fault(fault: str, **attrs) -> None:
    """Every injected fault lands in the process flight recorder the
    instant it fires — the postmortem timeline's ground truth for "what
    was done to this run" (tools/postmortem.py)."""
    flightrec_lib.default_recorder().emit("fault_fired", fault=fault, **attrs)


# ---------------------------------------------------------------------------
# Clock
# ---------------------------------------------------------------------------


class FaultClock:
    """Manually-advanced clock, drop-in for the ``clock=`` seams
    (Scheduler/ServeEngine/Watchdog/MetricsLogger). Starts at ``start``
    and only moves when told to — latency and deadline logic becomes
    exactly reproducible."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError("clocks only go forward")
        self.t += float(dt)
        return self.t


# ---------------------------------------------------------------------------
# Fault vocabulary
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Sigterm:
    """Send SIGTERM to our own process after train step ``step``
    completes (FaultCallback seam)."""

    step: int


@dataclasses.dataclass(frozen=True)
class DataError:
    """Raise ``IOError`` from the data iterator on its ``batch``-th
    ``next()`` call, 1-based — batch i feeds train step i
    (FaultyIterator seam)."""

    batch: int
    message: str = "injected data fault"


@dataclasses.dataclass(frozen=True)
class NaNBatch:
    """Poison the ``batch``-th batch (1-based): the first element of
    ``key``'s array (or of the first float array found) becomes NaN, so
    the step computes non-finite loss/grads — the seam for driving
    NaNGuard and validate_before_save (FaultyIterator seam).

    ``recur=True`` models *persistently* bad data at a fixed raw index
    — the numeric-anomaly defense's quarantine target: the fault keys
    on the exact index (``count == batch``) instead of the one-shot
    catch-up trigger (``count >= batch``) and never enters the plan's
    fired set, so every re-seek, restart, and incarnation that fetches
    that index is re-poisoned — until the quarantine-aware stream stops
    fetching it at all (docs/resilience.md "Numeric anomalies")."""

    batch: int
    key: str | None = None
    recur: bool = False


@dataclasses.dataclass(frozen=True)
class ClockStall:
    """Advance the plan's FaultClock by ``dt`` seconds after step
    ``step`` — a frozen host / stuck collective as seen by everything
    reading that clock (FaultCallback seam; pass the clock to
    ``FaultPlan.callback``)."""

    step: int
    dt: float


@dataclasses.dataclass(frozen=True)
class Hang:
    """Hang the host loop after step ``step`` completes: the callback
    spins in a Python-level sleep loop forever, so heartbeats from the
    step seam stop while the process stays alive — the
    missed-heartbeat death the FleetSupervisor must detect, and (with
    ``advance`` set and a FaultClock wired) the hung-step budget the
    Watchdog's ``abort_on_stall`` converts into a classified
    ``StalledError``. A SIGTERM only flags the PreemptionWatcher — the
    spin never reaches the next save cadence, so only SIGKILL (the
    fleet's gang-stop escalation) or an async abort ends it
    (FaultCallback seam)."""

    step: int
    #: advance the plan's FaultClock by this many seconds once, just
    #: before spinning — drives a clock-injected Watchdog over budget
    #: deterministically
    advance: float | None = None


@dataclasses.dataclass(frozen=True)
class TransientIOError:
    """Raise ``IOError`` from the data iterator fetching the ``batch``-th
    batch (1-based), ``times`` times IN TOTAL across every iterator
    wrapping this plan, then succeed — the remaining-fires count is
    plan-shared state, so a re-seeking retry wrapper sees the fault decay
    exactly ``times`` fires regardless of how often it rebuilds the
    stream. A huge ``times`` models a permanent IO failure (drives retry
    exhaustion); no source batch is ever consumed by a faulted fetch
    (FaultyIterator seam)."""

    batch: int
    times: int = 1
    message: str = "injected transient IO fault"


@dataclasses.dataclass(frozen=True)
class CorruptCheckpoint:
    """Truncate ``nbytes`` from the largest shard of the NEWEST saved
    checkpoint when supervisor restart number ``restart`` begins (1 = the
    first restart; ``FaultPlan.restart_hook`` seam). Models corruption
    discovered at restore time — the case fallback restore must
    quarantine and degrade past, not brick on."""

    restart: int = 1
    nbytes: int = 1


@dataclasses.dataclass(frozen=True)
class AsyncCommitKill:
    """SIGKILL our own process from the background async-save writer at
    step >= ``step``, between the shard writes and the manifest
    publish — the widest torn-write window the snapshot-then-commit
    layout has (``FaultPlan.save_hook`` seam). The kill is immediate and
    unhandleable; the staged ``.pending/<step>`` dir must never become
    restorable."""

    step: int


@dataclasses.dataclass(frozen=True)
class SlowWriter:
    """Sleep ``delay_s`` inside the background async-save writer before
    step >= ``step``'s shard writes begin (``FaultPlan.save_hook``
    seam) — a slow/stuck writer thread as seen by wait()'s bounded
    join, the heartbeat save-phase window, and retention."""

    step: int
    delay_s: float = 1.0


@dataclasses.dataclass(frozen=True)
class PodOutage:
    """SIGKILL this process once step >= ``step`` (``FaultCallback``
    seam).  Every worker of the victim pod carries the same fault, so
    the pod dies as a UNIT — the whole-fault-domain loss only a
    hierarchy can express: resilience/podfleet.py's pod supervisor
    restarts the pod at its own per-pod quorum ceiling while the other
    pods keep stepping.  An injected ``flush`` runs first so the
    flight recording survives the kill."""

    step: int


@dataclasses.dataclass(frozen=True)
class ControlPlanePartition:
    """Redirect heartbeat writes into a shadow file for ``steps`` steps
    once step >= ``step`` (``FaultPlan.callback(writer=...)`` seam →
    ``HeartbeatWriter.redirect``), then restore and beat immediately.
    The worker keeps training while its control-plane record goes
    stale with the process demonstrably alive — the partition a pod
    supervisor must FENCE on, never restart on: a relaunch would
    double-train the batch ranges the partitioned original is still
    training."""

    step: int
    steps: int = 3


@dataclasses.dataclass(frozen=True)
class SlowControlPlane:
    """Delay every heartbeat write by ``delay_s`` for ``steps`` steps
    once step >= ``step`` (``FaultPlan.beat_pace`` seam →
    ``train.callbacks.HeartbeatCallback(pace=...)``): the bounded gray
    failure — beats slow but regular, steps advancing — that neither
    the liveness budget nor the stall detector may convert into a
    death.  ``delay_s`` must stay well under ``heartbeat_timeout_s``
    for the judgment to hold; the fault models slow control-plane IO,
    not a partition."""

    step: int
    delay_s: float = 0.2
    steps: int = 3


Fault = (Sigterm | DataError | NaNBatch | ClockStall | Hang
         | TransientIOError | CorruptCheckpoint | AsyncCommitKill
         | SlowWriter | PodOutage | ControlPlanePartition
         | SlowControlPlane)


# ---------------------------------------------------------------------------
# Plan + injection seams
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An immutable schedule of faults. One plan drives three seams:
    ``plan.callback()`` goes into the Trainer's callback list (step
    faults), ``plan.wrap(iterator)`` wraps the batch source (data
    faults), ``plan.restart_hook(dir)`` goes into the Supervisor's
    ``on_restart`` list (restart-boundary disk faults).

    Each fault fires at most once PER PLAN: the fired set (and the
    remaining-fires count of TransientIOError) is shared mutable state on
    the plan, excluded from equality — so re-wrapping the stream or
    rebuilding the callback list (retry re-seeks, supervisor restarts)
    never re-fires a fault that already happened."""

    faults: tuple[Fault, ...] = ()
    #: indices of faults that already fired — plan-level, not per-seam
    _fired: set = dataclasses.field(
        default_factory=set, init=False, compare=False, repr=False)
    #: fault index → remaining fires, for TransientIOError decay
    _transient_left: dict = dataclasses.field(
        default_factory=dict, init=False, compare=False, repr=False)
    #: indices of ControlPlanePartition faults whose window already
    #: closed (redirect undone) — plan-level like _fired, so a rebuilt
    #: callback list mid-window still restores the real heartbeat path
    _partition_done: set = dataclasses.field(
        default_factory=set, init=False, compare=False, repr=False)

    @classmethod
    def seeded(cls, seed: int, num_steps: int,
               kinds: tuple[str, ...] = ("sigterm",)) -> "FaultPlan":
        """Deterministic random plan: each requested kind fires once at
        a seed-derived step in [2, num_steps-1] — never step 1 (nothing
        saved yet) and never the final step (nothing left to recover).
        Same (seed, num_steps, kinds) → identical plan."""
        if num_steps < 3:
            raise ValueError("need num_steps >= 3 to place a mid-run fault")
        rng = random.Random(seed)
        faults: list[Fault] = []
        for kind in kinds:
            at = rng.randint(2, num_steps - 1)
            if kind == "sigterm":
                faults.append(Sigterm(at))
            elif kind == "data_error":
                faults.append(DataError(at))
            elif kind == "nan_batch":
                faults.append(NaNBatch(at))
            elif kind == "clock_stall":
                faults.append(ClockStall(at, dt=rng.uniform(1.0, 600.0)))
            elif kind == "transient_io":
                faults.append(TransientIOError(at, times=rng.randint(1, 2)))
            elif kind == "ckpt_corrupt":
                # fires at the first restart boundary; `at` drawn anyway
                # so every kind consumes rng state uniformly
                faults.append(CorruptCheckpoint(restart=1))
            elif kind == "async_commit_kill":
                faults.append(AsyncCommitKill(at))
            elif kind == "slow_writer":
                faults.append(SlowWriter(at, delay_s=rng.uniform(0.5, 5.0)))
            elif kind == "pod_outage":
                faults.append(PodOutage(at))
            elif kind == "control_plane_partition":
                faults.append(
                    ControlPlanePartition(at, steps=rng.randint(2, 4)))
            elif kind == "slow_control_plane":
                faults.append(SlowControlPlane(
                    at, delay_s=rng.uniform(0.05, 0.5),
                    steps=rng.randint(2, 4)))
            else:
                raise ValueError(f"unknown fault kind {kind!r}")
        return cls(tuple(faults))

    def callback(self, clock: FaultClock | None = None,
                 writer=None, flush=None) -> "FaultCallback":
        """``writer``: the worker's live ``HeartbeatWriter``, required
        by ControlPlanePartition (its redirect seam). ``flush``: called
        before PodOutage's SIGKILL lands so the flight recording
        reaches disk."""
        return FaultCallback(self, clock=clock, writer=writer, flush=flush)

    def beat_pace(self, sleep=None):
        """A ``train.callbacks.HeartbeatCallback(pace=...)`` hook firing
        this plan's SlowControlPlane faults: a bounded delay injected on
        the beat path itself — training untouched, every heartbeat
        write inside the window ``delay_s`` late.  The fault RECORD
        fires once (plan-shared ``_fired``); the delay applies to every
        step of the window.  ``sleep`` is injectable for tests."""

        def pace(step: int) -> None:
            for i, fault in enumerate(self.faults):
                if not isinstance(fault, SlowControlPlane):
                    continue
                if fault.step <= step < fault.step + fault.steps:
                    if i not in self._fired:
                        self._fired.add(i)
                        _record_fault("slow_control_plane", step=step,
                                      delay_s=fault.delay_s,
                                      steps=fault.steps)
                        logger.warning(
                            "fault: slowing heartbeat writes %.2fs/step "
                            "for %d steps from step %d",
                            fault.delay_s, fault.steps, step)
                    if sleep is not None:
                        sleep(fault.delay_s)
                    else:
                        import time as time_lib

                        time_lib.sleep(fault.delay_s)

        return pace

    def wrap(self, iterator, start: int = 0) -> "FaultyIterator":
        """``start``: batches already consumed upstream (a resumed run's
        restored step), so batch-indexed faults stay aligned with GLOBAL
        step numbering across restarts and re-seeks."""
        return FaultyIterator(iterator, self, start=start)

    def restart_hook(self, directory: str):
        """A ``Supervisor(on_restart=…)`` hook firing this plan's
        CorruptCheckpoint faults against the newest step dir under
        ``directory`` (no-op until a checkpoint exists)."""

        def hook(restart_index: int, cause: str) -> None:
            for i, fault in enumerate(self.faults):
                if (not isinstance(fault, CorruptCheckpoint)
                        or i in self._fired
                        or restart_index < fault.restart):
                    continue
                step = _newest_step_on_disk(directory)
                if step is None:
                    continue  # nothing saved yet; try again next restart
                self._fired.add(i)
                path = truncate_shard(directory, step, nbytes=fault.nbytes)
                _record_fault("ckpt_corrupt", step=step,
                              restart=restart_index)
                logger.warning(
                    "fault: truncated %d byte(s) of newest checkpoint "
                    "(step %d) at restart %d: %s",
                    fault.nbytes, step, restart_index, path,
                )

        return hook

    def save_hook(self, flush=None, sleep=None):
        """A ``Checkpointer.save_hooks`` entry firing this plan's
        background-writer faults through the production async-commit
        seam. ``stage`` is the writer's position: ``async_begin`` (the
        SlowWriter stall point, before any shard write) and
        ``shards_done`` (the AsyncCommitKill window — shards durable,
        manifest NOT yet published).

        ``flush``: called after a kill fault is recorded and before
        SIGKILL lands, so the flight-recorder ring reaches disk — the
        postmortem's only record of a death this abrupt. ``sleep``:
        injectable stall for tests (default: real ``time.sleep``)."""

        def hook(stage: str, step: int) -> None:
            for i, fault in enumerate(self.faults):
                if i in self._fired:
                    continue
                if (isinstance(fault, SlowWriter)
                        and stage == "async_begin" and step >= fault.step):
                    self._fired.add(i)
                    _record_fault("slow_writer", step=step,
                                  delay_s=fault.delay_s)
                    logger.warning(
                        "fault: stalling the async checkpoint writer "
                        "%.2fs at step %d", fault.delay_s, step)
                    if sleep is not None:
                        sleep(fault.delay_s)
                    else:
                        import time as time_lib

                        time_lib.sleep(fault.delay_s)
                elif (isinstance(fault, AsyncCommitKill)
                        and stage == "shards_done" and step >= fault.step):
                    self._fired.add(i)
                    _record_fault("async_commit_kill", step=step)
                    logger.warning(
                        "fault: SIGKILL inside the async commit window "
                        "at step %d (shards written, manifest not "
                        "published)", step)
                    if flush is not None:
                        flush()
                    os.kill(os.getpid(), signal_lib.SIGKILL)

        return hook


def _newest_step_on_disk(directory: str) -> int | None:
    """Largest numeric step dir under ``directory`` (filesystem truth —
    no manager involved, matching how disk faults see the world)."""
    d = os.path.abspath(os.path.expanduser(directory))
    if not os.path.isdir(d):
        return None
    steps = [int(n) for n in os.listdir(d)
             if n.isdigit() and os.path.isdir(os.path.join(d, n))]
    return max(steps) if steps else None


class FaultCallback(Callback):
    """Fires the plan's step-indexed faults from ``on_step_end`` — the
    same seam every production hook uses, so a SIGTERM lands exactly
    where a GCE maintenance event would: between steps, with the
    PreemptionWatcher already installed."""

    def __init__(self, plan: FaultPlan, clock: FaultClock | None = None,
                 writer=None, flush=None):
        self.plan = plan
        self.clock = clock
        self.writer = writer
        self.flush = flush

    def on_step_end(self, trainer, step, metrics):
        fired = self.plan._fired  # plan-shared: at most once per PLAN
        for i, fault in enumerate(self.plan.faults):
            if (isinstance(fault, ControlPlanePartition) and i in fired
                    and i not in self.plan._partition_done
                    and self.writer is not None
                    and step >= fault.step + fault.steps):
                # window end: restore the real heartbeat path and beat
                # at once, so recovery is observable the same instant
                self.plan._partition_done.add(i)
                self.writer.redirect(None)
                self.writer.beat(step=step)
                logger.warning(
                    "fault: control-plane partition healed at step %d",
                    step)
                continue
            if i in fired:
                continue
            if isinstance(fault, Sigterm) and step >= fault.step:
                fired.add(i)
                _record_fault("sigterm", step=step)
                os.kill(os.getpid(), signal_lib.SIGTERM)
            elif isinstance(fault, ClockStall) and step >= fault.step:
                fired.add(i)
                if self.clock is None:
                    raise ValueError(
                        "ClockStall fault needs FaultPlan.callback(clock=...)"
                    )
                _record_fault("clock_stall", step=step, dt=fault.dt)
                self.clock.advance(fault.dt)
            elif isinstance(fault, PodOutage) and step >= fault.step:
                fired.add(i)
                _record_fault("pod_outage", step=step)
                logger.warning(
                    "fault: pod outage — SIGKILL at step %d", step)
                if self.flush is not None:
                    self.flush()
                os.kill(os.getpid(), signal_lib.SIGKILL)
            elif (isinstance(fault, ControlPlanePartition)
                    and step >= fault.step):
                fired.add(i)
                if self.writer is None:
                    raise ValueError(
                        "ControlPlanePartition needs "
                        "FaultPlan.callback(writer=...)")
                _record_fault("control_plane_partition", step=step,
                              steps=fault.steps)
                logger.warning(
                    "fault: partitioning the control plane for %d steps "
                    "from step %d (beats go to a shadow file)",
                    fault.steps, step)
                self.writer.redirect(
                    self.writer.path + ".partitioned")
            elif isinstance(fault, Hang) and step >= fault.step:
                fired.add(i)
                _record_fault("hang", step=step, advance=fault.advance)
                if fault.advance is not None:
                    if self.clock is None:
                        raise ValueError(
                            "Hang(advance=...) needs "
                            "FaultPlan.callback(clock=...)")
                    self.clock.advance(fault.advance)
                logger.warning("fault: hanging the host loop after step %d",
                               step)
                import time as time_lib

                # Python-level spin: interruptible only by an async
                # StalledError (Watchdog abort_on_stall) or SIGKILL —
                # SIGTERM merely flags the PreemptionWatcher and the
                # loop never reaches its next save cadence
                while True:
                    time_lib.sleep(0.05)


class FaultyIterator:
    """Wraps a batch iterator and injects the plan's batch-indexed
    faults. Batch numbering is 1-based and counts ``next()`` calls from
    ``start``, so with the standard loop batch i feeds train step i —
    pass ``start=restored_step`` on resume to keep global alignment.

    Fired-state is plan-shared: a one-shot fault (DataError/NaNBatch)
    fires once per PLAN even across re-wraps, and TransientIOError's
    remaining-fires count decays across re-seeks — a faulted fetch never
    consumes a source batch, so recovery sees the data it missed."""

    def __init__(self, iterator, plan: FaultPlan, start: int = 0):
        self._it = iter(iterator)
        self.plan = plan
        self.count = start

    def __iter__(self) -> "FaultyIterator":
        return self

    def __next__(self):
        self.count += 1
        fired = self.plan._fired
        left = self.plan._transient_left
        for i, fault in enumerate(self.plan.faults):
            if isinstance(fault, DataError):
                if i not in fired and self.count >= fault.batch:
                    fired.add(i)
                    _record_fault("data_error", step=self.count)
                    raise IOError(f"{fault.message} (batch {self.count})")
            elif isinstance(fault, TransientIOError):
                if self.count >= fault.batch:
                    remaining = left.setdefault(i, fault.times)
                    if remaining > 0:
                        left[i] = remaining - 1
                        _record_fault("transient_io", step=self.count,
                                      fires_left=remaining - 1)
                        raise IOError(
                            f"{fault.message} (batch {self.count}, "
                            f"{remaining - 1} fire(s) left)"
                        )
        batch = next(self._it)
        for i, fault in enumerate(self.plan.faults):
            if not isinstance(fault, NaNBatch):
                continue
            if fault.recur:
                # persistent bad index: fires on EVERY fetch of exactly
                # this index, across re-wraps and incarnations — only a
                # quarantine hole (the stream never fetching it) ends it
                if self.count == fault.batch:
                    _record_fault("nan_batch", step=self.count, recur=True)
                    batch = _poison_batch(batch, fault.key)
            elif i not in fired and self.count >= fault.batch:
                fired.add(i)
                _record_fault("nan_batch", step=self.count)
                batch = _poison_batch(batch, fault.key)
        return batch


def _poison_batch(batch, key: str | None):
    """Copy ``batch`` with one NaN planted in the chosen (or first)
    float array — enough to make the whole step's grads non-finite
    through the loss reduction."""
    if not isinstance(batch, dict):
        raise TypeError(f"NaNBatch expects a dict batch, got {type(batch)}")
    out = dict(batch)
    keys = [key] if key is not None else [
        k for k, v in batch.items()
        if np.issubdtype(np.asarray(v).dtype, np.floating)
    ]
    if not keys:
        raise ValueError("NaNBatch: no float array in batch to poison")
    k = keys[0]
    arr = np.array(batch[k], dtype=np.asarray(batch[k]).dtype, copy=True)
    arr.reshape(-1)[0] = np.nan
    out[k] = arr
    return out


# ---------------------------------------------------------------------------
# Disk faults: checkpoint shard corruption
# ---------------------------------------------------------------------------


def _manifest_files(d: str) -> list[dict]:
    """Files listed in the step dir's MANIFEST.dtf (largest first), or a
    raw directory walk when no manifest exists."""
    path = os.path.join(d, "MANIFEST.dtf")
    if os.path.exists(path):
        from ..runtime import io as io_lib

        files = json.loads(io_lib.read_payload(path))["files"]
    else:
        files = []
        for root, _, names in os.walk(d):
            for n in sorted(names):
                if n == "MANIFEST.dtf":
                    continue
                p = os.path.join(root, n)
                files.append({
                    "path": os.path.relpath(p, d),
                    "bytes": os.path.getsize(p),
                })
    files = [f for f in files if f["bytes"] > 0]
    if not files:
        raise FileNotFoundError(f"no corruptible files under {d}")
    return sorted(files, key=lambda f: -f["bytes"])


def truncate_shard(directory: str, step: int, nbytes: int = 1,
                   index: int = 0) -> str:
    """Truncate ``nbytes`` from the ``index``-th largest file of the
    step's checkpoint dir (the partial-write / torn-copy fault).
    Returns the mutilated path; ``verify_manifest`` must now raise."""
    from ..train.checkpoint import step_dir

    d = step_dir(directory, step)
    entry = _manifest_files(d)[index]
    path = os.path.join(d, entry["path"])
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size - nbytes, 0))
    return path


def corrupt_shard(directory: str, step: int, offset: int = 0,
                  index: int = 0) -> str:
    """Flip one byte of the ``index``-th largest file at ``offset`` (the
    bit-rot fault — size-preserving, so only content checks like the
    manifest CRC on MANIFEST.dtf itself, or orbax's own digests, can
    catch it). Returns the mutilated path."""
    from ..train.checkpoint import step_dir

    d = step_dir(directory, step)
    entry = _manifest_files(d)[index]
    path = os.path.join(d, entry["path"])
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        if not b:
            raise ValueError(f"offset {offset} past end of {path}")
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return path
