"""Fault injection + recovery validation (docs/resilience.md).

The durability layers (train/checkpoint.py preemption saves + manifests,
serve admission control, crash-safe Trainer exits) are only as good as
the faults that have actually been thrown at them. This package holds
the deterministic fault harness that drives every recovery path
end-to-end — in tests (tests/test_resilience.py, tests/chaos_worker.py)
and in the CI chaos smoke (tools/chaos_smoke.py).
"""

from .faults import (  # noqa: F401
    ClockStall,
    DataError,
    FaultCallback,
    FaultClock,
    FaultPlan,
    FaultyIterator,
    NaNBatch,
    Sigterm,
    corrupt_shard,
    truncate_shard,
)
