"""Fault injection + automatic recovery (docs/resilience.md).

The durability layers (train/checkpoint.py preemption saves + manifests,
serve admission control, crash-safe Trainer exits) are only as good as
the faults that have actually been thrown at them. This package holds
both halves of that story:

- the deterministic fault harness (faults.py) that drives every recovery
  path end-to-end — in tests (tests/test_resilience.py,
  tests/chaos_worker.py) and in the CI chaos smoke
  (tools/chaos_smoke.py);
- the recovery machinery itself: a generic retry/backoff executor with
  seeded jitter and obs counters (retry.py), and the in-process training
  Supervisor that classifies failures and restarts `Trainer.fit` from
  the latest *valid* checkpoint under a restart budget (supervisor.py);
- the process-liveness protocol (liveness.py): atomic heartbeat files,
  incarnation fencing, monitor-clock staleness, and launch-seam handle
  teardown — the ONE implementation shared by the training fleet
  (fleet.py) and the serving fleet (serve/fleet.py);
- the cluster-level layer over both: a collective-free, heartbeat-based
  fleet control plane that supervises worker PROCESSES and turns any
  classified failure into a coordinated gang restart from the latest
  common valid checkpoint (fleet.py);
- the hierarchical fault-domain layer over THAT (podfleet.py): one
  fleet.py-derived pod supervisor per pod plus a global coordinator
  over the same file+signal control plane — two-level
  ``(global_epoch, pod_incarnation)`` fencing, per-pod quorum restore
  under a cross-pod barrier, and partition fencing so a stale control
  plane is never mistaken for a dead pod;
- the numeric-anomaly defense (anomaly.py): host policy over the
  in-graph no-update-on-nonfinite guard — bounded batch skipping,
  deterministic bad-batch blame (live flag or restart-time bisection),
  and the quarantine file that steers data/pipeline.QuarantineFilter
  around condemned indices so poisoned restarts converge.
"""

from .anomaly import (  # noqa: F401
    AnomalyConfig,
    AnomalyPolicy,
    SkipBudgetExhausted,
    bisect_blame,
    blame_hook,
    load_quarantine,
    quarantine_index,
    quarantine_path,
    read_quarantine,
)
from .faults import (  # noqa: F401
    AsyncCommitKill,
    ClockStall,
    ControlPlanePartition,
    CorruptCheckpoint,
    DataError,
    FaultCallback,
    FaultClock,
    FaultPlan,
    FaultyIterator,
    Hang,
    NaNBatch,
    PodOutage,
    Sigterm,
    SlowControlPlane,
    SlowWriter,
    TransientIOError,
    corrupt_shard,
    truncate_shard,
)
from .liveness import (  # noqa: F401
    atomic_write,
    ensure_dead,
    reap,
)
from .fleet import (  # noqa: F401
    EXIT_FAILED,
    EXIT_PREEMPTED,
    FleetConfig,
    FleetExhausted,
    FleetSupervisor,
    Heartbeat,
    HeartbeatMonitor,
    HeartbeatWriter,
    WorkerDead,
    clear_catchup,
    clear_restore_step,
    evict_steps_above,
    heartbeat_path,
    newest_common_valid_step,
    newest_valid_step,
    read_heartbeat,
    read_incarnation,
    read_restore_step,
    request_catchup,
    valid_steps,
    write_incarnation,
    write_restore_step,
)
from .podfleet import (  # noqa: F401
    PodFleetConfig,
    PodFleetSupervisor,
    PodPlan,
    PodSupervisor,
    clear_pod_plan,
    hierarchical_common_step,
    pod_dir,
    pod_quorum_step,
    pod_valid_step_sets,
    podbeat_path,
    read_global_epoch,
    read_pod_plan,
    write_global_epoch,
    write_pod_plan,
)
from .retry import (  # noqa: F401
    AttemptTimeout,
    RetryExhausted,
    RetryPolicy,
    retry_call,
)
from .supervisor import (  # noqa: F401
    FATAL,
    POISONED,
    PREEMPTION,
    STALLED,
    TRANSIENT,
    Supervisor,
    SupervisorConfig,
    SupervisorExhausted,
    classify_failure,
)
from ..train.callbacks import StalledError  # noqa: F401  (the `stalled` class)
