"""Fault injection + automatic recovery (docs/resilience.md).

The durability layers (train/checkpoint.py preemption saves + manifests,
serve admission control, crash-safe Trainer exits) are only as good as
the faults that have actually been thrown at them. This package holds
both halves of that story:

- the deterministic fault harness (faults.py) that drives every recovery
  path end-to-end — in tests (tests/test_resilience.py,
  tests/chaos_worker.py) and in the CI chaos smoke
  (tools/chaos_smoke.py);
- the recovery machinery itself: a generic retry/backoff executor with
  seeded jitter and obs counters (retry.py), and the in-process training
  Supervisor that classifies failures and restarts `Trainer.fit` from
  the latest *valid* checkpoint under a restart budget (supervisor.py).
"""

from .faults import (  # noqa: F401
    ClockStall,
    CorruptCheckpoint,
    DataError,
    FaultCallback,
    FaultClock,
    FaultPlan,
    FaultyIterator,
    NaNBatch,
    Sigterm,
    TransientIOError,
    corrupt_shard,
    truncate_shard,
)
from .retry import (  # noqa: F401
    AttemptTimeout,
    RetryExhausted,
    RetryPolicy,
    retry_call,
)
from .supervisor import (  # noqa: F401
    FATAL,
    POISONED,
    PREEMPTION,
    TRANSIENT,
    Supervisor,
    SupervisorConfig,
    SupervisorExhausted,
    classify_failure,
)
