"""Generic retry/backoff executor for transient-failure seams.

PR 3 made faults *detectable* (manifest checks, watchdog, NaN refusal);
this module makes the transient subset *survivable*. One policy object
describes the whole budget — attempt count, exponential backoff with
seeded deterministic jitter, per-attempt timeout, total deadline — and
``retry_call`` executes any callable under it, emitting the two obs
counters every site shares:

    retry_attempts_total{site}   re-attempts after a retryable failure
    retry_exhausted_total{site}  budgets exhausted (the give-up events)

plus the flight-recorder events ``retry_attempt``/``retry_exhausted``
(obs/flightrec.py) and a ``wasted_seconds_total{cause=retry_backoff}``
goodput entry for every backoff slept (obs/goodput.py).

Determinism is a design requirement, not a nicety: the jitter is derived
from ``(seed, retry_index)``, so a chaos run that retries is exactly
reproducible — the same property FaultPlan.seeded gives the faults
themselves. Consumers: train/checkpoint.py (shard/manifest writes and
restores, sites ``ckpt_*``), data/pipeline.RetryingIterator (site
``data``), and resilience/supervisor.py reuses ``backoff_s`` for its
restart escalation.

Nothing here imports jax or train/ — plain stdlib + obs, so the
scheduler- and pipeline-level tests run device-free and checkpoint.py
can import it without a cycle.
"""

from __future__ import annotations

import dataclasses
import logging
import queue as queue_lib
import random
import threading
import time
from typing import Any, Callable

from ..obs import flightrec as flightrec_lib
from ..obs import goodput
from ..obs.flightrec import FlightRecorder
from ..obs.registry import Registry, default_registry

logger = logging.getLogger(__name__)

#: counter names (documented in docs/observability.md)
ATTEMPTS_TOTAL = "retry_attempts_total"
EXHAUSTED_TOTAL = "retry_exhausted_total"


class RetryExhausted(RuntimeError):
    """The retry budget (attempts or total deadline) ran out. Carries the
    ``site`` and attempt count; the last underlying failure is chained as
    ``__cause__`` so classification (resilience/supervisor.py) can see
    through to what actually failed."""

    def __init__(self, site: str, attempts: int, reason: str,
                 last: BaseException):
        super().__init__(
            f"retry budget exhausted at site {site!r} after {attempts} "
            f"failed attempt(s) ({reason}); last: {last!r}"
        )
        self.site = site
        self.attempts = attempts
        self.reason = reason


class AttemptTimeout(OSError):
    """A single attempt exceeded ``RetryPolicy.attempt_timeout_s``.
    Subclasses OSError so the default policy retries it."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Declarative retry budget. Immutable, so one policy instance can be
    shared across sites and threads; all mutable accounting lives in
    ``retry_call``'s frame."""

    #: total calls allowed (first try included); the Nth failure exhausts
    max_attempts: int = 3
    #: backoff before retry k is base_s * multiplier**k, capped
    base_s: float = 0.05
    multiplier: float = 2.0
    max_backoff_s: float = 30.0
    #: fraction of each backoff randomized away: delay ∈ [d·(1−jitter), d].
    #: Jitter is derived from (seed, retry_index) — deterministic.
    jitter: float = 0.5
    seed: int = 0
    #: wall budget across ALL attempts and backoffs; None = unbounded
    deadline_s: float | None = None
    #: per-attempt wall cap, enforced on a worker thread (the timed-out
    #: attempt's thread is abandoned, daemon); None = no cap
    attempt_timeout_s: float | None = None
    #: exception classes considered transient. IOError is an OSError
    #: alias, so the default covers the whole injected-IO fault family.
    retry_on: tuple[type[BaseException], ...] = (OSError,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_s < 0 or self.max_backoff_s < 0:
            raise ValueError("backoff durations must be >= 0")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1 (backoff escalates)")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")

    def backoff_s(self, retry_index: int) -> float:
        """Delay before the ``retry_index``-th retry (0-based). Pure
        function of (policy, retry_index) — same seed, same schedule."""
        d = min(self.base_s * self.multiplier ** retry_index,
                self.max_backoff_s)
        if self.jitter and d > 0:
            # str seeds hash via sha512 in random.seed(version=2):
            # stable across processes, unlike PYTHONHASHSEED-dependent hash()
            u = random.Random(f"{self.seed}:{retry_index}").random()
            d *= 1.0 - self.jitter * u
        return d


def _call_with_timeout(fn: Callable[[], Any], timeout_s: float,
                       site: str) -> Any:
    """Run ``fn`` on a daemon thread, bounded by ``timeout_s``. On
    timeout the thread is abandoned (it cannot be killed) and
    AttemptTimeout raised — acceptable for idempotent IO attempts, which
    is what the checkpoint/data seams are."""
    out: queue_lib.Queue = queue_lib.Queue(maxsize=1)

    def run():
        try:
            out.put((True, fn()))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            out.put((False, e))

    t = threading.Thread(target=run, daemon=True, name=f"retry-{site}")
    t.start()
    try:
        ok, val = out.get(timeout=timeout_s)
    except queue_lib.Empty:
        raise AttemptTimeout(
            f"{site}: attempt exceeded {timeout_s}s (worker abandoned)"
        ) from None
    if ok:
        return val
    raise val


def retry_call(
    fn: Callable[[], Any],
    *,
    policy: RetryPolicy = RetryPolicy(),
    site: str,
    registry: Registry | None = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Callable[[int, BaseException], None] | None = None,
    flightrec: FlightRecorder | None = None,
) -> Any:
    """Call ``fn`` under ``policy``; return its value or raise
    RetryExhausted (chaining the last failure).

    ``on_retry(failures, exc)`` runs after the backoff sleep and before
    the re-attempt — the seam RetryingIterator uses to re-seek its
    stream. Non-retryable exceptions (not in ``policy.retry_on``)
    propagate untouched and never touch the counters.
    """
    reg = registry if registry is not None else default_registry()
    rec = flightrec if flightrec is not None else flightrec_lib.default_recorder()
    attempts_c = reg.counter(
        ATTEMPTS_TOTAL, "re-attempts after a retryable failure", site=site)
    exhausted_c = reg.counter(
        EXHAUSTED_TOTAL, "retry budgets exhausted", site=site)
    t0 = clock()
    failures = 0
    pending: BaseException | None = None  # failure awaiting its on_retry
    while True:
        try:
            # the hook runs INSIDE the protected attempt: a re-seek that
            # hits the same outage counts against the budget and ends in
            # RetryExhausted like any other failure, instead of escaping
            # retry_call raw
            if pending is not None and on_retry is not None:
                on_retry(failures, pending)
            pending = None
            if policy.attempt_timeout_s is not None:
                return _call_with_timeout(fn, policy.attempt_timeout_s, site)
            return fn()
        except policy.retry_on as e:
            failures += 1
            if failures >= policy.max_attempts:
                exhausted_c.inc()
                rec.emit("retry_exhausted", site=site, failures=failures,
                         reason="attempt budget")
                raise RetryExhausted(site, failures, "attempt budget", e) from e
            delay = policy.backoff_s(failures - 1)
            if (policy.deadline_s is not None
                    and (clock() - t0) + delay > policy.deadline_s):
                exhausted_c.inc()
                rec.emit("retry_exhausted", site=site, failures=failures,
                         reason="total deadline")
                raise RetryExhausted(site, failures, "total deadline", e) from e
            attempts_c.inc()
            rec.emit("retry_attempt", site=site, failures=failures)
            logger.warning(
                "retry[%s]: attempt %d/%d failed (%s); backing off %.3fs",
                site, failures, policy.max_attempts, e, delay,
            )
            t_sleep = clock()
            sleep(delay)
            # goodput books ELAPSED time around the (injectable) sleep,
            # not the nominal delay: a no-op test sleep wastes nothing
            slept = clock() - t_sleep
            if slept > 0:
                goodput.note_wasted(goodput.WASTE_RETRY_BACKOFF, slept,
                                    registry=reg)
            pending = e
