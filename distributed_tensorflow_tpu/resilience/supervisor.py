"""In-process training supervision: restart-from-last-valid-checkpoint.

The reference's `MonitoredTrainingSession` hid a `_RecoverableSession`
($TF monitored_session.py:1238): when a run-call died of a transient
error it silently rebuilt the session from the last checkpoint and kept
going. Our rebuild made recovery checkpoint-restart (train/checkpoint.py)
but left the restart to an external scheduler; `Supervisor` closes the
loop *in process* — it wraps `Trainer.fit`, classifies what killed an
attempt, and relaunches from the newest checkpoint that passes integrity
checks, under a restart budget with escalating, seeded-jitter backoff.

Failure taxonomy (``classify_failure``, docs/resilience.md):

- ``transient``  — IO-class errors (OSError/IOError, incl. a
  RetryExhausted whose underlying failures were IO): the world glitched,
  the state on disk is fine → restart and resume.
- ``poisoned``   — FloatingPointError (NaNGuard abort,
  validate-before-save refusal): the in-memory state went bad; the last
  *valid* checkpoint predates the poison → roll back and retry. With
  deterministic data the poison usually recurs and the restart budget
  converts it into a loud, classified failure.
- ``stalled``    — StalledError (Watchdog ``abort_on_stall``, or a fleet
  liveness judgment): the step stopped making progress. Host state may
  be fine but is unprovable; roll back to the last valid checkpoint and
  restart.
- ``fatal``      — everything else (bugs, bad config, KeyboardInterrupt):
  re-raised immediately, never retried.
- ``preemption`` — not an exception: `Trainer.fit` returned cleanly with
  ``trainer.preempted`` set (SIGTERM → coordinated save). Restartable in
  process for single-host runs and chaos tests; on a real TPU slice the
  machine is going away, so production configs typically drop it from
  ``restart_on`` and let the cluster scheduler do the restart.

The supervisor itself never touches a checkpoint: the *builder* callable
constructs each attempt — fresh `Checkpointer` (fresh signal watcher),
`init_or_restore(..., fallback=True)` so a corrupt newest checkpoint is
quarantined and the run degrades by a few steps instead of bricking, a
fresh `Trainer`, and the data stream positioned at the restored step.
Driven end-to-end by the seedable FaultPlan in tests, so every recovery
is exactly reproducible.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal as signal_lib
import threading
import time
from typing import Any, Callable, Iterable, Sequence

from ..obs import flightrec as flightrec_lib
from ..obs import goodput
from ..obs.flightrec import FlightRecorder
from ..obs.registry import Registry, default_registry
from .retry import RetryExhausted, RetryPolicy

logger = logging.getLogger(__name__)

#: failure classes (classify_failure) and the preemption restart cause
TRANSIENT = "transient"
POISONED = "poisoned"
STALLED = "stalled"
FATAL = "fatal"
PREEMPTION = "preemption"

#: counter name (documented in docs/observability.md)
RESTARTS_TOTAL = "supervisor_restarts_total"


def classify_failure(exc: BaseException) -> str:
    """Map an exception out of ``Trainer.fit`` to a failure class."""
    # lazy: train.callbacks must stay importable without resilience/
    # (resilience/__init__ -> faults -> train.callbacks would cycle)
    from ..train.callbacks import StalledError

    if isinstance(exc, StalledError):
        return STALLED
    if isinstance(exc, RetryExhausted):
        # see through to what the retries were absorbing
        under = exc.__cause__
        if isinstance(under, FloatingPointError):
            return POISONED
        return TRANSIENT
    if isinstance(exc, FloatingPointError):
        return POISONED
    if isinstance(exc, OSError):  # IOError/TimeoutError are aliases/subclasses
        return TRANSIENT
    return FATAL


class SupervisorExhausted(RuntimeError):
    """The restart budget ran out. ``cause`` is the classified failure
    class of the last attempt; the last exception (if the attempt raised
    rather than exiting via preemption) is chained as ``__cause__``."""

    def __init__(self, cause: str, restarts: int, last: BaseException | None):
        super().__init__(
            f"supervisor restart budget exhausted after {restarts} "
            f"restart(s); last failure class {cause!r}"
            + (f": {last!r}" if last is not None else "")
        )
        self.cause = cause
        self.restarts = restarts


@dataclasses.dataclass(frozen=True)
class SupervisorConfig:
    #: restarts allowed (attempts = max_restarts + 1)
    max_restarts: int = 3
    #: failure classes that earn a restart; anything else re-raises
    restart_on: tuple[str, ...] = (TRANSIENT, POISONED, PREEMPTION, STALLED)
    #: escalating backoff between attempts — reuses RetryPolicy's
    #: seeded-jitter schedule (max_attempts is ignored here; the restart
    #: budget is max_restarts above)
    backoff: RetryPolicy = RetryPolicy(
        base_s=0.2, multiplier=2.0, max_backoff_s=60.0)

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        unknown = set(self.restart_on) - {TRANSIENT, POISONED, PREEMPTION,
                                          STALLED}
        if unknown:
            raise ValueError(f"unknown restart_on classes: {sorted(unknown)}")


class Supervisor:
    """Run ``build → fit`` until the target step is reached, restarting
    restartable failures from the latest valid checkpoint.

    ``build(restart_index)`` returns ``(trainer, data, checkpointer)``
    for one attempt; ``checkpointer`` may be None, otherwise the
    supervisor closes it when the attempt ends (success or failure) so
    signal handlers and async savers never leak across attempts.

    ``on_restart`` hooks run as ``hook(restart_index, cause)`` after the
    backoff sleep and before the next ``build`` — the production seam
    for cache cleanup or operator paging, and the seam
    ``FaultPlan.restart_hook`` uses to model corruption discovered at
    restart time. Hooks execute inside the next attempt's classified
    try: a hook that raises transiently earns a restart like any other
    failure, and the hooks re-run on that next attempt — keep them
    idempotent. ``sleep`` is injectable so chaos tests run the full
    escalation in microseconds; when NOT injected, backoff waits are an
    interruptible ``Event.wait`` that ``interrupt()`` — or a SIGTERM —
    wakes immediately, so a preemption is processed at once instead of
    after up to a full backoff interval (the signal is re-delivered to
    the pre-backoff handler once the wait returns).

    ``heartbeat`` (resilience/fleet.HeartbeatWriter, optional) is the
    fleet-liveness seam: the supervisor beats at every attempt boundary
    with the attempt number, so the fleet control plane sees life even
    while build/restore runs between training loops.
    """

    def __init__(
        self,
        build: Callable[[int], tuple[Any, Iterable, Any]],
        num_steps: int,
        cfg: SupervisorConfig = SupervisorConfig(),
        registry: Registry | None = None,
        on_restart: Sequence[Callable[[int, str], None]] = (),
        sleep: Callable[[float], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
        flightrec: FlightRecorder | None = None,
        postmortem_dir: str | None = None,
        heartbeat=None,
    ):
        self.build = build
        self.num_steps = num_steps
        self.cfg = cfg
        self.registry = registry if registry is not None else default_registry()
        self.on_restart = tuple(on_restart)
        self.sleep = sleep
        self.clock = clock
        self.heartbeat = heartbeat
        self._wake = threading.Event()
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        #: where the exhaustion postmortem lands; defaults to the first
        #: attempt checkpointer's directory (the run dir) when not given
        self.postmortem_dir = postmortem_dir
        #: restarts performed by the last run() (observability for tests)
        self.restarts = 0

    def interrupt(self) -> None:
        """Wake the in-progress (or next) backoff wait immediately. The
        wakeup is consumed by that one wait — never lost when it races
        the sleep, but not sticky either: later restarts keep their
        escalating backoff instead of degenerating into a zero-delay
        restart storm."""
        self._wake.set()

    def _backoff_wait(self, delay: float) -> None:
        """Sleep out one restart backoff. With an injected ``sleep`` the
        caller owns the semantics (tests). Otherwise wait on the wake
        event AND catch SIGTERM for the duration: during backoff no
        attempt checkpointer is alive, so no PreemptionWatcher handler
        is installed — without this, a preemption either kills the
        process mid-backoff (default handler) or waits out the full
        delay. The caught signal is re-delivered to the restored
        handler after the wait, so its real semantics still apply —
        just immediately."""
        if self.sleep is not None:
            self.sleep(delay)
            return
        pending: list[int] = []

        def handler(signum, frame):
            pending.append(signum)
            self._wake.set()

        main = threading.current_thread() is threading.main_thread()
        prev = signal_lib.signal(signal_lib.SIGTERM, handler) if main else None
        try:
            if self._wake.wait(delay):
                self._wake.clear()  # one-shot: later backoffs still wait
        finally:
            if main:
                signal_lib.signal(signal_lib.SIGTERM, prev)
        if pending:
            logger.warning(
                "supervisor: SIGTERM during restart backoff — woke early, "
                "re-delivering to the previous handler")
            os.kill(os.getpid(), pending[0])

    def run(self):
        """Supervised ``Trainer.fit``; returns the final TrainState.

        Raises SupervisorExhausted when the restart budget runs out, or
        re-raises the attempt's exception for non-restartable classes.
        A deliberate early stop (``trainer.request_stop`` without
        preemption, or data exhaustion) is respected and returned as-is.
        """
        restarts = 0
        last_exc: BaseException | None = None
        #: (restart_index, cause) the on_restart hooks still owe a run for
        pending_hook: tuple[int, str] | None = None
        while True:
            self.restarts = restarts
            cause: str | None = None
            trainer = ckpt = None
            self.flightrec.emit("sup_attempt", attempt=restarts)
            try:
                try:
                    if self.heartbeat is not None:
                        # fleet liveness: prove life before the (possibly
                        # slow) hook + build + restore boundary work
                        self.heartbeat.beat(attempt=restarts, phase="init")
                    # hooks and build are INSIDE the classified attempt:
                    # a transient failure at the restart boundary (a
                    # hook's disk work, a restore-time IO blip) earns
                    # another restart, not a raw escape. Hooks re-run on
                    # the next attempt if one raised — keep them
                    # idempotent. A builder that dies after creating its
                    # checkpointer must close it itself — the supervisor
                    # never saw it.
                    t_boundary = self.clock()
                    if pending_hook is not None:
                        for hook in self.on_restart:
                            hook(*pending_hook)
                        pending_hook = None
                    trainer, data, ckpt = self.build(restarts)
                    # goodput: hook + build time (restore, re-init) is
                    # wall-clock the job did not train — startup counts
                    # as warmup, restart boundaries as recovery
                    goodput.note_wasted(
                        goodput.WASTE_COMPILE_WARMUP if restarts == 0
                        else goodput.WASTE_RESTART_RECOVERY,
                        self.clock() - t_boundary, registry=self.registry,
                    )
                    if self.postmortem_dir is None:
                        self.postmortem_dir = getattr(
                            getattr(ckpt, "cfg", None), "directory", None)
                    state = trainer.fit(data, num_steps=self.num_steps)
                except BaseException as e:
                    cause = classify_failure(e)
                    last_exc = e
                    self.flightrec.emit(
                        "sup_failure", attempt=restarts, cause=cause,
                        error=repr(e)[:200],
                    )
                    logger.error(
                        "supervised attempt %d failed [%s]: %r",
                        restarts, cause, e,
                    )
                    if cause not in self.cfg.restart_on:
                        raise
                else:
                    done = int(state.step) >= self.num_steps
                    if done or not getattr(trainer, "preempted", False):
                        return state
                    cause, last_exc = PREEMPTION, None
                    if cause not in self.cfg.restart_on:
                        return state
            finally:
                if ckpt is not None:
                    # close() joins the async background writer (bounded)
                    # and re-raises its stored error after a clean
                    # shutdown — a failed attempt's exception must not be
                    # masked by it, so it is logged here (the success
                    # path already surfaced it via Checkpointer.wait in
                    # CheckpointCallback.on_train_end)
                    try:
                        ckpt.close()
                    except Exception:
                        logger.exception(
                            "closing checkpointer (async writer join) "
                            "after attempt %d failed", restarts,
                        )
            if restarts >= self.cfg.max_restarts:
                self.flightrec.emit("sup_exhausted", cause=cause,
                                    restarts=restarts)
                self._dump_postmortem(f"supervisor_exhausted:{cause}")
                raise SupervisorExhausted(cause, restarts, last_exc) from last_exc
            delay = self.cfg.backoff.backoff_s(restarts)
            restarts += 1
            self.registry.counter(
                RESTARTS_TOTAL, "supervised restarts by failure class",
                cause=cause,
            ).inc()
            self.flightrec.emit("sup_restart", restart=restarts, cause=cause,
                                backoff_s=round(delay, 6))
            logger.warning(
                "supervisor: restart %d/%d (cause=%s) after %.2fs backoff",
                restarts, self.cfg.max_restarts, cause, delay,
            )
            t_sleep = self.clock()
            self._backoff_wait(delay)
            # ELAPSED, not nominal: an injected no-op sleep wastes nothing
            slept = self.clock() - t_sleep
            if slept > 0:
                goodput.note_wasted(goodput.WASTE_RESTART_RECOVERY, slept,
                                    registry=self.registry)
            pending_hook = (restarts, cause)

    def _dump_postmortem(self, reason: str) -> None:
        flightrec_lib.dump_postmortem(self.flightrec, self.postmortem_dir,
                                      reason=reason)
