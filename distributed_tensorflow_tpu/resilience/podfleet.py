"""Hierarchical fault domains — a fleet of fleets (docs/resilience.md
"Hierarchical fault domains").

Everything below resilience/fleet.py assumes ONE flat fault domain: a
single FleetSupervisor, one ``newest_common_valid_step`` intersection,
one gang — so any failure the elastic path cannot absorb stops the
whole world. Real pods are not flat: intra-pod ICI and cross-pod DCN
fail differently (the MLPerf TPU-pod scaling work treats them as
different animals), and a whole pod's outage — or a partitioned control
plane — should degrade, never gang-stop, the planet. This module is the
two-level layer, built in the exact shape the single-level machinery
already proved out:

- **One pod supervisor per pod.** ``PodSupervisor`` IS a
  ``FleetSupervisor`` over the pod's own subdirectory
  (``<workdir>/pod-<p>/`` — a complete, self-contained fleet dir:
  heartbeats, INCARNATION, RESTORE_STEP, SHARD_PLAN, catchup/). Worker
  deaths, stalls, per-pod elastic shrinks, and pod-local gang restarts
  are handled entirely inside the pod.
- **A global coordinator over the same file+signal control plane.**
  Each pod supervisor heartbeats pod-level liveness into
  ``podbeat-<p>.json`` under the GLOBAL dir with the SAME
  writer/monitor protocol workers use one level down; the coordinator
  talks back through one atomic ``POD_PLAN`` file (the PR 12
  hold→release handshake, one level up). No direct calls cross the
  boundary in either direction, so a partitioned control plane is a
  real, injectable failure mode.
- **Two-level incarnation fencing** ``(global_epoch, pod_incarnation)``.
  The coordinator bumps ``GLOBAL_EPOCH`` once per run; podbeats and
  POD_PLANs are stamped with it and records from any other epoch read
  as *absent*. Inside a pod, the pod's own INCARNATION fences worker
  beats exactly as before — a worker's identity is the pair.
- **Hierarchical restore ceilings.** A pod that gang-restarts resumes
  at its OWN per-pod quorum (``newest_common_valid_step`` over its own
  ckpt dirs) — healthy pods are never rolled back by a neighbour's
  outage. The cross-pod ceiling (``hierarchical_common_step``) is the
  intersection of the LIVE pods' verified-step sets: set-intersection
  is associative, so with every pod healthy the two-level ceiling
  equals the flat one, and a dead pod's stale dirs can never veto a
  healthy pod's quorum because they are excluded from the live set.
- **Partition fencing, not split-brain.** A pod whose worker
  heartbeats ALL go stale while the processes are demonstrably alive
  (``poll()`` still None — with pulsed writers a live process always
  ticks ``seq``, so frozen-file + live-handle means the control plane,
  not the worker, failed) is FENCED: the supervisor emits
  ``pod_fence``, takes no restore/relaunch action, and waits. Acting
  on the stale record — relaunching workers whose originals are still
  training — would double-train the same batch ranges: the split-brain
  this rule exists to prevent. The fence lifts the moment fresh beats
  land (``pod_unfence``); only past ``fence_timeout_s`` does the pod
  take the ordinary outage path (where the gang stop first kills every
  still-alive handle, so even the escalation cannot split-brain).
- **Bounded cross-pod skew.** While a pod restarts, healthy pods keep
  stepping until they lead the restarting pod's ceiling by
  ``max_pod_skew_steps``; then the coordinator writes a POD_PLAN hold,
  each held pod supervisor parks its OWN workers at a worker-level
  barrier (the PR 12 machinery unchanged), and the release follows the
  recovered pod's first live beat. With ``elastic_pods=True`` the
  coordinator instead shrinks the cross-pod data axis immediately
  (hold → release at world = live pods) and grows it back on rejoin —
  the same shrink/rejoin dance ``FleetSupervisor`` does per worker.

Clocks and sleeps are injectable; nothing here imports jax.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Mapping, Sequence

from ..obs import flightrec as flightrec_lib
from ..obs.flightrec import FlightRecorder
from ..obs.registry import Registry, default_registry
from .fleet import (
    FleetConfig,
    FleetExhausted,
    FleetSupervisor,
    PLAN_HOLD,
    PLAN_STEADY,
    newest_common_valid_step,
    read_restore_step,
    valid_steps,
)
from .liveness import (
    DEAD,
    HeartbeatMonitor,
    HeartbeatWriter,
    atomic_write as _atomic_write,
)
from .supervisor import FATAL

logger = logging.getLogger(__name__)

GLOBAL_EPOCH_FILE = "GLOBAL_EPOCH"
_POD_PLAN_FILE = "POD_PLAN"

#: metric names (documented in docs/observability.md)
POD_RESTARTS_TOTAL = "pod_restarts_total"
FLEET_PODS_LIVE = "fleet_pods_live"
POD_BARRIER_SECONDS = "pod_barrier_seconds"

#: podbeat phases a pod supervisor moves through ("barrier" is in
#: liveness.HOLD_PHASES, so a coordinator monitor never calls a held
#: pod stalled; "fenced" changes the progress tuple, so neither does a
#: fence)
POD_TRAIN = "train"
POD_RESTARTING = "restarting"
POD_FENCED = "fenced"
POD_BARRIER = "barrier"


def pod_dir(workdir: str, pod: int) -> str:
    """Pod ``pod``'s own fleet dir — a complete single-level control
    plane (heartbeats, INCARNATION, RESTORE_STEP, SHARD_PLAN) nested
    under the global one."""
    return os.path.join(
        os.path.abspath(os.path.expanduser(workdir)), f"pod-{pod}")


def podbeat_path(workdir: str, pod: int) -> str:
    """Pod ``pod``'s pod-level heartbeat under the GLOBAL dir — written
    by its pod supervisor with the same protocol workers use one level
    down (incarnation field = the global epoch)."""
    return os.path.join(
        os.path.abspath(os.path.expanduser(workdir)), f"podbeat-{pod}.json")


def read_global_epoch(workdir: str) -> int:
    """Current global epoch (0 when no pod fleet has ever run here)."""
    path = os.path.join(
        os.path.abspath(os.path.expanduser(workdir)), GLOBAL_EPOCH_FILE)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except FileNotFoundError:
        return 0
    except (OSError, ValueError) as e:
        logger.warning("unreadable global-epoch file %s (%s); assuming 0",
                       path, e)
        return 0


def write_global_epoch(workdir: str, epoch: int) -> None:
    d = os.path.abspath(os.path.expanduser(workdir))
    os.makedirs(d, exist_ok=True)
    _atomic_write(os.path.join(d, GLOBAL_EPOCH_FILE), f"{int(epoch)}\n")


# ---------------------------------------------------------------------------
# Pod plan (cross-pod hold/release control file — the ShardPlan shape,
# one level up: ranks map PODS onto the cross-pod data axis)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PodPlan:
    """One cross-pod sharding epoch. ``ranks`` maps pod → rank over
    ``world`` (the cross-pod data axis); ``phase == PLAN_HOLD`` names
    the pods whose supervisors must park their workers at a worker-level
    barrier until a newer steady release. ``epoch``-fenced: a plan from
    any other global epoch reads as absent."""

    version: int
    phase: str
    world: int
    ranks: dict[int, int]
    barrier_step: int
    epoch: int = 0
    hold: tuple[int, ...] = ()
    #: the NOMINAL pod count the run was configured for
    num_pods: int = 0

    def __post_init__(self):
        if self.phase not in (PLAN_STEADY, PLAN_HOLD):
            raise ValueError(f"unknown pod-plan phase {self.phase!r}")
        if self.world < 1 or self.version < 1:
            raise ValueError("pod-plan world and version must be >= 1")
        if sorted(self.ranks.values()) != list(range(len(self.ranks))):
            raise ValueError(
                f"pod-plan ranks must be a bijection onto "
                f"0..{len(self.ranks) - 1}, got {self.ranks}")
        if self.world != len(self.ranks):
            raise ValueError(
                f"pod-plan world={self.world} != {len(self.ranks)} ranks")


def _pod_plan_path(workdir: str) -> str:
    return os.path.join(
        os.path.abspath(os.path.expanduser(workdir)), _POD_PLAN_FILE)


def read_pod_plan(workdir: str, epoch: int | None = None) -> PodPlan | None:
    """Current pod plan; None when absent, unreadable, or (with
    ``epoch`` given) stamped with a different global epoch — a stale
    plan file must never be actionable, that is the fencing rule."""
    try:
        with open(_pod_plan_path(workdir)) as f:
            d = json.load(f)
        plan = PodPlan(
            version=int(d["version"]), phase=str(d["phase"]),
            world=int(d["world"]),
            ranks={int(k): int(v) for k, v in d["ranks"].items()},
            barrier_step=int(d["barrier_step"]),
            epoch=int(d.get("epoch", 0)),
            hold=tuple(int(i) for i in d.get("hold", ())),
            num_pods=int(d.get("num_pods", 0)),
        )
    except FileNotFoundError:
        return None
    except (OSError, ValueError, KeyError, TypeError) as e:
        logger.warning("unreadable pod plan in %s (%s); treating as absent",
                       workdir, e)
        return None
    if epoch is not None and plan.epoch != int(epoch):
        return None
    return plan


def write_pod_plan(workdir: str, plan: PodPlan) -> None:
    d = os.path.abspath(os.path.expanduser(workdir))
    os.makedirs(d, exist_ok=True)
    _atomic_write(os.path.join(d, _POD_PLAN_FILE), json.dumps({
        "version": plan.version, "phase": plan.phase, "world": plan.world,
        "ranks": {str(k): v for k, v in plan.ranks.items()},
        "barrier_step": plan.barrier_step, "epoch": plan.epoch,
        "hold": list(plan.hold), "num_pods": plan.num_pods,
    }))


def clear_pod_plan(workdir: str) -> None:
    path = _pod_plan_path(workdir)
    if os.path.exists(path):
        os.remove(path)


# ---------------------------------------------------------------------------
# Hierarchical restore ceilings
# ---------------------------------------------------------------------------


def pod_quorum_step(ckpt_dirs: Sequence[str]) -> int | None:
    """A pod's OWN restart point: the newest step every worker of the
    pod retains and can verify — ``newest_common_valid_step`` scoped to
    one fault domain. This is the ceiling a pod-local gang restart
    resumes at; no other pod's retention appears in it."""
    return newest_common_valid_step(ckpt_dirs)


def pod_valid_step_sets(
    pod_ckpt_dirs: Mapping[int, Sequence[str]],
) -> dict[int, set[int]]:
    """Per-pod quorum SETS: pod → the steps every one of its workers
    can verify (the intersection within the pod)."""
    out: dict[int, set[int]] = {}
    for p, dirs in pod_ckpt_dirs.items():
        if not dirs:
            out[p] = set()
            continue
        common = set(valid_steps(dirs[0]))
        for d in dirs[1:]:
            common &= set(valid_steps(d))
        out[p] = common
    return out


def hierarchical_common_step(
    pod_ckpt_dirs: Mapping[int, Sequence[str]],
    live_pods: Sequence[int] | None = None,
) -> int | None:
    """The cross-pod restart point: per-pod quorum first, then the
    intersection across the LIVE pods. Set-intersection is associative,
    so with ``live_pods`` covering every pod this equals the flat
    ``newest_common_valid_step`` over all dirs — and excluding a dead
    pod from ``live_pods`` is exactly what keeps its stale dirs from
    vetoing a healthy pod's quorum. Empty intersection pins to 0 (the
    live pods fresh-start together); None when no live pod has dirs."""
    live = set(live_pods) if live_pods is not None else None
    quorums = pod_valid_step_sets(pod_ckpt_dirs)
    pods = [p for p in sorted(pod_ckpt_dirs)
            if (live is None or p in live) and pod_ckpt_dirs[p]]
    if not pods:
        return None
    common = set(quorums[pods[0]])
    for p in pods[1:]:
        common &= quorums[p]
    return max(common) if common else 0


# ---------------------------------------------------------------------------
# Pod-tagged flight recording
# ---------------------------------------------------------------------------


class _PodTaggedRecorder:
    """Duck-typed FlightRecorder proxy that stamps ``pod`` onto every
    event — a pod supervisor's whole record (fleet_launch,
    fleet_gang_stop, …) lands in the shared ring tagged with its fault
    domain, which is what lets ONE merged timeline span coordinator →
    pod supervisors → workers (obs/fleetview.py matches anchors per
    pod). Everything but ``emit`` forwards to the real ring."""

    def __init__(self, rec: FlightRecorder, pod: int):
        self._rec = rec
        self.pod = int(pod)

    def emit(self, kind: str, step: int | None = None, **attrs: Any) -> None:
        attrs.setdefault("pod", self.pod)
        self._rec.emit(kind, step=step, **attrs)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._rec, name)


# ---------------------------------------------------------------------------
# Pod-level supervision config
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PodFleetConfig:
    #: coordinator poll cadence
    poll_s: float = 0.25
    #: fence instead of restarting when a worker's heartbeat goes stale
    #: while its process is still alive (requires pulsed writers for the
    #: judgment to be sound — a live pulsed process always ticks seq)
    fence_on_partition: bool = True
    #: a fence older than this escalates to the ordinary outage path
    #: (the gang stop kills the still-alive handles first, so even the
    #: escalation cannot split-brain)
    fence_timeout_s: float = 60.0
    #: healthy pods may lead a restarting pod's ceiling by this many
    #: steps before the coordinator holds them at a cross-pod barrier
    max_pod_skew_steps: int = 64
    #: a cross-pod hold is released after this budget even if the
    #: restarting pod is still down — unbounded skew (deterministic
    #: replay covers it) beats cascading worker hold-timeouts
    pod_hold_timeout_s: float = 45.0
    #: shrink the cross-pod data axis on a pod outage instead of
    #: holding at a skew barrier; grow it back when the pod rejoins
    elastic_pods: bool = False
    #: no podbeat within this budget after the first one → the pod's
    #: control plane is stale (fence if its thread is alive). Sized
    #: above the longest gang-stop + restart backoff a pod supervisor
    #: sits through without polling.
    podbeat_timeout_s: float = 45.0
    #: podbeats ticking but pod-level progress frozen this long → stalled
    pod_stall_timeout_s: float = 600.0
    #: budget for a pod supervisor's FIRST podbeat
    pod_launch_grace_s: float = 120.0

    def __post_init__(self):
        if self.poll_s <= 0:
            raise ValueError("poll_s must be positive")
        if self.fence_timeout_s <= 0 or self.pod_hold_timeout_s <= 0:
            raise ValueError("fence/hold budgets must be positive")
        if self.max_pod_skew_steps < 1:
            raise ValueError("max_pod_skew_steps must be >= 1")


# ---------------------------------------------------------------------------
# Pod supervisor: a FleetSupervisor that is also a citizen of a pod fleet
# ---------------------------------------------------------------------------


class PodSupervisor(FleetSupervisor):
    """One pod's FleetSupervisor, extended with the pod-fleet protocol:

    - every flight-recorder event it (or its aggregator) emits carries
      ``pod`` — the merged postmortem's fault-domain label;
    - it heartbeats pod-level liveness into ``podbeat-<p>.json`` under
      the global dir (incarnation field = global epoch) every poll
      round, carrying min member step, restart count, and phase;
    - a gang failure emits ``pod_outage`` before the stop,
      ``pod_restart`` (with the per-pod quorum ceiling) at the
      relaunch, and ``pod_rejoin`` when the new gang confirms live —
      the pod-level causal chain the two-pod chaos round asserts;
    - worker heartbeats that ALL go stale while their processes are
      alive FENCE the pod (``pod_fence``) instead of restarting it —
      the control plane, not the worker, failed (see the module
      docstring's split-brain rule);
    - it obeys the coordinator's POD_PLAN: a hold naming this pod parks
      the pod's own workers at a worker-level barrier (elastic mode's
      PLAN_HOLD, unchanged), and the release un-parks them.
    """

    def __init__(self, pod: int, global_dir: str, epoch: int,
                 *args: Any, pod_cfg: PodFleetConfig = PodFleetConfig(),
                 **kwargs: Any):
        self.pod = int(pod)
        self.global_dir = os.path.abspath(os.path.expanduser(global_dir))
        self.epoch = int(epoch)
        self.pod_cfg = pod_cfg
        rec = kwargs.pop("flightrec", None)
        if rec is None:
            rec = flightrec_lib.default_recorder()
        kwargs["flightrec"] = _PodTaggedRecorder(rec, pod)
        super().__init__(*args, **kwargs)
        self._podbeat_writer = HeartbeatWriter(
            podbeat_path(self.global_dir, self.pod), incarnation=self.epoch,
            clock=self.clock)
        #: partition fence state: {"t0": monitor-clock fence start}
        self._fence: dict | None = None
        #: cross-pod hold state: {"pod_version", "version", "holders"}
        self._pod_hold: dict | None = None
        #: newest POD_PLAN version acted on
        self._pod_plan_applied = 0
        self._pod_phase = POD_TRAIN

    # -- lifecycle ---------------------------------------------------------

    def run(self) -> dict:
        try:
            out = super().run()
            self._podbeat_writer.finish("done")
            return out
        except FleetExhausted as e:
            self._podbeat_writer.finish("failed", cause=e.cause)
            raise
        except BaseException:
            self._podbeat_writer.finish("failed", cause=FATAL)
            raise

    def request_stop(self) -> None:
        """Coordinator-side global gang stop: make this pod's next poll
        take the preempted-teardown path (coordinated worker saves)
        without delivering a real signal. The sentinel 0 keeps the
        run() epilogue's re-delivery a no-op (``os.kill(pid, 0)`` only
        checks liveness)."""
        self._stop_signal.append(0)
        self.interrupt()

    # -- pod-level causal chain -------------------------------------------

    def _gang_path(self, cause: str, detail: str):
        self._fence = None
        self._pod_phase = POD_RESTARTING
        self.flightrec.emit("pod_outage", cause=cause)
        self._podbeat_writer.beat(attempt=self.restarts,
                                  phase=POD_RESTARTING)
        return super()._gang_path(cause, detail)

    def _gang_restart(self, cause: str):
        pending = super()._gang_restart(cause)
        self.registry.counter(
            POD_RESTARTS_TOTAL, "pod-local gang restarts by failure class",
            cause=cause,
        ).inc()
        self.flightrec.emit("pod_restart", restart=self.restarts,
                            cause=cause, ceiling=self._ceiling)
        self._podbeat_writer.beat(attempt=self.restarts,
                                  phase=POD_RESTARTING)
        return pending

    # -- poll round: fence, rejoin, pod plan, podbeat ---------------------

    def _poll_round(self, pending_restart, relayed):
        out = super()._poll_round(pending_restart, relayed)
        nxt_pending, nxt_relayed, failed = out
        if failed is not None:
            failed = self._maybe_fence(failed)
            out = (nxt_pending, nxt_relayed, failed)
        elif self._fence is not None:
            # super() reported NO failure this round: the heartbeat is
            # fresh again, so the partition healed. (A failure the
            # fence itself suppressed must NOT land here — unfencing on
            # it would reset the fence clock every poll and neuter
            # fence_timeout_s.)
            self._unfence()
        if (pending_restart is not None and nxt_pending is None
                and failed is None):
            self._pod_phase = POD_TRAIN
            self.flightrec.emit("pod_rejoin", restart=pending_restart[0])
        self._pod_plan_tick()
        self._podbeat()
        return out

    def _maybe_fence(self, failed):
        """The partition-fencing judgment. ``failed`` came out of the
        flat poll round; suppress it (return None) when the evidence
        says control-plane partition — heartbeat file frozen
        (monitor-clock DEAD) while the worker process is demonstrably
        alive — rather than death. Everything else (exit codes, stalls,
        a worker that never beat) passes through untouched."""
        worker, cause, detail = failed
        w = self._workers[worker]
        if (not self.pod_cfg.fence_on_partition
                or w.handle.poll() is not None
                or w.monitor.heartbeat is None
                or w.monitor.check() != DEAD):
            return failed
        now = self.clock()
        if self._fence is None:
            self._fence = {"t0": now}
            self._pod_phase = POD_FENCED
            self.flightrec.emit("pod_fence", worker=worker)
            self._podbeat(phase=POD_FENCED)
            logger.warning(
                "podfleet: pod %d FENCED — worker %d's heartbeat is stale "
                "but pid %s is alive; treating as control-plane partition, "
                "taking no action on the stale record", self.pod, worker,
                getattr(w.handle, "pid", None))
        if now - self._fence["t0"] > self.pod_cfg.fence_timeout_s:
            logger.error(
                "podfleet: pod %d fence outlived %.1fs; escalating to the "
                "outage path", self.pod, self.pod_cfg.fence_timeout_s)
            return (worker, cause, f"fence timeout: {detail}")
        return None

    def _unfence(self) -> None:
        fenced_s = max(self.clock() - self._fence["t0"], 0.0)
        self._fence = None
        self._pod_phase = POD_TRAIN
        self.flightrec.emit("pod_unfence", fenced_s=round(fenced_s, 6))
        self._podbeat(phase=POD_TRAIN)
        logger.warning("podfleet: pod %d unfenced after %.2fs — control "
                       "plane is back, nothing was restarted", self.pod,
                       fenced_s)

    def _pod_plan_tick(self) -> None:
        """Obey the coordinator's POD_PLAN (epoch-fenced read). A hold
        naming this pod is propagated DOWN as a worker-level PLAN_HOLD
        over the pod's own members; the steady release un-parks them at
        an unchanged sharding. Pods whose workers do not speak the plan
        channel (cfg.elastic=False) cannot be paused and simply ack."""
        plan = read_pod_plan(self.global_dir, epoch=self.epoch)
        if plan is None or plan.version <= self._pod_plan_applied:
            if self._pod_hold is not None and plan is not None \
                    and plan.version == self._pod_plan_applied:
                self._check_pod_hold_acked(plan)
            return
        if plan.phase == PLAN_HOLD and self.pod in plan.hold:
            self._begin_pod_hold(plan)
        elif plan.phase == PLAN_STEADY:
            self._release_pod_hold(plan)
        else:
            # a hold not naming us: nothing to do until the release
            self._pod_plan_applied = plan.version
            self._podbeat_writer.note_plan(plan.version, plan.world)

    def _begin_pod_hold(self, plan: PodPlan) -> None:
        if self._resize is not None:
            return  # an own-gang resize is in flight; retry next round
        if not self.cfg.elastic:
            # no worker-level plan channel: the pod cannot pause, so it
            # acks immediately and keeps stepping (documented unbounded-
            # skew fallback)
            self._pod_plan_applied = plan.version
            self._podbeat_writer.note_plan(plan.version, plan.world)
            return
        holders = tuple(sorted(
            w.index for w in self._workers if w.member and not w.done))
        self._pod_plan_applied = plan.version
        if not holders:
            self._podbeat_writer.note_plan(plan.version, plan.world)
            self._podbeat(phase=POD_BARRIER)
            return
        v = self._plan.version + 1
        wplan = dataclasses.replace(
            self._plan, version=v, phase=PLAN_HOLD, hold=holders)
        # anchor BEFORE the plan write (same discipline as _begin_shrink)
        self.flightrec.emit("fleet_hold", version=v, hold=list(holders),
                            resize="podhold")
        self._write_plan(wplan)
        self._pod_hold = {"pod_version": plan.version, "version": v,
                          "holders": holders, "world": plan.world}
        logger.warning("podfleet: pod %d holding %s for the cross-pod "
                       "barrier (pod plan v%d)", self.pod, list(holders),
                       plan.version)

    def _check_pod_hold_acked(self, plan: PodPlan) -> None:
        """Podbeat phase flips to ``barrier`` (the coordinator's ack
        signal) only once every held worker parked."""
        st = self._pod_hold
        for i in st["holders"]:
            w = self._workers[i]
            if w.done:
                continue
            hb = w.monitor.heartbeat
            if (hb is None or hb.plan_version != st["version"]
                    or hb.phase != "barrier"):
                return
        if self._pod_phase != POD_BARRIER:
            self._pod_phase = POD_BARRIER
            self._podbeat_writer.note_plan(st["pod_version"], st["world"])
            self._podbeat(phase=POD_BARRIER)

    def _release_pod_hold(self, plan: PodPlan) -> None:
        self._pod_plan_applied = plan.version
        self._podbeat_writer.note_plan(plan.version, plan.world)
        st, self._pod_hold = self._pod_hold, None
        if st is None:
            return
        steps = [hb.step for i in st["holders"]
                 if (hb := self._workers[i].monitor.heartbeat) is not None]
        barrier = max([plan.barrier_step] + steps)
        v = self._plan.version + 1
        # release anchor BEFORE the plan write, mirroring _release(): a
        # worker's elastic_release can only follow its read of the
        # steady plan, so this pod_release strictly precedes it
        self.flightrec.emit("pod_release", version=v,
                            world=self._plan.world, barrier=barrier)
        self._write_plan(dataclasses.replace(
            self._plan, version=v, phase=PLAN_STEADY, hold=(),
            barrier_step=barrier))
        self._pod_phase = POD_TRAIN
        self._podbeat(phase=POD_TRAIN)
        logger.warning("podfleet: pod %d released from the cross-pod "
                       "barrier at step %d (plan v%d)", self.pod, barrier, v)

    def _podbeat(self, phase: str | None = None) -> None:
        steps = [hb.step for w in self._workers
                 if w.member and (hb := w.monitor.heartbeat) is not None]
        self._podbeat_writer.beat(
            step=min(steps) if steps else 0, attempt=self.restarts,
            phase=phase if phase is not None else self._pod_phase)


# ---------------------------------------------------------------------------
# Global coordinator
# ---------------------------------------------------------------------------


class PodFleetSupervisor:
    """Supervise a fleet of pod fleets.

    ``launch(pod, worker, incarnation)`` starts one worker of one pod
    and returns a Popen-shaped handle — the same seam FleetSupervisor
    takes, plus the fault-domain coordinate. ``ckpt_dirs`` (optional)
    is one sequence of per-worker checkpoint dirs PER POD; each pod's
    restore ceiling is computed only over its own — the per-pod quorum.

    The coordinator runs every pod's ``PodSupervisor`` in a thread
    (signal handling stays on the coordinator's main thread) but talks
    to them only through the file control plane: podbeats up, POD_PLAN
    down. ``run()`` returns ``{"epoch", "restarts", "pod_restarts",
    "resizes"}``; a pod that exhausts its restart budget (or fails a
    non-restartable class) stops the planet — every other pod is
    gang-stopped through its coordinated-save path and
    ``FleetExhausted`` propagates with the postmortem dumped."""

    def __init__(
        self,
        launch: Callable[[int, int, int], Any],
        num_pods: int,
        workers_per_pod: int,
        workdir: str,
        cfg: FleetConfig = FleetConfig(),
        pod_cfg: PodFleetConfig = PodFleetConfig(),
        ckpt_dirs: Sequence[Sequence[str]] | None = None,
        registry: Registry | None = None,
        flightrec: FlightRecorder | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] | None = None,
        postmortem_dir: str | None = None,
    ):
        if num_pods < 1:
            raise ValueError("num_pods must be >= 1")
        if workers_per_pod < 1:
            raise ValueError("workers_per_pod must be >= 1")
        if ckpt_dirs is not None and len(ckpt_dirs) != num_pods:
            raise ValueError("ckpt_dirs must have one entry per pod")
        self.launch = launch
        self.num_pods = num_pods
        self.workers_per_pod = workers_per_pod
        self.workdir = os.path.abspath(os.path.expanduser(workdir))
        self.cfg = cfg
        self.pod_cfg = pod_cfg
        self.ckpt_dirs = (
            [list(d) for d in ckpt_dirs] if ckpt_dirs is not None else None)
        self.registry = registry if registry is not None else default_registry()
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.clock = clock
        self.sleep = sleep
        self.postmortem_dir = postmortem_dir or self.workdir
        self.epoch = 0
        self.pods: list[PodSupervisor] = []
        self._results: dict[int, dict] = {}
        self._errors: dict[int, BaseException] = {}
        self._threads: list[threading.Thread] = []
        self._monitors: list[HeartbeatMonitor] = []
        self._plan: PodPlan | None = None
        #: cross-pod barrier state: {"version", "t0", "hold", "reason"}
        self._hold: dict | None = None
        #: coordinator-level fence flags (stale podbeat, live thread)
        self._pod_fenced: set[int] = set()
        self._m_live = self.registry.gauge(
            FLEET_PODS_LIVE, "pods currently making training progress "
            "(live podbeat in a train/barrier phase)")
        self._h_barrier = self.registry.histogram(
            POD_BARRIER_SECONDS,
            "cross-pod barrier wall seconds, hold write to release write")

    # -- lifecycle ---------------------------------------------------------

    def _wait(self, delay: float) -> None:
        if self.sleep is not None:
            self.sleep(delay)
        else:
            time.sleep(delay)

    def _run_pod(self, p: int) -> None:
        try:
            self._results[p] = self.pods[p].run()
        except BaseException as e:  # held for the coordinator to classify
            self._errors[p] = e

    def run(self) -> dict:
        os.makedirs(self.workdir, exist_ok=True)
        self.epoch = read_global_epoch(self.workdir) + 1
        write_global_epoch(self.workdir, self.epoch)
        clear_pod_plan(self.workdir)
        self._plan = PodPlan(
            version=1, phase=PLAN_STEADY, world=self.num_pods,
            ranks={p: p for p in range(self.num_pods)}, barrier_step=0,
            epoch=self.epoch, num_pods=self.num_pods)
        write_pod_plan(self.workdir, self._plan)
        self._hold = None
        self._pod_fenced = set()
        self._results = {}
        self._errors = {}
        self.flightrec.emit("fleet_start",
                            workers=self.num_pods * self.workers_per_pod,
                            incarnation=self.epoch, pods=self.num_pods)
        self.pods = [
            PodSupervisor(
                p, self.workdir, self.epoch,
                # FleetSupervisor args: launch, num_workers, workdir, ...
                (lambda i, inc, _p=p: self.launch(_p, i, inc)),
                self.workers_per_pod, pod_dir(self.workdir, p),
                cfg=self.cfg, pod_cfg=self.pod_cfg,
                ckpt_dirs=(self.ckpt_dirs[p]
                           if self.ckpt_dirs is not None else None),
                registry=self.registry, flightrec=self.flightrec,
                clock=self.clock, sleep=self.sleep,
                postmortem_dir=pod_dir(self.workdir, p),
            )
            for p in range(self.num_pods)
        ]
        self._monitors = [
            HeartbeatMonitor(
                podbeat_path(self.workdir, p), self.epoch, clock=self.clock,
                heartbeat_timeout_s=self.pod_cfg.podbeat_timeout_s,
                stall_timeout_s=self.pod_cfg.pod_stall_timeout_s,
                launch_grace_s=self.pod_cfg.pod_launch_grace_s)
            for p in range(self.num_pods)
        ]
        self._threads = [
            threading.Thread(target=self._run_pod, args=(p,),
                             name=f"podfleet-p{p}", daemon=True)
            for p in range(self.num_pods)
        ]
        self._m_live.set(self.num_pods)
        for t in self._threads:
            t.start()
        try:
            while any(t.is_alive() for t in self._threads):
                self._wait(self.pod_cfg.poll_s)
                self._coordinate()
                if self._errors:
                    self._global_gang_stop()
                    break
            for t in self._threads:
                t.join()
        finally:
            for p, sup in enumerate(self.pods):
                for w in sup._workers:
                    if w.handle.poll() is None:
                        logger.error("podfleet: killing pod %d worker %d "
                                     "still alive at coordinator exit", p,
                                     w.index)
                        w.handle.kill()
        if self._errors:
            cause, detail = self._classify_errors()
            self.flightrec.emit(
                "fleet_exhausted", cause=cause,
                restarts=sum(s.restarts for s in self.pods),
                pods=sorted(self._errors))
            flightrec_lib.dump_postmortem(
                self.flightrec, self.postmortem_dir,
                reason=f"podfleet_exhausted:{cause}")
            raise FleetExhausted(cause,
                                 sum(s.restarts for s in self.pods), detail)
        self._m_live.set(0)
        self.flightrec.emit("fleet_done", incarnation=self.epoch,
                            pods=self.num_pods)
        logger.info("podfleet: all %d pods done (epoch %d)", self.num_pods,
                    self.epoch)
        return {
            "epoch": self.epoch,
            "restarts": sum(s.restarts for s in self.pods),
            "pod_restarts": {p: s.restarts
                             for p, s in enumerate(self.pods)},
            "resizes": sum(s.resizes for s in self.pods),
        }

    def _classify_errors(self) -> tuple[str, str]:
        p = sorted(self._errors)[0]
        e = self._errors[p]
        if isinstance(e, FleetExhausted):
            return e.cause, f"pod {p}: {e}"
        return FATAL, f"pod {p}: {e!r}"

    def _global_gang_stop(self) -> None:
        """A pod is irrecoverably down: pod-local restart lost, global
        gang-stop wins. Every still-running pod supervisor takes its
        preempted-teardown path (coordinated worker saves)."""
        failed = sorted(self._errors)
        logger.error("podfleet: pod(s) %s exhausted; stopping the planet",
                     failed)
        for p, t in enumerate(self._threads):
            if t.is_alive():
                self.pods[p].request_stop()
        for t in self._threads:
            t.join()

    # -- one coordinator tick ---------------------------------------------

    def _pod_states(self) -> list[tuple[str, str | None]]:
        """(liveness status, last podbeat phase) per pod, from the
        podbeat files alone — the coordinator never reaches into a pod
        supervisor's memory for its judgment."""
        out = []
        for m in self._monitors:
            status = m.check()
            hb = m.heartbeat
            out.append((status, hb.phase if hb is not None else None))
        return out

    def _coordinate(self) -> None:
        states = self._pod_states()
        live = 0
        restarting: list[int] = []
        for p, (status, phase) in enumerate(states):
            alive = self._threads[p].is_alive()
            if phase in (POD_TRAIN, POD_BARRIER) and status != DEAD and alive:
                live += 1
            if phase == POD_RESTARTING and alive:
                restarting.append(p)
            # coordinator-side fencing: a pod whose podbeat went stale
            # while its supervisor is demonstrably alive is FENCED — its
            # stale record is never acted on (not counted live, never a
            # reason to hold or reshard the others)
            if (status == DEAD and alive
                    and self._monitors[p].heartbeat is not None
                    and phase not in (POD_RESTARTING, "done", "failed")):
                if p not in self._pod_fenced:
                    self._pod_fenced.add(p)
                    self.flightrec.emit(
                        "pod_fence", pod=p,
                        stale_s=round(self.pod_cfg.podbeat_timeout_s, 6))
                    logger.warning("podfleet: coordinator fenced pod %d — "
                                   "podbeat stale, supervisor alive", p)
            elif p in self._pod_fenced and status != DEAD:
                self._pod_fenced.discard(p)
                self.flightrec.emit("pod_unfence", pod=p, fenced_s=None)
        self._m_live.set(live)
        self._barrier_tick(states, restarting)

    def _barrier_tick(self, states, restarting: list[int]) -> None:
        """The cross-pod skew barrier (or, with elastic_pods, the
        cross-pod shrink/rejoin) — all of it through POD_PLAN writes."""
        now = self.clock()
        if self._hold is not None:
            self._hold_tick(states, restarting, now)
            return
        if not restarting:
            return
        healthy = [p for p in range(self.num_pods)
                   if p not in restarting and p not in self._pod_fenced
                   and self._threads[p].is_alive()
                   and states[p][1] not in ("done", "failed")]
        if not healthy:
            return
        if self.pod_cfg.elastic_pods:
            self._write_hold(healthy, now, reason="shrink")
            return
        # bounded skew: hold only once a healthy pod leads the
        # restarting pod's own quorum ceiling by max_pod_skew_steps
        floor = min((read_restore_step(pod_dir(self.workdir, p)) or 0)
                    for p in restarting)
        lead = max((self._monitors[p].heartbeat.step
                    if self._monitors[p].heartbeat is not None else 0)
                   for p in healthy)
        if lead - floor > self.pod_cfg.max_pod_skew_steps:
            self._write_hold(healthy, now, reason="skew")

    def _write_hold(self, healthy: list[int], now: float,
                    reason: str) -> None:
        v = self._plan.version + 1
        # anchor BEFORE the plan write: a pod supervisor's fleet_hold
        # (resize=podhold) can only follow its read of this plan
        self.flightrec.emit("pod_hold", version=v, hold=list(healthy),
                            reason=reason)
        self._plan = dataclasses.replace(
            self._plan, version=v, phase=PLAN_HOLD, hold=tuple(healthy))
        write_pod_plan(self.workdir, self._plan)
        self._hold = {"version": v, "t0": now, "hold": tuple(healthy),
                      "reason": reason, "stage": "hold"}
        logger.warning("podfleet: cross-pod hold v%d over pods %s (%s)",
                       v, healthy, reason)

    def _hold_tick(self, states, restarting: list[int], now: float) -> None:
        st = self._hold
        overrun = now - st["t0"] > self.pod_cfg.pod_hold_timeout_s
        if st["stage"] == "hold":
            acked = all(
                (hb := self._monitors[p].heartbeat) is not None
                and hb.plan_version == st["version"]
                for p in st["hold"]
                if self._threads[p].is_alive())
            if not acked and not overrun:
                return
            if self.pod_cfg.elastic_pods and st["reason"] == "shrink":
                if restarting and not overrun:
                    # shrink now: the survivors train at world=len(hold)
                    self._write_release(st, world=len(st["hold"]),
                                        pods=list(st["hold"]), now=now)
                    return
                # the pod came back before the shrink landed (or the
                # hold overran): release at full world
                self._write_release(st, world=self.num_pods,
                                    pods=list(range(self.num_pods)),
                                    now=now)
                return
            if restarting and not overrun:
                return  # held until the pod recovers (or the budget)
            self._write_release(st, world=self.num_pods,
                                pods=list(range(self.num_pods)), now=now)
        else:  # released (elastic shrink): wait for the pod to rejoin
            if restarting and not overrun:
                return
            self._hold = None
            if self.pod_cfg.elastic_pods and self._plan.world < self.num_pods:
                # grow back: hold the current members, then release at
                # full width next ticks
                healthy = [p for p in range(self.num_pods)
                           if self._threads[p].is_alive()]
                self._write_hold([p for p in healthy
                                  if p in self._plan.ranks], now,
                                 reason="rejoin")

    def _write_release(self, st: dict, world: int, pods: list[int],
                       now: float) -> None:
        steps = [hb.step for p in st["hold"]
                 if (hb := self._monitors[p].heartbeat) is not None]
        barrier = max(steps) if steps else 0
        v = self._plan.version + 1
        self.flightrec.emit("pod_release", version=v, world=world,
                            barrier=barrier)
        self._plan = PodPlan(
            version=v, phase=PLAN_STEADY, world=world,
            ranks={p: r for r, p in enumerate(sorted(pods))},
            barrier_step=barrier, epoch=self.epoch, hold=(),
            num_pods=self.num_pods)
        write_pod_plan(self.workdir, self._plan)
        self._h_barrier.observe(max(now - st["t0"], 0.0))
        if world < self.num_pods or st["reason"] == "rejoin":
            self._hold = dict(st, stage="released", version=v) \
                if world < self.num_pods else None
        else:
            self._hold = None
        logger.warning("podfleet: cross-pod release v%d (world %d, barrier "
                       "step %d)", v, world, barrier)
