"""Slotted KV cache — the resident state of the decode engine.

Layout: one pair of buffers for the whole model, layers stacked on the
leading axis::

    k, v : [num_layers, num_slots, num_heads, max_len, head_dim]

``num_slots`` is the fixed decode-batch width (continuous batching keeps
it full by admitting a queued request the moment a slot frees up —
scheduler.py); ``max_len`` is the per-slot token budget. Each slot is a
ring-less append buffer with a per-sequence write index owned by the
engine: a slot's positions ``0..written-1`` hold real tokens and
everything above is stale garbage that ``cached_attention``'s
``j <= q_pos`` predicate masks, so slot reuse needs NO zeroing — a new
request's prefill simply overwrites from position 0.

Sharding: the cache is a pytree like any other, so the rules of
parallel/sharding.py apply unchanged (docs/serving.md): the ``heads``
dim shards over ``model`` exactly as the attention weights do under
TP_RULES (a TP shard holds the K/V of its own heads — no gather), and
the ``slots`` dim shards over the batch axes ``(data, fsdp)`` like any
input batch. ``CACHE_LOGICAL`` names the dims; ``cache_specs`` maps them
through a logical-rule table.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..models.transformer import TransformerConfig
from ..parallel import sharding


@dataclasses.dataclass
class KVCache:
    """k/v: [num_layers, num_slots, num_heads, max_len, head_dim]."""

    k: jax.Array
    v: jax.Array

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


jax.tree_util.register_dataclass(
    KVCache, data_fields=["k", "v"], meta_fields=[]
)

#: Logical dim names of each cache buffer, resolvable by the same rule
#: tables that place the model weights (sharding.spec_from_logical).
CACHE_LOGICAL = ("layers", "batch", "heads", "len", "kv")


def init_cache(
    cfg: TransformerConfig,
    num_slots: int,
    max_len: int | None = None,
    dtype: str | jnp.dtype | None = None,
) -> KVCache:
    """Zero-filled cache for ``cfg``. ``max_len`` defaults to the model's
    context window; ``dtype`` to the model compute dtype (bf16 on TPU —
    halving cache HBM is usually the right serving trade; tests pin
    float32 for exact parity with the uncached forward)."""
    M = cfg.max_len if max_len is None else max_len
    if M > cfg.max_len:
        raise ValueError(
            f"cache max_len={M} exceeds the model context window "
            f"(cfg.max_len={cfg.max_len}: pos_embed has no row for it)"
        )
    dt = jnp.dtype(cfg.dtype if dtype is None else dtype)
    shape = (cfg.num_layers, num_slots, cfg.num_heads, M, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))


def cache_specs(rules: sharding.LogicalRules | None = None) -> KVCache:
    """PartitionSpec pytree for the cache under ``rules`` (default
    TP_RULES: heads → ``model``, slots → ``(data, fsdp)``). Feed to
    ``sharding.shard_tree`` / ``jax.jit`` in/out shardings."""
    rules = sharding.TP_RULES if rules is None else rules
    spec = sharding.spec_from_logical(CACHE_LOGICAL, rules)
    return KVCache(k=spec, v=spec)


def shard_cache(
    cache: KVCache, mesh, rules: sharding.LogicalRules | None = None
) -> KVCache:
    """Place the cache on a mesh per ``cache_specs`` (device_put)."""
    return sharding.shard_tree(cache, mesh, cache_specs(rules))
