"""KV caches — the resident state of the decode engine.

Two layouts live here (docs/serving.md):

- **Paged** (the default): a fixed pool of KV blocks (``PagedKVCache``)
  plus host-side free/used accounting with copy-on-write refcounts and
  a shared-prefix cache (``BlockAllocator``). A resident request costs
  ``ceil(tokens / block_size)`` blocks instead of a dense ``max_len``
  row, and requests sharing a common prefix map the same physical
  blocks until their first divergent write.
- **Slot-dense** (``KVCache``, the exact-parity fallback): the PR-1
  layout described below, kept bit-for-bit for parity testing and as
  the ``ServeEngine(paged=False)`` escape hatch.

Dense layout: one pair of buffers for the whole model, layers stacked
on the leading axis::

    k, v : [num_layers, num_slots, num_heads, max_len, head_dim]

``num_slots`` is the fixed decode-batch width (continuous batching keeps
it full by admitting a queued request the moment a slot frees up —
scheduler.py); ``max_len`` is the per-slot token budget. Each slot is a
ring-less append buffer with a per-sequence write index owned by the
engine: a slot's positions ``0..written-1`` hold real tokens and
everything above is stale garbage that ``cached_attention``'s
``j <= q_pos`` predicate masks, so slot reuse needs NO zeroing — a new
request's prefill simply overwrites from position 0.

Sharding: the cache is a pytree like any other, so the rules of
parallel/sharding.py apply unchanged (docs/serving.md): the ``heads``
dim shards over ``model`` exactly as the attention weights do under
TP_RULES (a TP shard holds the K/V of its own heads — no gather), and
the ``slots`` dim shards over the batch axes ``(data, fsdp)`` like any
input batch. ``CACHE_LOGICAL`` names the dims; ``cache_specs`` maps them
through a logical-rule table.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models.transformer import TransformerConfig
from ..parallel import mesh as mesh_lib
from ..parallel import sharding


@dataclasses.dataclass
class KVCache:
    """k/v: [num_layers, num_slots, num_heads, max_len, head_dim]."""

    k: jax.Array
    v: jax.Array

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


jax.tree_util.register_dataclass(
    KVCache, data_fields=["k", "v"], meta_fields=[]
)

#: Logical dim names of each cache buffer, resolvable by the same rule
#: tables that place the model weights (sharding.spec_from_logical).
CACHE_LOGICAL = ("layers", "batch", "heads", "len", "kv")

#: Partition-rules table for the dense cache (the default layout of
#: ``cache_specs``): heads → ``model`` exactly as the attention weights
#: under TRANSFORMER_RULES (a TP shard holds the K/V of its own heads),
#: slots → the batch axes like any input batch. Equal by construction
#: to ``spec_from_logical(CACHE_LOGICAL, TP_RULES)`` — pinned by
#: tests/test_serve.py::test_cache_specs_match_rules_table.
KV_CACHE_RULES = sharding.partition_rules(
    "serve-kv-cache",
    ((r"^(k|v)$",
      P(None, (mesh_lib.DATA, mesh_lib.FSDP), mesh_lib.MODEL,
        None, None)),),
    coverage=("k", "v"),
)


def init_cache(
    cfg: TransformerConfig,
    num_slots: int,
    max_len: int | None = None,
    dtype: str | jnp.dtype | None = None,
) -> KVCache:
    """Zero-filled cache for ``cfg``. ``max_len`` defaults to the model's
    context window; ``dtype`` to the model compute dtype (bf16 on TPU —
    halving cache HBM is usually the right serving trade; tests pin
    float32 for exact parity with the uncached forward)."""
    M = cfg.max_len if max_len is None else max_len
    if M > cfg.max_len:
        raise ValueError(
            f"cache max_len={M} exceeds the model context window "
            f"(cfg.max_len={cfg.max_len}: pos_embed has no row for it)"
        )
    dt = jnp.dtype(cfg.dtype if dtype is None else dtype)
    shape = (cfg.num_layers, num_slots, cfg.num_heads, M, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))


def cache_specs(rules: sharding.LogicalRules | None = None) -> KVCache:
    """PartitionSpec pytree for the cache. The default is the
    KV_CACHE_RULES partition-rules table (heads → ``model``, slots →
    ``(data, fsdp)``) resolved under the engine's strict coverage
    contract; passing explicit logical ``rules`` keeps the
    spec_from_logical escape hatch (tests re-derive the layout from
    custom tables). Feed to ``sharding.shard_tree`` / ``jax.jit``
    in/out shardings."""
    if rules is None:
        return sharding.match_partition_rules(
            KV_CACHE_RULES, KVCache(k=0, v=0)
        )
    spec = sharding.spec_from_logical(CACHE_LOGICAL, rules)
    return KVCache(k=spec, v=spec)


def shard_cache(
    cache: KVCache, mesh, rules: sharding.LogicalRules | None = None
) -> KVCache:
    """Place the cache on a mesh per ``cache_specs`` (device_put)."""
    return sharding.shard_tree(cache, mesh, cache_specs(rules))


# ---------------------------------------------------------------------------
# Paged cache: fixed block pool + host-side block tables (docs/serving.md
# "Paged KV cache"). The dense KVCache above stays as the exact-parity
# fallback (ServeEngine(paged=False)).
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PagedKVCache:
    """k/v: [num_layers, num_blocks, num_heads, block_size, head_dim].

    The device side of the paged cache is ONLY this pool of physical
    blocks — no slot dimension. Which blocks belong to which request is
    the per-slot block table, a small host-owned int32 array handed to
    every jit call (``models.Transformer(..., block_table=)``); free/
    used accounting and copy-on-write refcounts live in the host-side
    ``BlockAllocator``. A resident request therefore costs
    ``ceil(tokens / block_size)`` blocks instead of a dense ``max_len``
    row, and requests sharing a common prefix map the SAME physical
    blocks until their first divergent write."""

    k: jax.Array
    v: jax.Array

    @property
    def num_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes

    def block_nbytes(self) -> int:
        """Bytes of ONE physical block across both buffers and all
        layers — the unit of the bench's KV-per-request accounting."""
        return self.nbytes() // self.num_blocks


jax.tree_util.register_dataclass(
    PagedKVCache, data_fields=["k", "v"], meta_fields=[]
)

#: Logical dims of the pool. ``kv_blocks`` has no rule-table entry, so
#: it resolves to None (replicated): blocks are shared across requests,
#: and a request's blocks must not scatter over the batch axes. Heads
#: still shard over ``model`` exactly like the dense cache.
PAGED_CACHE_LOGICAL = ("layers", "kv_blocks", "heads", "len", "kv")

#: Partition-rules table for the block pool (default of
#: ``paged_cache_specs``): heads → ``model``, blocks REPLICATED — a
#: request's blocks must not scatter over the batch axes. Pinned to the
#: logical-rules derivation by
#: tests/test_serve.py::test_paged_cache_specs_match_rules_table.
PAGED_KV_CACHE_RULES = sharding.partition_rules(
    "serve-paged-kv-cache",
    ((r"^(k|v)$", P(None, None, mesh_lib.MODEL, None, None)),),
    coverage=("k", "v"),
)


def init_paged_cache(
    cfg: TransformerConfig,
    num_blocks: int,
    block_size: int,
    dtype: str | jnp.dtype | None = None,
) -> PagedKVCache:
    """Zero-filled block pool for ``cfg``. Unlike the dense cache there
    is no per-slot ``max_len`` row: capacity is simply
    ``num_blocks * block_size`` tokens shared by every resident
    request."""
    if num_blocks < 1:
        raise ValueError("num_blocks must be >= 1")
    if block_size < 1:
        raise ValueError("block_size must be >= 1")
    dt = jnp.dtype(cfg.dtype if dtype is None else dtype)
    shape = (cfg.num_layers, num_blocks, cfg.num_heads, block_size,
             cfg.head_dim)
    return PagedKVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))


def paged_cache_specs(
    rules: sharding.LogicalRules | None = None,
) -> PagedKVCache:
    """PartitionSpec pytree for the block pool (heads → ``model``,
    blocks replicated) — PAGED_KV_CACHE_RULES by default, explicit
    logical ``rules`` as the escape hatch."""
    if rules is None:
        return sharding.match_partition_rules(
            PAGED_KV_CACHE_RULES, PagedKVCache(k=0, v=0)
        )
    spec = sharding.spec_from_logical(PAGED_CACHE_LOGICAL, rules)
    return PagedKVCache(k=spec, v=spec)


def shard_paged_cache(
    cache: PagedKVCache, mesh, rules: sharding.LogicalRules | None = None
) -> PagedKVCache:
    """Place the pool on a mesh per ``paged_cache_specs``."""
    return sharding.shard_tree(cache, mesh, paged_cache_specs(rules))


class NoFreeBlocks(RuntimeError):
    """The pool is exhausted and nothing is evictable — the engine's
    cue to preempt a resident request (backpressure, not corruption)."""


class BlockAllocator:
    """Host-side free/used accounting for the block pool — plain
    Python, jax-free, so every invariant (used + free == pool size,
    refcounts hit zero, no leaked blocks) is testable with no device.

    Three kinds of ownership, all through one refcount array:

    - a resident request holds one ref on every block in its table;
    - the **prefix cache** holds one ref on each registered full block
      (``register_prefix``), so a popular system-prompt prefix survives
      the request that wrote it; entries are LRU-evicted when ``alloc``
      finds the free list empty (``evictions`` counts them);
    - **partially filled tail blocks** are registered weakly (no ref,
      validated by a per-block generation counter), so an identical
      prompt can map the same tail block — the copy-on-write case: the
      first APPEND into a block with refcount > 1 must copy it
      (``ensure `` via the engine's COW path), because the writer and
      the sharers diverge at that position.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # pop() hands out 0, 1, 2, ... — deterministic block placement
        self._free = list(range(num_blocks - 1, -1, -1))
        self._ref = [0] * num_blocks
        #: bumped on every alloc — stale weak (partial) registrations
        #: carry the generation they were made under and are pruned lazily
        self._gen = [0] * num_blocks
        #: full-block prefix cache: token prefix (length k*block_size,
        #: as a tuple) → physical block id of block k-1. Insertion order
        #: doubles as LRU (move_to_end on hit).
        self._prefix: dict[tuple[int, ...], int] = {}
        #: weak partial-tail registrations: full-block prefix → list of
        #: (tail_content, block_id, generation)
        self._partial: dict[tuple[int, ...],
                            list[tuple[tuple[int, ...], int, int]]] = {}
        #: prefix-cache blocks evicted under pressure (feeds the
        #: kv_block_evictions_total counter)
        self.evictions = 0
        #: copy-on-write block copies performed (engine bumps this when
        #: it resolves a shared-block write)
        self.cow_copies = 0

    # -- accounting --------------------------------------------------------

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def refcount(self, bid: int) -> int:
        return self._ref[bid]

    def evictable(self) -> int:
        """Prefix-cache blocks held ONLY by the cache (refcount 1) —
        freeable on demand, so admission may count them as capacity."""
        return sum(1 for bid in self._prefix.values()
                   if self._ref[bid] == 1)

    # -- alloc / free ------------------------------------------------------

    def alloc(self) -> int:
        """Hand out a free block (refcount 1). When the free list is
        empty, evict least-recently-used prefix-cache entries whose
        block nothing else holds; raises ``NoFreeBlocks`` when even
        that finds nothing."""
        if not self._free:
            self._evict_cached()
        if not self._free:
            raise NoFreeBlocks(
                f"all {self.num_blocks} KV blocks are referenced and no "
                f"prefix-cache entry is evictable"
            )
        bid = self._free.pop()
        self._ref[bid] = 1
        self._gen[bid] += 1
        return bid

    def incref(self, bid: int) -> None:
        if self._ref[bid] < 1:
            raise ValueError(f"incref on free block {bid}")
        self._ref[bid] += 1

    def decref(self, bid: int) -> bool:
        """Drop one reference; returns True when the block was freed."""
        if self._ref[bid] < 1:
            raise ValueError(f"decref on free block {bid}")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)
            return True
        return False

    def release_tail(self, blocks: list[int], keep: int) -> None:
        """Speculation rollback: drop ownership of every block past the
        first ``keep`` — a refcount/length edit, never a data copy. Pops
        ``blocks`` in place so the caller's per-slot block list stays
        the single source of truth; decref's double-free tripwire still
        guards each drop (a rejected suffix must not free a block the
        prefix cache or another slot co-owns more times than this slot
        held it)."""
        if keep < 0:
            raise ValueError(f"keep must be >= 0, got {keep}")
        while len(blocks) > keep:
            self.decref(blocks.pop())

    def _evict_cached(self) -> None:
        """LRU-evict prefix-cache entries whose block only the cache
        holds, until one block is actually freed."""
        for key in list(self._prefix):
            bid = self._prefix[key]
            if self._ref[bid] == 1:
                del self._prefix[key]
                self.evictions += 1
                if self.decref(bid):
                    return

    # -- prefix reuse ------------------------------------------------------

    def match_prefix(
        self, tokens: tuple[int, ...] | list[int]
    ) -> tuple[list[int], int]:
        """Longest reusable prefix of ``tokens``: full cached blocks
        first, then optionally one weakly-registered partial tail
        block. Returns ``(block_ids, matched_tokens)`` with one ref
        taken on every returned block (the caller now co-owns them)."""
        tokens = tuple(int(t) for t in tokens)
        bs = self.block_size
        blocks: list[int] = []
        matched = 0
        while matched + bs <= len(tokens):
            key = tokens[: matched + bs]
            bid = self._prefix.get(key)
            if bid is None:
                break
            self._prefix[key] = self._prefix.pop(key)  # LRU touch
            self.incref(bid)
            blocks.append(bid)
            matched += bs
        # partial tail: a registered block whose content agrees with the
        # remaining tokens on their common prefix
        tail = tokens[matched:]
        if tail:
            hit = self._lookup_partial(tokens[:matched], tail)
            if hit is not None:
                bid, common = hit
                self.incref(bid)
                blocks.append(bid)
                matched += common
        return blocks, matched

    def peek_match(self, tokens: tuple[int, ...] | list[int]) -> int:
        """``match_prefix`` without taking refs — how many FULL blocks
        admission could reuse (the gate's conservative estimate)."""
        tokens = tuple(int(t) for t in tokens)
        bs, n = self.block_size, 0
        while (n + 1) * bs <= len(tokens) \
                and tokens[: (n + 1) * bs] in self._prefix:
            n += 1
        return n

    def _lookup_partial(
        self, full_prefix: tuple[int, ...], tail: tuple[int, ...]
    ) -> tuple[int, int] | None:
        cands = self._partial.get(full_prefix)
        if not cands:
            return None
        live = []
        for content, bid, gen in cands:
            if self._ref[bid] < 1 or self._gen[bid] != gen:
                continue  # block was freed/reallocated: stale entry
            live.append((content, bid, gen))
        if len(live) != len(cands):
            if live:
                self._partial[full_prefix] = live
            else:
                del self._partial[full_prefix]
        best: tuple[int, int] | None = None
        for content, bid, _gen in live:
            common = 0
            for a, b in zip(content, tail):
                if a != b:
                    break
                common += 1
            if common > 0 and (best is None or common > best[1]):
                best = (bid, common)
        return best

    def register_prefix(
        self, tokens: tuple[int, ...] | list[int], blocks: list[int]
    ) -> None:
        """Publish a prefilled prompt's blocks for reuse: each FULL
        block enters the prefix cache (one cache ref, survives the
        request), a partially filled tail block is registered weakly
        (valid only while the block lives). Re-registering content that
        is already cached is a no-op — no double refs."""
        tokens = tuple(int(t) for t in tokens)
        bs = self.block_size
        n_full = len(tokens) // bs
        for j in range(min(n_full, len(blocks))):
            key = tokens[: (j + 1) * bs]
            if key in self._prefix:
                continue
            bid = blocks[j]
            self.incref(bid)
            self._prefix[key] = bid
        tail = tokens[n_full * bs:]
        if tail and len(blocks) > n_full:
            bid = blocks[n_full]
            key = tokens[: n_full * bs]
            entry = (tail, bid, self._gen[bid])
            cands = self._partial.setdefault(key, [])
            if entry not in cands:
                cands.append(entry)
            # weak entries are pruned lazily on lookup, which never
            # happens for prompts no one repeats — sweep when the map
            # outgrows the pool so host memory stays bounded
            if sum(len(c) for c in self._partial.values()) \
                    > max(64, 2 * self.num_blocks):
                self._prune_partials()

    def _prune_partials(self) -> None:
        """Drop every stale weak entry (block freed or reallocated)."""
        for key in list(self._partial):
            live = [(c, bid, gen) for c, bid, gen in self._partial[key]
                    if self._ref[bid] >= 1 and self._gen[bid] == gen]
            if live:
                self._partial[key] = live
            else:
                del self._partial[key]

    def note_write(self, bid: int, offset: int) -> None:
        """The sole owner is about to write block ``bid`` in place from
        ``offset`` on: weak partial entries claiming content AT or past
        that offset would describe overwritten K/V — drop them. (An
        append past an entry's registered fill leaves it valid; a COW
        writer gets a fresh block and never invalidates the original.)
        The engine calls this for every block a prefill chunk or decode
        write touches, so the weak registry can never serve stale
        content even if the engine's COW ordering ever changes. Cost:
        nothing when the registry is empty (reuse off, or no partial
        prompts), else one scan of a map the register-time sweep keeps
        bounded at ``max(64, 2 * num_blocks)`` entries."""
        if not self._partial:
            return
        for key in list(self._partial):
            kept = [(c, b, g) for c, b, g in self._partial[key]
                    if not (b == bid and len(c) > offset)]
            if kept:
                self._partial[key] = kept
            else:
                del self._partial[key]

    def release_cached(self, bid: int) -> bool:
        """Drop every prefix-cache ref on ``bid`` (full-block entries;
        weak partial entries hold no ref and die by generation).
        Returns True when an entry was removed. The engine's last
        resort when a copy-on-write target cannot be allocated: if the
        only other holder of a block is the cache itself, un-caching it
        makes the writer sole owner, who then writes in place — no copy
        needed."""
        removed = False
        for key in [k for k, b in self._prefix.items() if b == bid]:
            del self._prefix[key]
            self.evictions += 1
            self.decref(bid)
            removed = True
        return removed

    def flush_prefix_cache(self) -> int:
        """Drop every cached prefix ref (shutdown / leak audits):
        afterwards only resident requests hold blocks. Returns the
        number of blocks freed outright."""
        freed = 0
        for bid in self._prefix.values():
            freed += bool(self.decref(bid))
        self._prefix.clear()
        self._partial.clear()
        return freed
