"""Serve replica worker — one fleet replica as a real process.

Run as ``python -m distributed_tensorflow_tpu.serve.replica`` by the
serve-fleet chaos rig (tools/chaos_smoke.py); the supervisor talks to it
through ``serve.fleet.SubprocessReplica``. One process = one paged
``ServeEngine`` plus the fleet-worker observability kit training workers
carry (tests/chaos_worker.py): a heartbeat under the fleet workdir
(incarnation-fenced, pulsed so liveness ticks while idle), periodic
telemetry snapshots, and an identity-stamped flight-recorder dump on
every clean exit — the worker half of the merged serve-fleet postmortem.

Protocol (the file-based data plane, serve/fleet.py):

- **Inbox.** The supervisor atomically writes one JSON payload per
  dispatched request under ``replica-<i>/inbox/``; the replica ingests
  them in sequence order, emits the ``serve_route`` ACK for each (AFTER
  reading the payload, BEFORE any observable effect — the same
  emission-ordering rule as ``elastic_hold``, making the ACK a sound
  clock anchor: router dispatch happens-before replica ingest), and
  submits to the engine at the payload's lane priority.
- **Events stream.** Generated tokens and finishes append to
  ``replica-<i>/events-i<k>.jsonl`` (append-only, flushed per loop; the
  client tolerates a torn tail line). The terminal record is the
  ``drained`` leak audit: after ``drain()`` the block allocator must be
  all-free on every SURVIVING replica — a SIGKILLed one never writes
  it, which is the point.
- **Drain.** A ``DRAIN`` sentinel (or SIGTERM) stops ingestion, decodes
  the residents to completion, writes the audit, exports a final
  snapshot, dumps the flight recorder, and exits 0. Any other exit is
  a death the supervisor requeues around.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import time

logger = logging.getLogger(__name__)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--workdir", required=True,
                    help="fleet workdir (heartbeats, snapshots, inbox)")
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--incarnation", type=int, default=0)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--blocks", type=int, default=None)
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0,
                    help="model weights seed — IDENTICAL across replicas, "
                         "so a re-prefilled stream continues bit-identically "
                         "on any survivor")
    ap.add_argument("--pulse-s", type=float, default=0.2)
    ap.add_argument("--idle-sleep-s", type=float, default=0.005)
    args = ap.parse_args(argv)

    from distributed_tensorflow_tpu.models import transformer as tfm
    from distributed_tensorflow_tpu.obs import fleetview
    from distributed_tensorflow_tpu.obs import flightrec as fr
    from distributed_tensorflow_tpu.obs.registry import default_registry
    from distributed_tensorflow_tpu.obs.reqtrace import ReqTrace
    from distributed_tensorflow_tpu.resilience import liveness
    from distributed_tensorflow_tpu.serve import fleet as serve_fleet
    from distributed_tensorflow_tpu.serve.engine import ServeEngine

    rec = fr.default_recorder()
    writer = liveness.HeartbeatWriter(
        liveness.heartbeat_path(args.workdir, args.index),
        incarnation=args.incarnation, pulse_interval_s=args.pulse_s)
    exporter = fleetview.SnapshotExporter(
        fleetview.fleetsnap_path(args.workdir, args.index),
        worker=args.index, incarnation=args.incarnation,
        min_interval_s=0.5)

    # the tiny CPU-runnable decoder every serve rig shares
    # (tools/bench_serve.py); weights are seed-deterministic, so every
    # replica of one fleet serves the same model
    cfg = tfm.TransformerConfig(
        vocab_size=256, max_len=128, num_layers=2, d_model=64, num_heads=4,
        d_ff=128, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    # this replica's half of the request ledger (obs/reqtrace.py): one
    # span record per rid this incarnation served; src carries the
    # (worker, incarnation) identity into the merged timeline
    reqtrace = ReqTrace(src=f"w{args.index}i{args.incarnation}")
    engine = ServeEngine.with_random_params(
        cfg, seed=args.seed, num_slots=args.slots, paged=True,
        block_size=args.block_size, num_blocks=args.blocks,
        prefill_chunk=args.prefill_chunk, registry=default_registry(),
        reqtrace=reqtrace)
    bridge = serve_fleet.EngineBridge(engine)

    inbox = serve_fleet.replica_inbox_dir(args.workdir, args.index)
    os.makedirs(inbox, exist_ok=True)
    sentinel = serve_fleet.drain_path(args.workdir, args.index)
    events_path = serve_fleet.replica_events_path(
        args.workdir, args.index, args.incarnation)

    stop = {"drain": False}

    def _sigterm(signum, frame):
        stop["drain"] = True

    signal.signal(signal.SIGTERM, _sigterm)

    def dump_flightrec() -> None:
        base = os.path.join(
            os.path.abspath(os.path.expanduser(args.workdir)),
            f"flightrec-w{args.index}i{args.incarnation}")
        # never clobber (chaos_worker's rule): two dumps claiming one
        # (worker, incarnation) slot must fail the merge LOUDLY as a
        # label collision, not silently replace the first story
        path, n = f"{base}.jsonl", 0
        while os.path.exists(path):
            n += 1
            path = f"{base}-{n}.jsonl"
        rec.dump(path, reason="serve_replica_exit",
                 extra={"worker": args.index,
                        "incarnation": args.incarnation})

    trace_path = os.path.join(
        os.path.abspath(os.path.expanduser(args.workdir)),
        f"reqtrace-w{args.index}i{args.incarnation}.jsonl")
    trace_seq = {"dumped": -1}

    def dump_reqtrace(reason: str) -> None:
        """Atomically (re)write this incarnation's trace dump when the
        ledger changed. Called BEFORE token events are appended to the
        events stream, so any token the router observed has its trace
        transitions already durable — a SIGKILLed victim's spans for the
        killed request survive in its last dump."""
        if reqtrace.seq == trace_seq["dumped"]:
            return
        trace_seq["dumped"] = reqtrace.seq
        reqtrace.dump(trace_path, reason=reason,
                      extra={"worker": args.index,
                             "incarnation": args.incarnation})

    tokens_out = 0
    with open(events_path, "a") as out:  # append-only event stream

        def emit(events) -> None:
            nonlocal tokens_out
            for ev in events:
                if ev.get("kind") == "token":
                    tokens_out += 1
                out.write(json.dumps(ev) + "\n")
            if events:
                out.flush()

        emit([{"kind": "ready", "pid": os.getpid(),
               "incarnation": args.incarnation}])
        writer.beat(phase="serve")
        while not stop["drain"] and not os.path.exists(sentinel):
            for name in sorted(os.listdir(inbox)):
                path = os.path.join(inbox, name)
                try:
                    with open(path) as f:
                        payload = json.load(f)
                except (OSError, ValueError) as e:
                    logger.warning("replica %d: unreadable dispatch %s "
                                   "(%s); skipping", args.index, name, e)
                    os.remove(path)
                    continue
                # the ingest ACK — after the read, before any effect:
                # router dispatch strictly happens-before this emit, so
                # the merge may anchor on the rid pair
                rec.emit("serve_route", rid=payload["rid"],
                         lane=payload.get("lane"), replica=args.index)
                bridge.accept(payload)
                os.remove(path)
            busy = bridge.busy
            events = bridge.pump()
            dump_reqtrace("serve_replica_pump")  # durable before emit
            emit(events)
            writer.beat(step=tokens_out)
            try:
                exporter.export(step=tokens_out)
            except OSError:
                logger.exception("replica %d: snapshot export failed",
                                 args.index)
            if not busy:
                time.sleep(args.idle_sleep_s)
        events = bridge.drain()
        dump_reqtrace("serve_replica_drain")
        emit(events)
    try:
        exporter.export(step=tokens_out, force=True)
    except OSError:
        logger.exception("replica %d: final snapshot export failed",
                         args.index)
    dump_flightrec()
    writer.finish("done")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
