"""Prefill and single-token decode steps over the slotted KV cache.

The two jit units of the serving engine:

- **prefill** — run one request's prompt through the model with a
  single-slot view of the cache (gather the slot's [L,1,H,M,D] rows,
  apply, scatter back). Writes K/V for positions ``0..P-1`` and returns
  the next-token logits from the last REAL prompt position (prompts are
  padded to a bucket length so each bucket compiles once; padded rows
  produce garbage logits that are never read, and the garbage K/V they
  write above ``P`` stays masked until real tokens overwrite it).
- **decode_step** — one token for EVERY slot at once ([num_slots, 1]
  inputs at per-slot write positions). Idle slots decode garbage that is
  simply never delivered — uniform shapes keep ONE compiled program hot
  regardless of which subset of slots is live, which is the continuous-
  batching contract: admission/eviction never triggers a recompile.

Numerics: the cache path runs the same f32 masked softmax(QKᵀ)V as the
dense reference (ops.attention.cached_attention docstring), so cached
decode logits match the uncached full-context forward — asserted to
rtol 1e-4 and 64-step greedy equality in tests/test_serve.py.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..models.transformer import Transformer
from .kv_cache import KVCache, PagedKVCache


def prefill(
    model: Transformer,
    params,
    cache: KVCache,
    slot: jax.Array,
    tokens: jax.Array,
    length: jax.Array,
) -> tuple[jax.Array, KVCache]:
    """Prefill one slot. ``tokens`` [P] int32 (padded prompt), ``length``
    the real prompt length, ``slot`` the target cache row. Returns
    (next-token logits [vocab] f32, updated cache)."""
    P = tokens.shape[0]
    row = lambda buf: lax.dynamic_slice_in_dim(buf, slot, 1, axis=1)
    slot_cache = dataclasses.replace(cache, k=row(cache.k), v=row(cache.v))
    pos = jnp.arange(P, dtype=jnp.int32)[None]
    logits, slot_cache = model.apply(
        {"params": params}, tokens[None], kv_cache=slot_cache,
        decode_pos=pos,
    )
    put = lambda buf, upd: lax.dynamic_update_slice_in_dim(
        buf, upd, slot, axis=1
    )
    new_cache = dataclasses.replace(
        cache, k=put(cache.k, slot_cache.k), v=put(cache.v, slot_cache.v)
    )
    return logits[0, length - 1], new_cache


def decode_step(
    model: Transformer,
    params,
    cache: KVCache,
    tokens: jax.Array,
    lengths: jax.Array,
) -> tuple[jax.Array, KVCache]:
    """One decode step for all slots. ``tokens`` [num_slots] — each
    slot's most recent token; ``lengths`` [num_slots] — each slot's
    write index (= tokens already in its cache). Returns (next-token
    logits [num_slots, vocab] f32, updated cache)."""
    logits, cache = model.apply(
        {"params": params}, tokens[:, None], kv_cache=cache,
        decode_pos=lengths[:, None],
    )
    return logits[:, 0], cache


def jit_prefill(model: Transformer):
    """Compiled prefill; one compile per (prompt-bucket, cache shape).

    The cache argument is DONATED: XLA aliases it into the returned
    cache, so a step updates the resident buffers in place instead of
    paying a full cache copy (and 2× cache HBM) per call — same reason
    train/step.py donates the train state. Callers must rebind
    (``logits, cache = fn(params, cache, ...)``), never reuse the old
    pytree; the engine already does."""
    return jax.jit(partial(prefill, model), donate_argnums=(1,))


def jit_decode_step(model: Transformer):
    """Compiled decode step; one compile per cache shape. The cache is
    donated (see jit_prefill)."""
    return jax.jit(partial(decode_step, model), donate_argnums=(1,))


# ---------------------------------------------------------------------------
# Paged path (docs/serving.md "Paged KV cache"): the pool + block-table
# analogs of the two jit units above, plus the COW block copy. The dense
# functions above remain the exact-parity fallback.
# ---------------------------------------------------------------------------


def paged_prefill_chunk(
    model: Transformer,
    params,
    cache: PagedKVCache,
    table_row: jax.Array,
    tokens: jax.Array,
    start: jax.Array,
    length: jax.Array,
) -> tuple[jax.Array, PagedKVCache]:
    """One fixed-size prefill chunk of ONE request: ``tokens`` [C] int32
    (chunk, zero-padded past ``length``) at absolute positions
    ``start .. start+length-1``, scattered through ``table_row``
    [max_blocks]. Padded rows get a past-the-table sentinel position so
    their K/V writes are dropped (ops.paged_append_kv). Returns the
    next-token logits at the chunk's last REAL position — only the
    final chunk's caller reads them — and the updated pool.

    Chunks are a fixed shape, unlike the dense path's per-bucket
    prefill programs — one compiled program per TABLE-width bucket
    covers every prompt length (the engine trims ``table_row`` to the
    power-of-two width covering the slot's live blocks, so short
    prompts attend far fewer positions than ``max_blocks``)."""
    C = tokens.shape[0]
    sentinel = table_row.shape[0] * cache.block_size
    idx = jnp.arange(C, dtype=jnp.int32)
    pos = jnp.where(idx < length, start + idx, sentinel)
    logits, cache = model.apply(
        {"params": params}, tokens[None], kv_cache=cache,
        decode_pos=pos[None], block_table=table_row[None],
    )
    return logits[0, length - 1], cache


def paged_decode_step(
    model: Transformer,
    params,
    cache: PagedKVCache,
    block_tables: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
) -> tuple[jax.Array, PagedKVCache]:
    """One decode step for all slots over the block pool. ``lengths``
    [num_slots] is each slot's write position; idle and mid-prefill
    slots carry a past-the-table sentinel instead, so their garbage
    token writes NOTHING (a mid-prefill slot's frontier may sit in a
    COW-shared block that a stray write must not touch)."""
    logits, cache = model.apply(
        {"params": params}, tokens[:, None], kv_cache=cache,
        decode_pos=lengths[:, None], block_table=block_tables,
    )
    return logits[:, 0], cache


def paged_verify_step(
    model: Transformer,
    params,
    cache: PagedKVCache,
    block_tables: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
) -> tuple[jax.Array, PagedKVCache]:
    """Speculative verify: one chunked-prefill-shaped step over EVERY
    slot at once. ``tokens`` [num_slots, K+1] — each slot's newest
    sampled token followed by its K drafted tokens; ``positions``
    [num_slots, K+1] their absolute cache positions (row ``i`` of a
    slot's logits conditions, causally, on everything at or before
    ``positions[slot, i]`` — identical math to running K+1 sequential
    decode steps). Idle slots, mid-prefill slots, and unused draft rows
    carry the past-the-table sentinel so their K/V writes are dropped.

    Returns logits [num_slots, K+1, vocab] — the accept/reject rule
    (sampling.spec_verify_*) reads them on the host; rejected suffixes
    roll back via the block table (a refcount/length edit, not a
    device copy). One compiled program per K, shared by every prompt
    and every acceptance pattern."""
    logits, cache = model.apply(
        {"params": params}, tokens, kv_cache=cache,
        decode_pos=positions, block_table=block_tables,
    )
    return logits, cache


def copy_block(
    cache: PagedKVCache, src: jax.Array, dst: jax.Array
) -> PagedKVCache:
    """Copy-on-write resolution: duplicate physical block ``src`` into
    ``dst`` across every layer and both buffers, on device. The engine
    calls this (jit, donated) before the first divergent write into a
    block whose refcount is > 1."""
    return dataclasses.replace(
        cache,
        k=cache.k.at[:, dst].set(cache.k[:, src]),
        v=cache.v.at[:, dst].set(cache.v[:, src]),
    )


def jit_paged_prefill_chunk(model: Transformer):
    """Compiled paged prefill chunk; the pool is donated (in-place
    scatter, no per-chunk pool copy — see jit_prefill)."""
    return jax.jit(partial(paged_prefill_chunk, model), donate_argnums=(1,))


def jit_paged_decode_step(model: Transformer):
    """Compiled paged decode step; the pool is donated."""
    return jax.jit(partial(paged_decode_step, model), donate_argnums=(1,))


def jit_paged_verify_step(model: Transformer):
    """Compiled speculative verify step; the pool is donated. One
    compile per draft length K (tokens [num_slots, K+1])."""
    return jax.jit(partial(paged_verify_step, model), donate_argnums=(1,))


def jit_copy_block():
    """Compiled COW block copy; the pool is donated."""
    return jax.jit(copy_block, donate_argnums=(0,))


def prefill_bucket(length: int, *, minimum: int = 8) -> int:
    """Pad a prompt length to the next power of two (≥ ``minimum``): a
    handful of compiled prefill programs cover every prompt length, the
    classic bucketing trade against XLA's static shapes."""
    b = minimum
    while b < length:
        b *= 2
    return b
