"""Continuous-batching scheduler — deterministic, jax-free, CPU-testable.

Policy (the MLPerf lesson applied to serving: batching discipline, not
FLOPs, decides utilization — PAPERS.md):

- **FIFO admission.** Requests queue in submission order; the moment a
  decode slot frees, the head of the queue is admitted into it. No
  reordering, no priorities — fairness is positional.
- **Fixed decode-batch slots.** The decode batch is ``num_slots`` wide,
  always. The scheduler's job is to keep occupancy at 1.0 whenever the
  queue is non-empty (asserted by tools/bench_serve.py).
- **Evict on EOS / max-new / max-len.** A request leaves its slot the
  step it finishes: its own ``eos_id``, its ``max_new_tokens`` budget,
  or the slot's ``max_len`` cache budget (prompt + written tokens). The
  freed slot is re-admissible in the SAME engine step — prefill/decode
  interleaving with no idle step.

All state is plain Python (deque + list), so every invariant — no slot
leaks, FIFO order, eviction conditions — is testable with no model and
no device (tests/test_serve.py::test_scheduler_invariants).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Iterable

#: why a request finished
FINISH_EOS = "eos"
FINISH_MAX_NEW = "max_new_tokens"
FINISH_MAX_LEN = "max_len"


@dataclasses.dataclass
class Request:
    uid: int
    prompt: tuple[int, ...]
    max_new_tokens: int
    eos_id: int | None = None
    generated: list[int] = dataclasses.field(default_factory=list)
    finish_reason: str | None = None
    # lifecycle timestamps (scheduler clock), the raw material for the
    # serve latency metrics (docs/observability.md): queue wait =
    # t_admit - t_submit, TTFT = t_first_token - t_submit, per-token
    # decode latency = (t_finish - t_first_token) / (generated - 1).
    t_submit: float | None = None
    t_admit: float | None = None
    t_first_token: float | None = None
    t_finish: float | None = None

    @property
    def done(self) -> bool:
        return self.finish_reason is not None


class Scheduler:
    """FIFO continuous batching over ``num_slots`` decode slots, each
    with a ``max_len``-token KV budget (prompt + generated)."""

    def __init__(self, num_slots: int, max_len: int,
                 clock: Callable[[], float] = time.perf_counter):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.max_len = max_len
        self.clock = clock  # injectable for deterministic latency tests
        self.queue: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * num_slots
        self._next_uid = 0
        #: uid → Request, completion order. Retained until the caller
        #: collects results (ServeEngine.run / stream); long-lived
        #: servers must drain_finished() or history accumulates forever.
        self.finished: dict[int, Request] = {}

    # -- admission ---------------------------------------------------------

    def submit(
        self,
        prompt: Iterable[int],
        max_new_tokens: int = 32,
        eos_id: int | None = None,
    ) -> int:
        """Enqueue a request; returns its uid."""
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the per-slot cache "
                f"budget max_len={self.max_len}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = Request(self._next_uid, prompt, max_new_tokens, eos_id,
                      t_submit=self.clock())
        self._next_uid += 1
        self.queue.append(req)
        return req.uid

    def admit(self) -> list[tuple[int, Request]]:
        """Move queued requests into free slots, FIFO; returns the newly
        placed (slot, request) pairs — the engine prefills exactly
        these."""
        placed = []
        for slot in range(self.num_slots):
            if not self.queue:
                break
            if self.slots[slot] is None:
                req = self.queue.popleft()
                req.t_admit = self.clock()
                self.slots[slot] = req
                placed.append((slot, req))
        return placed

    # -- decode-loop bookkeeping -------------------------------------------

    def active_slots(self) -> list[int]:
        return [i for i, r in enumerate(self.slots) if r is not None]

    @property
    def occupancy(self) -> float:
        return len(self.active_slots()) / self.num_slots

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(
            r is not None for r in self.slots
        )

    def append_token(self, slot: int, token: int) -> Request | None:
        """Record a sampled token for the request in ``slot``; evict and
        return the request if this token finishes it, else None.

        Cache accounting: after ``g`` generated tokens, continuing
        requires writing token ``g`` at cache position ``P + g - 1``, so
        the slot is out of budget once ``P + g > max_len`` — the request
        keeps that final token (it was sampled from in-budget state) and
        frees the slot before an out-of-bounds write can happen."""
        req = self.slots[slot]
        if req is None:
            raise ValueError(f"append_token on empty slot {slot}")
        req.generated.append(int(token))
        g, P = len(req.generated), len(req.prompt)
        if g == 1:
            req.t_first_token = self.clock()
        if req.eos_id is not None and int(token) == req.eos_id:
            req.finish_reason = FINISH_EOS
        elif g >= req.max_new_tokens:
            req.finish_reason = FINISH_MAX_NEW
        elif P + g > self.max_len:
            req.finish_reason = FINISH_MAX_LEN
        if req.done:
            req.t_finish = self.clock()
            self.slots[slot] = None
            self.finished[req.uid] = req
            return req
        return None

    def drain_finished(self) -> dict[int, Request]:
        """Hand over (and forget) all completed requests — the memory
        bound for a long-lived engine: call after delivering results."""
        done, self.finished = self.finished, {}
        return done
