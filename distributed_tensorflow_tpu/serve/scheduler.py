"""Continuous-batching scheduler — deterministic, jax-free, CPU-testable.

Policy (the MLPerf lesson applied to serving: batching discipline, not
FLOPs, decides utilization — PAPERS.md):

- **FIFO admission.** Requests queue in submission order; the moment a
  decode slot frees, the head of the queue is admitted into it. No
  reordering, no priorities — fairness is positional.
- **Fixed decode-batch slots.** The decode batch is ``num_slots`` wide,
  always. The scheduler's job is to keep occupancy at 1.0 whenever the
  queue is non-empty (asserted by tools/bench_serve.py).
- **Evict on EOS / max-new / max-len.** A request leaves its slot the
  step it finishes: its own ``eos_id``, its ``max_new_tokens`` budget,
  or the slot's ``max_len`` cache budget (prompt + written tokens). The
  freed slot is re-admissible in the SAME engine step — prefill/decode
  interleaving with no idle step.

Admission control (docs/resilience.md — the failure modes an unbounded
FIFO hides until overload):

- **Bounded queue.** ``max_queue`` caps waiting requests; ``submit``
  raises ``QueueFull`` instead of growing without bound. Rejection is
  explicit backpressure the client can act on (retry, shed, reroute);
  silent queue growth just converts overload into timeout for everyone.
- **Deadlines.** A request may carry ``deadline_s``; once its absolute
  deadline passes it is evicted with ``FINISH_TIMEOUT`` — from the
  queue (never admitted, no wasted prefill) or from its slot (checked
  every engine step via ``expire()``).
- **Cancellation.** ``cancel(uid)`` evicts a queued or resident request
  with ``FINISH_CANCELLED``; idempotent, no-op on finished/unknown uids.
- **Drain.** ``close()`` stops admission (submit raises
  ``SchedulerClosed``) and cancels everything still queued; resident
  requests keep decoding until done — the graceful-shutdown half the
  engine exposes as ``ServeEngine.drain()``.

All state is plain Python (deque + list), so every invariant — no slot
leaks, FIFO order, eviction conditions — is testable with no model and
no device (tests/test_serve.py::test_scheduler_invariants).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable, Iterable

from ..obs import flightrec as flightrec_lib

#: why a request finished
FINISH_EOS = "eos"
FINISH_MAX_NEW = "max_new_tokens"
FINISH_MAX_LEN = "max_len"
FINISH_TIMEOUT = "timeout"
FINISH_CANCELLED = "cancelled"

#: every reason a Request.finish_reason can hold — the serve_finished
#: counter label set (obs wiring in engine.py keys off this tuple)
FINISH_REASONS = (
    FINISH_EOS, FINISH_MAX_NEW, FINISH_MAX_LEN,
    FINISH_TIMEOUT, FINISH_CANCELLED,
)


class QueueFull(RuntimeError):
    """Bounded-queue backpressure: the waiting line is at ``max_queue``.
    The client should retry later or shed the request."""


class SchedulerClosed(RuntimeError):
    """submit() after close()/drain(): the scheduler no longer admits."""


@dataclasses.dataclass
class Request:
    uid: int
    prompt: tuple[int, ...]
    max_new_tokens: int
    eos_id: int | None = None
    #: relative latency budget; ``t_deadline`` (absolute, scheduler
    #: clock) is stamped at submit and enforced by ``expire()``
    deadline_s: float | None = None
    t_deadline: float | None = None
    generated: list[int] = dataclasses.field(default_factory=list)
    finish_reason: str | None = None
    #: times this request was preempted back to the queue head (paged
    #: block exhaustion — engine re-prefills prompt+generated on
    #: re-admission); ``t_admit`` keeps its FIRST admission stamp
    preemptions: int = 0
    #: SLO tier: block-exhaustion preemption victimizes the LOWEST
    #: priority resident first (ties: youngest), so a low-priority batch
    #: lane absorbs cache pressure before interactive traffic. 0 =
    #: default; all-equal priorities reproduce pure youngest-first.
    priority: int = 0
    # lifecycle timestamps (scheduler clock), the raw material for the
    # serve latency metrics (docs/observability.md): queue wait =
    # t_admit - t_submit, TTFT = t_first_token - t_submit, per-token
    # decode latency = (t_finish - t_first_token) / (generated - 1).
    t_submit: float | None = None
    t_admit: float | None = None
    t_first_token: float | None = None
    t_finish: float | None = None
    #: router trace id (serve fleet): set when the request entered
    #: through a Router, None for direct engine submissions. Carried so
    #: the replica-side request ledger (obs/reqtrace.py) records this
    #: process's admission/prefill/preemption spans under the SAME id
    #: the router traces — the key the cross-process merge joins on.
    rid: int | None = None
    #: draft tokens the verify step accepted over this request's
    #: lifetime (speculative decoding only; stays 0 otherwise).
    #: ``spec_accepted / (generated - 1)`` approximates the per-request
    #: acceptance rate — the fleet-wide rate is the engine gauge.
    spec_accepted: int = 0

    @property
    def done(self) -> bool:
        return self.finish_reason is not None


class Scheduler:
    """FIFO continuous batching over ``num_slots`` decode slots, each
    with a ``max_len``-token KV budget (prompt + generated)."""

    def __init__(self, num_slots: int, max_len: int,
                 clock: Callable[[], float] = time.perf_counter,
                 max_queue: int | None = None, flightrec=None,
                 admission_gate: Callable[[Request], bool] | None = None,
                 reqtrace=None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None for unbounded)")
        self.num_slots = num_slots
        self.max_len = max_len
        self.max_queue = max_queue
        #: extra admission predicate beyond "a slot is free" — the paged
        #: engine installs a free-BLOCKS check here, so admission is
        #: gated on actual KV capacity, not slot count. Head-of-line
        #: blocking is deliberate: skipping past a starved head would
        #: break FIFO fairness.
        self.admission_gate = admission_gate
        self.clock = clock  # injectable for deterministic latency tests
        #: flight recorder for admit/evict/close lifecycle events
        #: (obs/flightrec.py — stdlib-only, so this stays jax-free)
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        #: per-request span ledger (obs/reqtrace.py), None = untraced.
        #: Only rid-carrying requests (router traffic) emit spans.
        self.reqtrace = reqtrace
        self.queue: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * num_slots
        self._next_uid = 0
        self._closed = False
        #: uid → Request, completion order. Retained until the caller
        #: collects results (ServeEngine.run / stream); long-lived
        #: servers must drain_finished() or history accumulates forever.
        self.finished: dict[int, Request] = {}

    # -- admission ---------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def submit(
        self,
        prompt: Iterable[int],
        max_new_tokens: int = 32,
        eos_id: int | None = None,
        deadline_s: float | None = None,
        priority: int = 0,
        rid: int | None = None,
    ) -> int:
        """Enqueue a request; returns its uid. Raises ``QueueFull`` when
        ``max_queue`` requests are already waiting (backpressure) and
        ``SchedulerClosed`` after ``close()``."""
        if self._closed:
            raise SchedulerClosed("scheduler is draining; admission stopped")
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the per-slot cache "
                f"budget max_len={self.max_len}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive (or None)")
        # capacity LAST: a malformed request must get its permanent
        # ValueError, not a retryable QueueFull the client would loop on
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            raise QueueFull(
                f"{len(self.queue)} requests waiting (max_queue="
                f"{self.max_queue}); retry later"
            )
        now = self.clock()
        req = Request(self._next_uid, prompt, max_new_tokens, eos_id,
                      deadline_s=deadline_s, priority=int(priority),
                      t_submit=now, rid=rid)
        if deadline_s is not None:
            req.t_deadline = now + deadline_s
        self._next_uid += 1
        self.queue.append(req)
        return req.uid

    def admit(self) -> list[tuple[int, Request]]:
        """Move queued requests into free slots, FIFO; returns the newly
        placed (slot, request) pairs — the engine prefills exactly
        these."""
        placed = []
        for slot in range(self.num_slots):
            if not self.queue:
                break
            if self.slots[slot] is None:
                if self.admission_gate is not None \
                        and not self.admission_gate(self.queue[0]):
                    break  # head-of-line blocked on capacity, stay FIFO
                req = self.queue.popleft()
                if req.t_admit is None:  # keep the FIRST admission stamp
                    req.t_admit = self.clock()
                self.slots[slot] = req
                placed.append((slot, req))
                self.flightrec.emit("serve_admit", uid=req.uid, slot=slot)
                if self.reqtrace is not None and req.rid is not None:
                    # admission ends the block-wait: the request enters
                    # its (chunked) prefill phase in this slot
                    self.reqtrace.transition(
                        req.rid, "prefill_chunks", uid=req.uid, slot=slot,
                        preemptions=req.preemptions)
        return placed

    # -- eviction beyond token-driven finish -------------------------------

    def _finish(self, req: Request, reason: str, now: float | None = None) -> None:
        """The single eviction bottleneck — every finished request, token-
        driven or not, passes through here exactly once (one flight-
        recorder ``serve_evict`` per request, reason attached)."""
        req.finish_reason = reason
        req.t_finish = self.clock() if now is None else now
        self.finished[req.uid] = req
        self.flightrec.emit("serve_evict", uid=req.uid, reason=reason)
        if self.reqtrace is not None and req.rid is not None:
            self.reqtrace.finish(req.rid, reason)

    def cancel(self, uid: int) -> Request | None:
        """Evict ``uid`` with ``FINISH_CANCELLED`` wherever it lives —
        still queued (removed without ever taking a slot) or resident
        (slot freed immediately; its next decode token is never
        delivered). Returns the evicted Request, or None if the uid is
        unknown or already finished (idempotent)."""
        for i, req in enumerate(self.queue):
            if req.uid == uid:
                del self.queue[i]
                self._finish(req, FINISH_CANCELLED)
                return req
        for slot, req in enumerate(self.slots):
            if req is not None and req.uid == uid:
                self.slots[slot] = None
                self._finish(req, FINISH_CANCELLED)
                return req
        return None

    def preempt(self, slot: int) -> Request:
        """Evict the request in ``slot`` back to the FRONT of the queue
        (it keeps its uid, prompt, and generated tokens — on
        re-admission the engine re-prefills everything it already knows
        and decoding continues where it left off). This is the paged
        engine's block-exhaustion pressure valve: the request is NOT
        finished, so no terminal accounting fires."""
        req = self.slots[slot]
        if req is None:
            raise ValueError(f"preempt on empty slot {slot}")
        self.slots[slot] = None
        req.preemptions += 1
        self.queue.appendleft(req)
        self.flightrec.emit("serve_preempt", uid=req.uid, slot=slot)
        if self.reqtrace is not None and req.rid is not None:
            self.reqtrace.transition(req.rid, "preempted", uid=req.uid,
                                     slot=slot)
        return req

    def expire(self) -> list[Request]:
        """Evict every request whose absolute deadline has passed, with
        ``FINISH_TIMEOUT``: queued requests are never admitted (no
        wasted prefill), resident requests free their slot. The engine
        calls this once per step, so a resident deadline is enforced to
        one decode-step granularity."""
        now = self.clock()
        evicted: list[Request] = []
        if any(r.t_deadline is not None and now >= r.t_deadline
               for r in self.queue):
            kept: deque[Request] = deque()
            for req in self.queue:  # one partition pass, not O(n) removes
                if req.t_deadline is not None and now >= req.t_deadline:
                    self._finish(req, FINISH_TIMEOUT, now)
                    evicted.append(req)
                else:
                    kept.append(req)
            self.queue = kept
        for slot, req in enumerate(self.slots):
            if req is not None and req.t_deadline is not None \
                    and now >= req.t_deadline:
                self.slots[slot] = None
                self._finish(req, FINISH_TIMEOUT, now)
                evicted.append(req)
        return evicted

    def close(self) -> list[Request]:
        """Stop admission and cancel everything still queued (they would
        never run); resident requests are left to finish decoding.
        Returns the cancelled requests; idempotent."""
        first_close = not self._closed
        self._closed = True
        evicted: list[Request] = []
        while self.queue:
            req = self.queue.popleft()
            self._finish(req, FINISH_CANCELLED)
            evicted.append(req)
        if first_close:
            self.flightrec.emit("serve_close", cancelled=len(evicted))
        return evicted

    # -- decode-loop bookkeeping -------------------------------------------

    def active_slots(self) -> list[int]:
        return [i for i, r in enumerate(self.slots) if r is not None]

    @property
    def occupancy(self) -> float:
        return len(self.active_slots()) / self.num_slots

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(
            r is not None for r in self.slots
        )

    def append_token(self, slot: int, token: int) -> Request | None:
        """Record a sampled token for the request in ``slot``; evict and
        return the request if this token finishes it, else None.

        Cache accounting: after ``g`` generated tokens, continuing
        requires writing token ``g`` at cache position ``P + g - 1``, so
        the slot is out of budget once ``P + g > max_len`` — the request
        keeps that final token (it was sampled from in-budget state) and
        frees the slot before an out-of-bounds write can happen."""
        req = self.slots[slot]
        if req is None:
            raise ValueError(f"append_token on empty slot {slot}")
        req.generated.append(int(token))
        g, P = len(req.generated), len(req.prompt)
        if g == 1:
            req.t_first_token = self.clock()
        if req.eos_id is not None and int(token) == req.eos_id:
            req.finish_reason = FINISH_EOS
        elif g >= req.max_new_tokens:
            req.finish_reason = FINISH_MAX_NEW
        elif P + g > self.max_len:
            req.finish_reason = FINISH_MAX_LEN
        if req.done:
            self.slots[slot] = None
            self._finish(req, req.finish_reason)
            return req
        return None

    def drain_finished(self) -> dict[int, Request]:
        """Hand over (and forget) all completed requests — the memory
        bound for a long-lived engine: call after delivering results."""
        done, self.finished = self.finished, {}
        return done
