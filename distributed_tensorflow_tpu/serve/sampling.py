"""Token sampling: greedy / temperature / top-k.

One function, batch-shaped: ``sample(logits [..., V], rng)``. Greedy
(``temperature <= 0``) is pure argmax — deterministic, rng ignored —
which is what the decode-parity tests and the bench use. Temperature
scales logits before a Gumbel draw (``jax.random.categorical``); top-k
first floors everything below the k-th logit so the tail can never be
drawn. All in f32 — the head already emits f32 logits (models/
transformer.py head_dtype docstring), and sampling is far off the FLOPs
critical path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF


def sample(
    logits: jax.Array,
    rng: jax.Array | None = None,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
) -> jax.Array:
    """logits [..., V] → token ids [...] (int32)."""
    logits = logits.astype(jnp.float32)
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        raise ValueError("temperature > 0 requires an rng key")
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
