"""Token sampling: greedy / temperature / top-k, plus the speculative
accept/reject rule.

One function, batch-shaped: ``sample(logits [..., V], rng)``. Greedy
(``temperature <= 0``) is pure argmax — deterministic, rng ignored —
which is what the decode-parity tests and the bench use. Temperature
scales logits before a Gumbel draw (``jax.random.categorical``); top-k
first floors everything below the k-th logit so the tail can never be
drawn. All in f32 — the head already emits f32 logits (models/
transformer.py head_dtype docstring), and sampling is far off the FLOPs
critical path.

The ``spec_verify_*`` pair is the other half of speculative decoding
(docs/serving.md "Speculative decoding"): given the target model's
logits at every drafted position (ONE chunked-prefill-shaped verify
step) and the drafter's proposals, decide the longest accepted prefix
and the one extra token every verify step is entitled to. Greedy
acceptance is EXACT (token == argmax, so the emitted stream is
bit-identical to non-speculative greedy decode); temperature acceptance
is the standard speculative-sampling rule specialized to a
DETERMINISTIC drafter (q is a point mass): accept draft ``d`` with
probability ``p_target(d)``, else resample from the renormalized
residual ``p_target`` with ``d`` removed — which preserves the target
distribution exactly (pinned statistically in tests/test_serve.py).
Host-side numpy on purpose: k is tiny, V is one row, and the decision
drives host bookkeeping (rollback), so a device round-trip buys nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import NEG_INF


def sample(
    logits: jax.Array,
    rng: jax.Array | None = None,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
) -> jax.Array:
    """logits [..., V] → token ids [...] (int32)."""
    logits = logits.astype(jnp.float32)
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        raise ValueError("temperature > 0 requires an rng key")
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def spec_verify_greedy(
    logits: np.ndarray, draft: list[int] | tuple[int, ...]
) -> tuple[list[int], int]:
    """Greedy-exact acceptance. ``logits`` [len(draft)+1, V] — the
    target's logits at each drafted position plus one past the last
    draft; row ``i`` conditions on the drafts before it, so it is only
    meaningful while every earlier draft was accepted. Returns
    ``(emitted, accepted)``: the argmax at each position up to and
    including the first mismatch (the mismatch row's argmax IS the
    correction token), plus the bonus token when every draft survives —
    always ``accepted + 1`` tokens, never zero, which is why a verify
    step can never be slower than a plain decode step in tokens."""
    arg = np.argmax(np.asarray(logits), axis=-1)
    emitted: list[int] = []
    accepted = 0
    for i, d in enumerate(draft):
        tok = int(arg[i])
        emitted.append(tok)
        if tok != int(d):
            return emitted, accepted
        accepted += 1
    emitted.append(int(arg[len(draft)]))
    return emitted, accepted


def spec_verify_sample(
    logits: np.ndarray,
    draft: list[int] | tuple[int, ...],
    gen: np.random.Generator,
    *,
    temperature: float,
    top_k: int = 0,
) -> tuple[list[int], int]:
    """Distribution-preserving acceptance for a deterministic drafter.
    Draft ``d_i`` is accepted with probability ``p_i(d_i)`` (``p_i`` the
    target's temperature/top-k distribution at that position — the
    draft's distribution is a point mass, so the min(1, p/q) rule
    reduces to this); on rejection the emitted token is drawn from the
    renormalized residual (``p_i`` with ``d_i`` zeroed) and verification
    stops. If every draft survives, the bonus token is drawn from the
    last row unmodified. The marginal of each emitted token equals
    straight temperature sampling — pinned statistically in
    tests/test_serve.py::test_spec_sample_matches_target_distribution."""
    if temperature <= 0.0:
        raise ValueError("spec_verify_sample requires temperature > 0; "
                         "use spec_verify_greedy")
    scaled = np.asarray(logits, np.float64) / temperature
    if top_k > 0:
        kth = -np.sort(-scaled, axis=-1)[:, top_k - 1: top_k]
        scaled = np.where(scaled < kth, NEG_INF, scaled)
    scaled = scaled - scaled.max(axis=-1, keepdims=True)
    p = np.exp(scaled)
    p /= p.sum(axis=-1, keepdims=True)
    emitted: list[int] = []
    accepted = 0
    for i, d in enumerate(draft):
        d = int(d)
        if gen.random() < p[i, d]:
            emitted.append(d)
            accepted += 1
            continue
        residual = p[i].copy()
        residual[d] = 0.0
        total = residual.sum()
        if total <= 0.0:  # the draft held ALL the mass; nothing to resample
            emitted.append(d)
            accepted += 1
            continue
        emitted.append(int(gen.choice(residual.shape[0], p=residual / total)))
        return emitted, accepted
    emitted.append(int(gen.choice(p.shape[-1], p=p[len(draft)])))
    return emitted, accepted
