"""Serve fleet — N replica engines under one supervisor, one router
(docs/serving.md "Serve fleet").

``ServeFleetSupervisor`` is the serving twin of the training fleet
(resilience/fleet.FleetSupervisor), built on the SAME liveness protocol
(resilience/liveness.py: atomic heartbeat files, incarnation fencing,
monitor-clock staleness, launch-seam teardown) — but where the training
fleet's unit of recovery is the whole gang (restart from a common
checkpoint), the serve fleet's is one REQUEST: a replica death loses no
durable state, only in-flight decodes, and those are requeued at their
lane head (serve/router.py) and re-prefilled on survivors. Scale-up is
symmetric: a joining replica becomes a placement target on the next
dispatch, no drain.

Topology::

    clients ──submit──> Router ──dispatch──> replica 0..N-1
                          ^                    (each: paged ServeEngine)
                          └── token/finish/death feedback (pump loop)

Two replica transports speak one protocol (Popen-shaped ``poll/
terminate/kill/wait/pid`` + ``send(payload)`` / ``poll_output()`` /
``request_drain()``):

- ``LocalReplica`` — an in-process engine behind the protocol, with a
  synthetic pid and a ``hard_kill()`` that drops the engine mid-stream.
  Deterministic (the supervisor's pump loop is single-threaded), so
  the router/failover invariants are testable without processes.
- ``SubprocessReplica`` — a real worker process
  (``python -m distributed_tensorflow_tpu.serve.replica``) fed through
  an inbox of atomically-written request files and tailed through an
  append-only events JSONL; heartbeats + telemetry snapshots ride next
  to them in the fleet workdir, exactly like training workers.

The supervisor's flight recorder carries the fleet half of the merged
postmortem (tools/postmortem.py --merge): ``fleet_launch`` per replica
(the required clock anchor), ``serve_route`` on dispatch (paired with
the replica's ingest ACK — the recurring lower bound),
``serve_replica_dead`` / ``serve_requeue`` on the death path, and
``fleet_done`` bounding every replica event from above.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import time
from collections import deque
from typing import Callable

from ..obs import fleetview as fleetview_lib
from ..obs import flightrec as flightrec_lib
from ..obs.registry import Registry, default_registry
from ..resilience import liveness
from .router import Router
from .scheduler import QueueFull

logger = logging.getLogger(__name__)

#: metric names (documented in docs/observability.md "Serve fleet")
SERVE_REPLICAS = "serve_replicas"
SERVE_REPLICA_DEATHS_TOTAL = "serve_replica_deaths_total"

#: replica exit protocol: 0 = clean drain; anything else mid-run is a
#: death (the request-level recovery needs no finer taxonomy)
DRAIN_SENTINEL = "DRAIN"


class ServeFleetExhausted(RuntimeError):
    """Replica deaths exceeded the fleet's budget, or the last replica
    died — there is no survivor to re-prefill on."""


def replica_dir(workdir: str, index: int) -> str:
    return os.path.join(os.path.abspath(os.path.expanduser(workdir)),
                        f"replica-{index}")


def replica_inbox_dir(workdir: str, index: int) -> str:
    return os.path.join(replica_dir(workdir, index), "inbox")


def replica_events_path(workdir: str, index: int, incarnation: int) -> str:
    """Append-only token/finish stream of one replica incarnation. The
    incarnation is in the name so a relaunch never interleaves with its
    corpse's stream."""
    return os.path.join(replica_dir(workdir, index),
                        f"events-i{incarnation}.jsonl")


def drain_path(workdir: str, index: int) -> str:
    return os.path.join(replica_dir(workdir, index), DRAIN_SENTINEL)


# ---------------------------------------------------------------------------
# Engine bridge — rid <-> uid, shared by LocalReplica and serve/replica.py
# ---------------------------------------------------------------------------


class EngineBridge:
    """The ONE rid↔uid adapter between router dispatch payloads and a
    ``ServeEngine`` (used in-process by ``LocalReplica`` and inside the
    replica worker) — so the re-prefill and backpressure semantics
    cannot drift between the test transport and the real one.

    Backpressure: a payload the engine refuses (``QueueFull``) waits in
    a local FIFO and is retried each pump, preserving dispatch order.
    """

    def __init__(self, engine):
        self.engine = engine
        self._pending: deque[dict] = deque()
        self._req_of: dict[int, object] = {}   # rid -> scheduler Request
        self._sent: dict[int, int] = {}        # rid -> tokens reported

    def accept(self, payload: dict) -> None:
        payload = dict(payload)
        rt = getattr(self.engine, "reqtrace", None)
        if rt is not None and "rid" in payload:
            # ingest span: opens when the order reaches the replica,
            # closes when the scheduler admits it to a slot. Its t0 is
            # the replica-side half of the dispatch→ingest clock anchor
            # (the router's ``route`` span is the other half), keyed by
            # (rid, requeue) so each life aligns independently.
            rt.transition(int(payload["rid"]), "admission_block",
                          requeue=int(payload.get("requeues", 0)))
        self._pending.append(payload)

    @property
    def busy(self) -> bool:
        return bool(self._pending or self._req_of
                    or self.engine.sched.has_work)

    def pump(self) -> list[dict]:
        """Feed waiting payloads, advance the engine one step, and
        report what changed: ``{kind: token|finish, rid, ...}``."""
        while self._pending:
            if not self._try_submit(self._pending[0]):
                break
            self._pending.popleft()
        if self.engine.sched.has_work:
            self.engine.step()
        return self.collect()

    def _try_submit(self, payload: dict) -> bool:
        try:
            self.engine.submit(
                payload["prompt"], payload["max_new_tokens"],
                eos_id=payload.get("eos_id"),
                priority=int(payload.get("priority", 0)),
                rid=payload.get("rid"),
            )
        except QueueFull:
            return False
        rid = int(payload["rid"])
        # the freshly submitted Request is the queue tail; holding the
        # object directly survives preemption requeues (same instance)
        self._req_of[rid] = self.engine.sched.queue[-1]
        self._sent[rid] = 0
        return True

    def collect(self) -> list[dict]:
        out: list[dict] = []
        for rid in list(self._req_of):
            req = self._req_of[rid]
            for tok in req.generated[self._sent[rid]:]:
                out.append({"kind": "token", "rid": rid, "token": int(tok)})
            self._sent[rid] = len(req.generated)
            if req.done:
                out.append({"kind": "finish", "rid": rid,
                            "reason": req.finish_reason})
                del self._req_of[rid], self._sent[rid]
                self.engine.sched.finished.pop(req.uid, None)
        return out

    def drain(self) -> list[dict]:
        """Engine shutdown: decode residents to completion, audit the
        block allocator, report the trailing events plus one terminal
        ``drained`` record (the leak gate every surviving replica must
        pass)."""
        eng = self.engine
        eng.drain()
        out = self.collect()
        free = int(getattr(eng.alloc, "blocks_free", 0)) if eng.paged else 0
        total = int(eng.cache.num_blocks) if eng.paged else 0
        out.append({"kind": "drained", "blocks_free": free,
                    "num_blocks": total, "leak_free": free == total})
        return out


# ---------------------------------------------------------------------------
# Replica transports
# ---------------------------------------------------------------------------

#: synthetic pids for in-process replicas — disjoint from real pids in
#: any merged timeline (kernel pids are far below this range)
_local_pids = itertools.count(10_000_000)


class LocalReplica:
    """An in-process replica: a real (usually paged) ``ServeEngine``
    behind the replica transport protocol. ``hard_kill()`` is the chaos
    seam — the engine is dropped on the floor exactly as a SIGKILL
    would, mid-stream, undelivered state and all."""

    def __init__(self, engine, *, pid: int | None = None):
        self.bridge = EngineBridge(engine)
        self.pid = int(pid) if pid is not None else next(_local_pids)
        self._rc: int | None = None
        self._draining = False

    # -- data plane --------------------------------------------------------

    def send(self, payload: dict) -> None:
        if self._rc is None and not self._draining:
            self.bridge.accept(payload)

    def poll_output(self) -> list[dict]:
        if self._rc is not None:
            return []
        if self._draining:
            events = self.bridge.drain()
            self._rc = 0
            return events
        return self.bridge.pump()

    def request_drain(self) -> None:
        self._draining = True

    # -- Popen shape -------------------------------------------------------

    def poll(self) -> int | None:
        return self._rc

    def wait(self, timeout: float | None = None) -> int:
        if self._rc is None:
            # an in-process replica only exits through drain/kill; a
            # bare wait() would spin forever — surface the misuse
            raise RuntimeError("LocalReplica.wait() before drain/kill")
        return self._rc

    def hard_kill(self) -> None:
        """SIGKILL equivalent: no drain, no leak audit, engine state
        (and every undelivered token) gone."""
        if self._rc is None:
            self._rc = -9

    def kill(self) -> None:
        self.hard_kill()

    def terminate(self) -> None:
        # SIGTERM equivalent: coordinated drain on the next pump
        self._draining = True


class SubprocessReplica:
    """Client side of one replica worker process: wraps its Popen
    handle, writes dispatch payloads into the inbox (atomic tmp+rename,
    so the worker never reads a torn request), and tails the replica's
    append-only events stream (complete lines only — a torn tail line
    is left for the next poll)."""

    def __init__(self, proc, workdir: str, index: int, incarnation: int):
        self.proc = proc
        self.workdir = workdir
        self.index = int(index)
        self.incarnation = int(incarnation)
        self._inbox = replica_inbox_dir(workdir, index)
        self._events = replica_events_path(workdir, index, incarnation)
        self._offset = 0
        self._seq = 0

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self):
        return self.proc.poll()

    def wait(self, timeout: float | None = None):
        return self.proc.wait(timeout=timeout)

    def terminate(self) -> None:
        self.proc.terminate()

    def kill(self) -> None:
        self.proc.kill()

    def send(self, payload: dict) -> None:
        os.makedirs(self._inbox, exist_ok=True)
        self._seq += 1
        liveness.atomic_write(
            os.path.join(self._inbox, f"req-{self._seq:06d}.json"),
            json.dumps(payload))

    def request_drain(self) -> None:
        liveness.atomic_write(drain_path(self.workdir, self.index), "1\n")

    def poll_output(self) -> list[dict]:
        try:
            with open(self._events) as f:
                f.seek(self._offset)
                chunk = f.read()
        except FileNotFoundError:
            return []
        events: list[dict] = []
        consumed = 0
        for line in chunk.splitlines(keepends=True):
            if not line.endswith("\n"):
                break  # torn tail: the writer is mid-append
            consumed += len(line)
            line = line.strip()
            if line:
                events.append(json.loads(line))
        self._offset += consumed
        return events


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Replica:
    index: int
    incarnation: int
    handle: object
    monitor: liveness.HeartbeatMonitor | None = None


class ServeFleetSupervisor:
    """Pump-driven supervisor over N replicas and one ``Router``.

    ``launch(index, incarnation)`` is the seam (FleetSupervisor's
    pattern): it returns a replica transport — tests and the bench
    driver hand back ``LocalReplica``s; tools/chaos_smoke.py spawns
    ``serve/replica.py`` workers and wraps them in
    ``SubprocessReplica``. One ``pump()`` is one deterministic
    iteration: dispatch → collect replica output → judge liveness (and
    run the death path) → optionally fold telemetry snapshots.

    Death path (cause: nonzero/early exit, or a DEAD/stalled heartbeat
    verdict when a workdir is configured): emit ``serve_replica_dead``,
    make the corpse final (``liveness.ensure_dead``), requeue its
    in-flight requests at their lane heads, and — with
    ``relaunch_dead`` — relaunch the slot at incarnation+1 behind a
    fresh incarnation fence, corpse heartbeat deleted first so the new
    monitor can never read stale liveness. Without relaunch the
    survivors simply absorb the load (elastic ``add_replica`` is the
    scale-up path, no drain either way).
    """

    def __init__(self, launch: Callable[[int, int], object],
                 num_replicas: int, *, router: Router | None = None,
                 workdir: str | None = None,
                 relaunch_dead: bool = False,
                 max_deaths: int = 8,
                 registry: Registry | None = None, flightrec=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 poll_s: float = 0.01, term_grace_s: float = 5.0,
                 heartbeat_timeout_s: float = 30.0,
                 stall_timeout_s: float = 120.0,
                 launch_grace_s: float = 120.0,
                 snapshot_poll_s: float | None = None):
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        self.launch = launch
        self.num_replicas = num_replicas
        self.workdir = (os.path.abspath(os.path.expanduser(workdir))
                        if workdir else None)
        self.relaunch_dead = relaunch_dead
        self.max_deaths = max_deaths
        self.registry = registry if registry is not None \
            else default_registry()
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.router = router if router is not None else Router(
            registry=self.registry, flightrec=self.flightrec, clock=clock)
        self.clock = clock
        self.sleep = sleep
        self.poll_s = poll_s
        self.term_grace_s = term_grace_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.stall_timeout_s = stall_timeout_s
        self.launch_grace_s = launch_grace_s
        self.deaths = 0
        self.replicas: dict[int, _Replica] = {}
        #: index → terminal ``drained`` record (the leak audit of every
        #: replica that shut down cleanly)
        self.drained: dict[int, dict] = {}
        self._m_replicas = self.registry.gauge(
            SERVE_REPLICAS, "live serve replicas behind the router")
        self._m_deaths = self.registry.counter(
            SERVE_REPLICA_DEATHS_TOTAL,
            "serve replica deaths detected (exit, missed heartbeat)")
        self.aggregator: fleetview_lib.FleetAggregator | None = None
        self._snapshot_poll_s = snapshot_poll_s
        self._t_agg: float | None = None
        if snapshot_poll_s is not None and self.workdir:
            self.aggregator = fleetview_lib.FleetAggregator(
                self.workdir, range(num_replicas),
                registry=self.registry, flightrec=self.flightrec,
                clock=self.clock)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.flightrec.emit("fleet_start", workers=self.num_replicas,
                            incarnation=0)
        for i in range(self.num_replicas):
            self._launch(i, 0)

    def _launch(self, index: int, incarnation: int) -> None:
        if self.workdir:
            # clear corpse state BEFORE the fence goes up: a stale
            # heartbeat or half-eaten inbox must not leak into the new
            # incarnation (requeued requests were already re-owned by
            # the router, so leftover inbox files are duplicates)
            hb = liveness.heartbeat_path(self.workdir, index)
            if os.path.exists(hb):
                os.remove(hb)
            inbox = replica_inbox_dir(self.workdir, index)
            if os.path.isdir(inbox):
                for name in os.listdir(inbox):
                    os.remove(os.path.join(inbox, name))
            stale_drain = drain_path(self.workdir, index)
            if os.path.exists(stale_drain):
                os.remove(stale_drain)
        handle = self.launch(index, incarnation)
        monitor = None
        if self.workdir:
            monitor = liveness.HeartbeatMonitor(
                liveness.heartbeat_path(self.workdir, index), incarnation,
                clock=self.clock,
                heartbeat_timeout_s=self.heartbeat_timeout_s,
                stall_timeout_s=self.stall_timeout_s,
                launch_grace_s=self.launch_grace_s)
        self.replicas[index] = _Replica(index, incarnation, handle, monitor)
        self.flightrec.emit("fleet_launch", worker=index,
                            incarnation=incarnation,
                            pid=getattr(handle, "pid", None))
        self.router.add_replica(index)
        self._m_replicas.set(len(self.replicas))

    def add_replica(self) -> int:
        """Elastic scale-up: launch one more replica (next free index,
        incarnation 0) and make it a placement target on the very next
        dispatch — the fleet never drains."""
        index = max(self.replicas, default=-1) + 1
        if self.aggregator is not None:
            self.aggregator.workers.append(index)
        self._launch(index, 0)
        return index

    # -- the pump ----------------------------------------------------------

    def pump(self) -> bool:
        """One supervision iteration; returns True while work remains
        (requests queued or in flight)."""
        for target, req in self.router.dispatch():
            self.replicas[target].handle.send(req.payload())
        for rep in list(self.replicas.values()):
            for ev in rep.handle.poll_output():
                self._on_replica_event(rep, ev)
        self._check_liveness()
        self._maybe_aggregate()
        return not self.router.idle

    def _on_replica_event(self, rep: _Replica, ev: dict) -> None:
        kind = ev.get("kind")
        if kind == "token":
            self.router.on_token(int(ev["rid"]), int(ev["token"]))
        elif kind == "finish":
            self.router.on_finish(int(ev["rid"]), str(ev["reason"]))
        elif kind == "drained":
            self.drained[rep.index] = dict(ev)
        # anything else ("ready", diagnostics) is informational

    def _check_liveness(self) -> None:
        for rep in list(self.replicas.values()):
            rc = rep.handle.poll()
            cause = None
            if rc is not None:
                # ANY exit while supervised is a death: clean drains
                # happen in stop(), after the replica leaves the table
                cause = "exit" if rc else "early_exit"
            elif rep.monitor is not None:
                verdict = rep.monitor.check()
                if verdict == liveness.DEAD:
                    cause = "heartbeat"
                elif verdict == liveness.STALLED_HB:
                    cause = "stall"
            if cause is not None:
                self._on_death(rep, cause, rc)

    def _on_death(self, rep: _Replica, cause: str, rc) -> None:
        self.deaths += 1
        self._m_deaths.inc()
        self.flightrec.emit(
            "serve_replica_dead", replica=rep.index, cause=cause,
            incarnation=rep.incarnation,
            pid=getattr(rep.handle, "pid", None))
        logger.error("serve fleet: replica %d dead [%s] rc=%r",
                     rep.index, cause, rc)
        liveness.ensure_dead(rep.handle, self.term_grace_s, self.poll_s,
                             clock=self.clock, sleep=self.sleep)
        del self.replicas[rep.index]
        self._m_replicas.set(len(self.replicas))
        # drain the corpse's last delivered tokens? No: its events were
        # already polled this pump; anything undelivered died with it —
        # the requeue below re-prefills past exactly what the client saw
        self.router.requeue_replica(rep.index)
        if self.deaths > self.max_deaths:
            raise ServeFleetExhausted(
                f"{self.deaths} replica deaths exceed the budget "
                f"({self.max_deaths})")
        if self.relaunch_dead:
            self._launch(rep.index, rep.incarnation + 1)
        elif not self.replicas:
            raise ServeFleetExhausted(
                "last replica died with relaunch disabled; no survivor "
                "to re-prefill on")

    def _maybe_aggregate(self) -> None:
        if self.aggregator is None:
            return
        now = self.clock()
        if self._t_agg is None or now - self._t_agg >= self._snapshot_poll_s:
            self._t_agg = now
            self.aggregator.poll()

    # -- driving -----------------------------------------------------------

    def run(self, max_pumps: int = 1_000_000) -> None:
        """Pump until every submitted request finished. ``max_pumps``
        bounds the loop so a wedged fleet fails loudly instead of
        spinning forever."""
        for _ in range(max_pumps):
            if not self.pump():
                return
            self.sleep(self.poll_s)
        raise ServeFleetExhausted(
            f"fleet made no progress to idle within {max_pumps} pumps "
            f"({self.router.inflight()} in flight)")

    def stop(self, timeout_s: float = 60.0) -> None:
        """Coordinated shutdown: ask every replica to drain, keep
        pumping their output (the terminal leak audits arrive here),
        reap, and close the timeline with ``fleet_done`` — the merge
        anchor that bounds every replica event from above."""
        for rep in self.replicas.values():
            rep.handle.request_drain()
        deadline = self.clock() + timeout_s
        live = dict(self.replicas)
        while live and self.clock() < deadline:
            for i, rep in list(live.items()):
                for ev in rep.handle.poll_output():
                    self._on_replica_event(rep, ev)
                if rep.handle.poll() is not None:
                    del live[i]
            if live:
                self.sleep(self.poll_s)
        for rep in self.replicas.values():
            liveness.ensure_dead(rep.handle, self.term_grace_s, self.poll_s,
                                 clock=self.clock, sleep=self.sleep)
        if self.aggregator is not None:
            self.aggregator.poll()
        incarnation = max(
            (r.incarnation for r in self.replicas.values()), default=0)
        self.flightrec.emit("fleet_done", incarnation=incarnation)
        self.replicas.clear()
        self._m_replicas.set(0)
