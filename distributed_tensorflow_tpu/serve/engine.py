"""ServeEngine — the user-facing submit/step/stream loop.

Ties the pieces together: jit-compiled prefill and decode steps
(decode.py) over one resident cache — the PAGED block pool by default,
the slot-dense KVCache as the exact-parity fallback (kv_cache.py,
``paged=False``) — driven by the continuous-batching scheduler
(scheduler.py), with sampling.py choosing tokens. One engine ``step()``
is the serving analog of one train step:

1. **Admit.** Every queued request the scheduler can place into a free
   slot — paged admission additionally gated on free KV blocks — is
   admitted; paged admission also maps whatever prefix the block cache
   already holds (copy-on-write sharing).
2. **Prefill.** Paged: at most ONE fixed-size chunk per mid-prefill
   slot per step, so a long prompt never starves the resident decoders
   for more than one chunk. Dense: the whole prompt at once (one
   compiled program per prompt bucket). The final chunk samples the
   request's first token.
3. **Decode.** One fused decode step advances every decode-ready slot
   by one token ([num_slots, 1] inputs — idle and mid-prefill slots
   compute garbage that is never delivered and, on the paged path,
   write through an out-of-bounds sentinel so it lands nowhere).
4. **Deliver + evict.** Sampled tokens are appended via the scheduler,
   which evicts finished requests (EOS / max-new / max-len) so their
   slots — and their KV blocks — are re-admissible on the NEXT step's
   admit phase.

Everything device-side is shape-static; everything dynamic (queue
state, per-slot write indices, request lifetimes) lives host-side in
plain Python/numpy — the same host-drives/device-computes split as the
training loop.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import Transformer, TransformerConfig, make_init_fn
from ..obs import flightrec as flightrec_lib
from ..obs.registry import Registry
from . import decode as decode_lib
from . import sampling
from .kv_cache import (
    BlockAllocator,
    KVCache,
    NoFreeBlocks,
    PagedKVCache,
    init_cache,
    init_paged_cache,
)
from .scheduler import (
    FINISH_REASONS,
    Request,
    Scheduler,
)


@dataclasses.dataclass
class StepStats:
    """What one engine step did (tools/bench_serve.py aggregates these)."""

    admitted: int = 0
    decoded_slots: int = 0
    occupancy: float = 0.0
    #: prefill chunks run this step (paged engine; dense prefill is
    #: atomic and reports 0)
    prefill_chunks: int = 0
    #: (uid, token) pairs in delivery order — a uid can appear twice in
    #: one step (its prefill token AND its first decode token)
    tokens: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    finished: list[int] = dataclasses.field(default_factory=list)
    #: host wall-clock split of this step: prefill phase (all admits,
    #: compile-warm), decode phase (one fused step), and the whole call.
    #: Timings block on sampled-token transfer, so they are real compute
    #: latencies, not dispatch times.
    wall_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0


class ServeEngine:
    """KV-cached continuous-batching inference over a causal Transformer.

    >>> eng = ServeEngine.with_random_params(cfg, num_slots=4)
    >>> uid = eng.submit([5, 17, 3], max_new_tokens=16)
    >>> for tok in eng.stream([5, 17, 3]):
    ...     print(tok)
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        *,
        num_slots: int = 4,
        max_len: int | None = None,
        max_queue: int | None = None,
        cache_dtype=None,
        paged: bool = True,
        block_size: int = 16,
        num_blocks: int | None = None,
        prefill_chunk: int = 32,
        prefix_reuse: bool = True,
        spec_k: int = 0,
        spec_ngram: int = 4,
        paged_impl: str | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        registry: Registry | None = None,
        clock: Callable[[], float] = time.perf_counter,
        flightrec=None,
        reqtrace=None,
    ):
        if not cfg.causal:
            raise ValueError("ServeEngine requires a causal (decoder) model")
        if paged_impl is not None:
            # per-engine override of the paged-attention dispatch
            # (ops.attention.paged_attention impl=): the bench and the
            # parity gates pin "gather" / "fused" / "pallas" without
            # touching the model config they were handed
            cfg = dataclasses.replace(cfg, paged_attention_impl=paged_impl)
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if spec_k > 0 and not paged:
            raise ValueError(
                "speculative decoding (spec_k > 0) requires the paged "
                "engine: rollback is a block-table edit"
            )
        if spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, got {spec_ngram}")
        self.spec_k = spec_k
        self.spec_ngram = spec_ngram
        self.cfg = cfg
        self.params = params
        self.model = Transformer(cfg)
        M = cfg.max_len if max_len is None else max_len
        if M > cfg.max_len:
            raise ValueError(
                f"max_len={M} exceeds the model context window "
                f"(cfg.max_len={cfg.max_len})"
            )
        self.paged = paged
        self.prefix_reuse = prefix_reuse and paged
        if paged:
            if prefill_chunk < 1:
                raise ValueError("prefill_chunk must be >= 1")
            self.block_size = block_size
            self.prefill_chunk = prefill_chunk
            #: logical blocks per request = the per-request token budget
            self._mb = -(-M // block_size)
            if num_blocks is None:
                # default: same token capacity as the dense cache; pass
                # fewer blocks to trade worst-case headroom for memory
                num_blocks = num_slots * self._mb
            if num_blocks < self._mb:
                raise ValueError(
                    f"num_blocks={num_blocks} < ceil(max_len/block_size)="
                    f"{self._mb}: one request could exhaust the pool with "
                    f"no one left to preempt"
                )
            self.cache: PagedKVCache = init_paged_cache(
                cfg, num_blocks, block_size, dtype=cache_dtype
            )
            self.alloc = BlockAllocator(num_blocks, block_size)
            #: slot → physical block ids in logical order (host truth);
            #: the device-side table mirrors it, sentinel-padded
            self._blocks: list[list[int]] = [[] for _ in range(num_slots)]
            self._table = np.full((num_slots, self._mb), num_blocks,
                                  np.int32)
            #: past-the-table write position — routes a slot's K/V write
            #: out of bounds so the scatter drops it (idle / mid-prefill)
            self._oob = self._mb * block_size
            #: slot → next prefill position (chunked prefill in flight)
            self._pending: dict[int, int] = {}
            #: slot → all tokens known at admission (prompt + generated,
            #: the re-prefill source after a preemption)
            self._ptoks: dict[int, tuple[int, ...]] = {}
            self._evictions_seen = 0
            #: blocks promised to requests approved earlier in the same
            #: admit cycle (reset each step): the gate must not hand the
            #: same free blocks to two queue heads
            self._gate_reserved = 0
        else:
            # dense fallback: the PR-1 slot-dense cache, kept as the
            # exact-parity reference path (docs/serving.md)
            self.cache: KVCache = init_cache(
                cfg, num_slots, max_len=M, dtype=cache_dtype
            )
        self.clock = clock
        # one recorder feeds the scheduler's admit/evict events and the
        # engine's drain event, so the postmortem timeline interleaves
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        #: per-request span ledger (obs/reqtrace.py) shared with the
        #: scheduler; None = untraced. Only requests that entered with a
        #: router trace id (rid) emit spans — direct submissions don't.
        self.reqtrace = reqtrace
        self.sched = Scheduler(
            num_slots, M, clock=clock, max_queue=max_queue,
            flightrec=self.flightrec, reqtrace=reqtrace,
            admission_gate=self._admission_gate if paged else None,
        )
        self.temperature = temperature
        self.top_k = top_k
        self._rng = jax.random.PRNGKey(seed)
        # per-slot host state: cache write index and most recent token
        self._written = np.zeros(num_slots, np.int32)
        self._last = np.zeros(num_slots, np.int32)
        if paged:
            self._prefill_chunk_fn = decode_lib.jit_paged_prefill_chunk(
                self.model)
            self._decode = decode_lib.jit_paged_decode_step(self.model)
            self._copy_block = decode_lib.jit_copy_block()
            if spec_k > 0:
                self._verify = decode_lib.jit_paged_verify_step(self.model)
                #: host-side accept-rule randomness (temperature spec);
                #: numpy on purpose — the accept decision is host
                #: bookkeeping, a device categorical buys nothing
                self._spec_gen = np.random.default_rng(seed)
        else:
            self._prefill = decode_lib.jit_prefill(self.model)
            self._decode = decode_lib.jit_decode_step(self.model)
        # telemetry: one registry per engine by default (isolated,
        # mergeable upstream); pass obs.default_registry() to publish
        # into the process-wide scrape surface. Handles are resolved
        # once here — the decode hot loop only does .observe()/.inc().
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        self._m_queue_wait = r.histogram(
            "serve_queue_wait_seconds", "submit → slot admission")
        self._m_ttft = r.histogram(
            "serve_ttft_seconds", "submit → first token delivered")
        self._m_tpot = r.histogram(
            "serve_tpot_seconds",
            "mean per-output-token decode latency of a finished request")
        self._m_step = r.histogram(
            "serve_step_seconds", "one engine step (admit+prefill+decode)")
        self._m_prefill = r.histogram(
            "serve_prefill_seconds", "prefill phase of an engine step")
        self._m_decode = r.histogram(
            "serve_decode_seconds", "fused decode phase of an engine step")
        self._m_occupancy = r.gauge(
            "serve_occupancy", "active slots / num_slots at last decode")
        self._m_admitted = r.counter(
            "serve_admitted_total", "requests admitted into a slot")
        self._m_tokens = r.counter(
            "serve_tokens_total", "tokens delivered (prefill + decode)")
        self._m_finished = {
            reason: r.counter(
                "serve_finished_total", "finished requests by eviction reason",
                reason=reason)
            for reason in FINISH_REASONS
        }
        # paged-cache surface (docs/observability.md "Paged KV cache");
        # registered unconditionally so dashboards see zeros, not holes,
        # on a dense-fallback engine
        self._m_blocks_used = r.gauge(
            "kv_blocks_in_use", "physical KV blocks with refcount > 0")
        self._m_blocks_free = r.gauge(
            "kv_blocks_free", "physical KV blocks on the free list")
        self._m_block_evic = r.counter(
            "kv_block_evictions_total",
            "prefix-cache blocks evicted under pool pressure")
        self._m_reuse = r.counter(
            "prefix_reuse_hits_total",
            "physical blocks mapped from the shared-prefix cache at "
            "admission instead of being prefilled")
        self._m_chunks = r.counter(
            "prefill_chunks_total", "prefill chunks run (chunked prefill)")
        # speculative-decoding surface (docs/observability.md
        # "Speculative decoding") — unconditional, same zeros-not-holes
        # contract as the paged gauges above
        self._m_spec_prop = r.counter(
            "spec_tokens_proposed_total",
            "draft tokens proposed to the speculative verify step")
        self._m_spec_acc = r.counter(
            "spec_tokens_accepted_total",
            "draft tokens the speculative verify step accepted")
        self._m_spec_rate = r.gauge(
            "spec_acceptance_rate",
            "accepted / proposed draft tokens over the engine lifetime")
        #: engine-lifetime accept accounting behind the gauge
        self._spec_proposed = 0
        self._spec_accepted = 0
        if paged:
            self._sync_block_metrics()

    @classmethod
    def with_random_params(
        cls, cfg: TransformerConfig, *, seed: int = 0, **kw
    ) -> "ServeEngine":
        """Random-weight engine for demos/benches (examples/serve.py)."""
        params, _ = make_init_fn(Transformer(cfg), min(8, cfg.max_len))(
            jax.random.PRNGKey(seed)
        )
        return cls(cfg, params, seed=seed, **kw)

    # -- public API --------------------------------------------------------

    def submit(
        self,
        prompt: Iterable[int],
        max_new_tokens: int = 32,
        eos_id: int | None = None,
        deadline_s: float | None = None,
        priority: int = 0,
        rid: int | None = None,
    ) -> int:
        """Enqueue a request (raises ``scheduler.QueueFull`` under
        backpressure, ``scheduler.SchedulerClosed`` after drain).
        Higher ``priority`` residents are preempted LAST on block
        exhaustion (the serve fleet's lane tiering rides on this);
        ``rid`` carries the router trace id into the request ledger."""
        return self.sched.submit(prompt, max_new_tokens, eos_id,
                                 deadline_s=deadline_s, priority=priority,
                                 rid=rid)

    def cancel(self, uid: int) -> bool:
        """Cancel a queued or in-flight request (``FINISH_CANCELLED``);
        returns False if the uid is unknown or already finished."""
        req = self.sched.cancel(uid)
        if req is None:
            return False
        self._observe_finish(req, None)
        self._reconcile_slots()
        if self.paged:
            self._sync_block_metrics()
        return True

    def step(self) -> StepStats:
        """Enforce deadlines, admit newly placed requests, run at most
        ONE prefill chunk per mid-prefill slot (paged — so a long
        prompt never starves the resident decoders for more than one
        chunk; dense prefill stays atomic), then advance every
        decode-ready slot by one token. Returns per-step stats and
        records them into ``self.registry``."""
        stats = StepStats()
        t0 = self.clock()
        expired = self.sched.expire()
        for req in expired:
            self._observe_finish(req, stats)
        if expired:
            self._reconcile_slots()
        if self.paged:
            self._gate_reserved = 0  # fresh admit cycle
        placed = self.sched.admit()
        for slot, req in placed:
            stats.admitted += 1
            self._m_admitted.inc()
            if req.preemptions == 0:
                self._m_queue_wait.observe(req.t_admit - req.t_submit)
            if self.paged:
                self._begin_paged(slot, req)
        # occupancy counts every slot WORKING this step — decoding,
        # mid-chunked-prefill, or just admitted (even if its first
        # token finishes it before the step ends); measured here, after
        # admission and before any delivery, so a max_new=1 stream
        # still reads as a full batch
        stats.occupancy = (
            len(self.sched.active_slots()) / self.sched.num_slots
        )
        if self.paged:
            # one chunk per pending slot per step — the interleave bound
            for slot in sorted(self._pending):
                if slot in self._pending:  # preemption may drop peers
                    self._paged_prefill_step(slot, stats)
        else:
            for slot, req in placed:
                self._do_prefill(slot, req, stats)
        t1 = self.clock()
        active = self.sched.active_slots()
        if self.paged:
            active = [s for s in active if s not in self._pending]
        if active:
            self._do_decode(active, stats)
        t2 = self.clock()
        stats.prefill_s = t1 - t0
        stats.decode_s = t2 - t1
        stats.wall_s = t2 - t0
        self._m_step.observe(stats.wall_s)
        if stats.admitted or stats.prefill_chunks:
            self._m_prefill.observe(stats.prefill_s)
        if stats.decoded_slots:  # not a step whose decode preempted away
            self._m_decode.observe(stats.decode_s)
        if stats.occupancy:  # publish prefill-only steps too
            self._m_occupancy.set(stats.occupancy)
        if self.paged:
            self._sync_block_metrics()
        return stats

    def stream(
        self,
        prompt: Iterable[int],
        max_new_tokens: int = 32,
        eos_id: int | None = None,
        deadline_s: float | None = None,
    ) -> Iterator[int]:
        """Submit one request and yield its tokens as they are decoded
        (other queued requests keep making progress in the same steps).
        A ``deadline_s`` expiry simply ends the stream after whatever
        tokens made it out (``finish_reason`` on the Request says why)."""
        uid = self.submit(prompt, max_new_tokens, eos_id,
                          deadline_s=deadline_s)
        # hold the Request object itself: its identity is stable across
        # queue → slot → finished, and stays valid even if a concurrent
        # drain() hands the finished map to its caller — the stream can
        # still deliver the tokens drain() decoded, instead of KeyError
        req = self._find(uid)
        delivered = 0
        while True:
            self.step()
            while delivered < len(req.generated):
                yield req.generated[delivered]
                delivered += 1
            if req.done:
                self.sched.finished.pop(uid, None)  # delivered in full
                return

    def run(self) -> dict[int, Request]:
        """Drain queue + slots to completion; returns (and forgets)
        uid → Request, so repeated run() calls don't accumulate."""
        while self.sched.has_work:
            self.step()
        return self.sched.drain_finished()

    def drain(self) -> dict[int, Request]:
        """Graceful shutdown: stop admission (further ``submit`` raises
        ``SchedulerClosed``), cancel everything still queued, decode the
        resident requests to completion, and leave telemetry flushed
        (final occupancy 0, every request's terminal counter bumped).
        Returns (and forgets) uid → Request for everything finished."""
        for req in self.sched.close():
            self._observe_finish(req, None)
        # queue check: close() emptied it, but a paged preemption can
        # push a resident back to the queue head mid-drain
        while any(r is not None for r in self.sched.slots) \
                or self.sched.queue:
            self.step()
        self._reconcile_slots()
        if self.paged:
            # shutdown is the leak audit: drop the prefix cache's refs
            # too, so a clean drain leaves the allocator ALL-free
            self.alloc.flush_prefix_cache()
            self._sync_block_metrics()
        self._m_occupancy.set(0.0)
        done = self.sched.drain_finished()
        self.flightrec.emit("serve_drain", finished=len(done))
        return done

    # -- internals ---------------------------------------------------------

    def _park_idle_written(self) -> None:
        """Idle slots park their write index at 0 (the convention
        ``_deliver`` keeps for token-driven evictions); timeout/cancel
        evictions free slots outside ``append_token``, so re-park here."""
        for i, req in enumerate(self.sched.slots):
            if req is None:
                self._written[i] = 0

    def _reconcile_slots(self) -> None:
        """Bring engine host state into line with the scheduler after
        any out-of-band eviction (timeout, cancel, close): every slot
        the scheduler freed gives its blocks back and parks its write
        index — the no-leaked-blocks bottleneck for non-token-driven
        eviction paths."""
        if self.paged:
            for i, req in enumerate(self.sched.slots):
                if req is None and (self._blocks[i] or i in self._pending):
                    self._release_slot(i)
        self._park_idle_written()

    # -- paged internals ---------------------------------------------------

    def _sync_block_metrics(self) -> None:
        self._m_blocks_used.set(float(self.alloc.blocks_in_use))
        self._m_blocks_free.set(float(self.alloc.blocks_free))
        d = self.alloc.evictions - self._evictions_seen
        if d:
            self._m_block_evic.inc(d)
            self._evictions_seen = self.alloc.evictions

    def _mb_bucket(self, hi_blocks: int) -> int:
        """Table width (in blocks) to hand the jit'd step: the smallest
        power of two covering the widest live slot, capped at the full
        table. Dense attention pays ``max_len`` positions every step;
        the block table knows how few are actually mapped, so the fused
        kernels attend (and gather) only that — at the cost of one
        compiled program per bucket, ≤ log2(max_blocks)+1 in total, all
        hot after the first long request."""
        mbu = 1
        while mbu < hi_blocks:
            mbu *= 2
        return min(mbu, self._mb)

    def _admission_gate(self, req: Request) -> bool:
        """Admission is gated on KV capacity, not slot count: the
        request needs blocks for every position it will write through
        its first decode token — capped at ``max_len``, past which the
        scheduler finishes it before any write — minus what the prefix
        cache can supply. ``evictable`` cache blocks count as capacity
        (alloc reclaims them on demand), excluding the ones the match
        itself would pin; as a fallback the FULL need may be covered by
        evicting even the matched entries (reuse then degrades to
        re-prefill — and a block whose only other holder is the cache
        is resolved in place by ``_ensure_blocks``, never deadlocked
        on). ``_gate_reserved`` accounts for requests approved earlier
        in the SAME admit cycle, whose blocks are not yet taken."""
        T = len(req.prompt) + len(req.generated)
        need = -(-min(T + 1, self.sched.max_len) // self.block_size)
        m = self.alloc.peek_match(req.prompt) if self.prefix_reuse else 0
        free, ev = self.alloc.blocks_free, self.alloc.evictable()
        reserved = self._gate_reserved
        with_reuse = free + max(ev - m, 0) - reserved >= max(need - m, 1)
        without_reuse = free + ev - reserved >= max(need, 1)
        if with_reuse or without_reuse:
            self._gate_reserved += max(need - (m if with_reuse else 0), 1)
            return True
        return False

    def _release_slot(self, slot: int) -> None:
        """Give every block in ``slot``'s table back to the allocator
        (shared blocks just drop one ref) and reset the slot to the
        idle sentinel state."""
        for bid in self._blocks[slot]:
            self.alloc.decref(bid)
        self._blocks[slot] = []
        self._table[slot, :] = self.cache.num_blocks
        self._written[slot] = 0
        self._pending.pop(slot, None)
        self._ptoks.pop(slot, None)

    def _youngest_resident(self, exclude: int) -> int | None:
        """Preemption victim: the LOWEST-priority resident, youngest
        (highest uid) among equals — so batch-lane work absorbs block
        exhaustion before interactive traffic, and all-default
        priorities reproduce the original pure youngest-first policy."""
        best = None
        for i, req in enumerate(self.sched.slots):
            if req is None or i == exclude:
                continue
            if best is None:
                best = i
                continue
            cur = self.sched.slots[best]
            if (req.priority, -req.uid) < (cur.priority, -cur.uid):
                best = i
        return best

    def _paged_alloc(self, slot: int) -> int:
        """Allocate one block for ``slot``; on exhaustion, preempt the
        youngest OTHER resident back to the queue head (its blocks come
        home, it re-prefills later) and retry. Terminates: num_blocks >=
        ceil(max_len/block_size) guarantees a lone request always fits
        once the prefix cache and its peers have been drained."""
        while True:
            try:
                return self.alloc.alloc()
            except NoFreeBlocks:
                victim = self._youngest_resident(exclude=slot)
                if victim is None:
                    raise
                self.sched.preempt(victim)
                self._release_slot(victim)

    def _ensure_blocks(self, slot: int, start: int, end: int) -> None:
        """Make positions ``[start, end)`` of ``slot`` writable: append
        fresh blocks past the table's frontier, and copy-on-write any
        block about to be written whose refcount is > 1 (shared via
        prefix reuse) — the sharers keep the original, this slot gets a
        private device-side copy."""
        bs = self.block_size
        blocks = self._blocks[slot]
        for b in range(start // bs, (end - 1) // bs + 1):
            if b < len(blocks):
                bid = blocks[b]
                if self.alloc.refcount(bid) > 1:
                    try:
                        new = self._paged_alloc(slot)
                    except NoFreeBlocks:
                        # the pool cannot supply a copy and no one is
                        # preemptible, so the other holder must be the
                        # prefix cache itself: un-cache the block and
                        # write in place as sole owner instead
                        self.alloc.release_cached(bid)
                        if self.alloc.refcount(bid) != 1:
                            raise
                    else:
                        self.cache = self._copy_block(self.cache, bid, new)
                        self.alloc.decref(bid)
                        self.alloc.cow_copies += 1
                        blocks[b] = new
                        self._table[slot, b] = new
            else:
                new = self._paged_alloc(slot)
                blocks.append(new)
                self._table[slot, b] = new
            # in-place writes land below: weak registrations claiming
            # the written offsets are stale from here on
            self.alloc.note_write(blocks[b], max(start - b * bs, 0))

    def _begin_paged(self, slot: int, req: Request) -> None:
        """Admission bookkeeping for the paged path: map what the
        prefix cache already holds (never the last known position —
        its logits must be recomputed to sample the next token) and
        queue the rest for chunked prefill."""
        toks = tuple(req.prompt) + tuple(req.generated)
        blocks: list[int] = []
        matched = 0
        if self.prefix_reuse:
            blocks, matched = self.alloc.match_prefix(toks)
            matched = min(matched, len(toks) - 1)
            if blocks:
                self._m_reuse.inc(len(blocks))
        self._blocks[slot] = blocks
        self._table[slot, :] = self.cache.num_blocks
        self._table[slot, :len(blocks)] = blocks
        self._written[slot] = matched
        self._pending[slot] = matched
        self._ptoks[slot] = toks

    def _paged_prefill_step(self, slot: int, stats: StepStats) -> None:
        """Run ONE prefill chunk for ``slot``; on the final chunk,
        sample the first token, publish the prompt's blocks for prefix
        reuse, and hand the slot to the decode phase."""
        req = self.sched.slots[slot]
        toks = self._ptoks[slot]
        T = len(toks)
        start = self._pending[slot]
        end = min(start + self.prefill_chunk, T)
        self._ensure_blocks(slot, start, end)
        buf = np.zeros(self.prefill_chunk, np.int32)
        buf[: end - start] = toks[start:end]
        mbu = self._mb_bucket(len(self._blocks[slot]))
        logits, self.cache = self._prefill_chunk_fn(
            self.params, self.cache, jnp.asarray(self._table[slot, :mbu]),
            jnp.asarray(buf), start, end - start,
        )
        stats.prefill_chunks += 1
        self._m_chunks.inc()
        self.flightrec.emit("serve_prefill_chunk", uid=req.uid, slot=slot,
                            start=start, n=end - start)
        if self.reqtrace is not None and req.rid is not None:
            # one span per chunk: the waterfall shows where a long
            # prompt's prefill interleaved with the residents' decode
            self.reqtrace.transition(req.rid, "prefill_chunks",
                                     uid=req.uid, slot=slot,
                                     start=start, n=end - start)
        self._written[slot] = end
        if end < T:
            self._pending[slot] = end
            return
        del self._pending[slot]
        if self.prefix_reuse:
            P = len(req.prompt)
            n_prompt_blocks = -(-P // self.block_size)
            self.alloc.register_prefix(
                req.prompt, self._blocks[slot][:n_prompt_blocks]
            )
        tok = int(
            sampling.sample(
                logits, self._next_rng(),
                temperature=self.temperature, top_k=self.top_k,
            )
        )
        self._last[slot] = tok
        if self.reqtrace is not None and req.rid is not None:
            # prefill complete, first token of this residency sampled —
            # the request enters decode; this is also the replica-side
            # half of the sample→delivery clock anchor (the router's
            # matching decode_gap span opens strictly later)
            self.reqtrace.transition(req.rid, "decode_gap", uid=req.uid)
        self._deliver(slot, tok, stats)

    def _observe_finish(self, req: Request, stats: StepStats | None) -> None:
        """The ONE terminal observation per finished request, whatever
        ended it (token-driven eviction, timeout, cancel) — the PR-2
        invariant lives here and only here: every finished request
        contributes exactly one TTFT and one TPOT observation, so their
        counts equal Σ serve_finished_total. TPOT is the mean decode
        latency per output token (a single-token request has no decode
        interval → observes 0). A request aborted before its first token
        observes time-to-abort as TTFT — the latency the client actually
        experienced — and 0 TPOT; one aborted mid-decode already
        observed TTFT at first token and records its realized decode
        latency here."""
        if stats is not None:
            stats.finished.append(req.uid)
        self._m_finished[req.finish_reason].inc()
        if req.t_first_token is None:
            self._m_ttft.observe(req.t_finish - req.t_submit)
            self._m_tpot.observe(0.0)
        else:
            g = len(req.generated)
            self._m_tpot.observe(
                (req.t_finish - req.t_first_token) / max(g - 1, 1)
            )

    def _find(self, uid: int) -> Request:
        req = self.sched.finished.get(uid)
        if req is not None:
            return req
        for r in self.sched.slots:
            if r is not None and r.uid == uid:
                return r
        for r in self.sched.queue:
            if r.uid == uid:
                return r
        raise KeyError(f"unknown request uid {uid}")

    def _next_rng(self) -> jax.Array | None:
        if self.temperature <= 0.0:
            return None
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _deliver(self, slot: int, token: int, stats: StepStats) -> None:
        req = self.sched.slots[slot]
        stats.tokens.append((req.uid, token))
        self._m_tokens.inc()
        finished = self.sched.append_token(slot, token)
        if len(req.generated) == 1:
            self._m_ttft.observe(req.t_first_token - req.t_submit)
        if finished is not None:
            if self.paged:
                self._release_slot(slot)  # blocks home before slot reuse
            self._written[slot] = 0  # idle slots park their write index at 0
            self._observe_finish(finished, stats)

    def _do_prefill(self, slot: int, req: Request, stats: StepStats) -> None:
        P = len(req.prompt)
        bucket = min(decode_lib.prefill_bucket(P), self.cache.max_len)
        toks = np.zeros(bucket, np.int32)
        toks[:P] = req.prompt
        logits, self.cache = self._prefill(
            self.params, self.cache, slot, toks, P
        )
        tok = int(
            sampling.sample(
                logits, self._next_rng(),
                temperature=self.temperature, top_k=self.top_k,
            )
        )
        self._written[slot] = P
        self._last[slot] = tok
        if self.reqtrace is not None and req.rid is not None:
            self.reqtrace.transition(req.rid, "decode_gap", uid=req.uid)
        self._deliver(slot, tok, stats)

    def _draft(self, slot: int, k: int) -> list[int]:
        """N-gram prompt-lookup drafter (zero extra weights): find the
        longest suffix of the slot's known tokens (n = spec_ngram down
        to 1) that recurs earlier in prompt+generated, and propose the
        ``k`` tokens that followed its most recent earlier occurrence
        (short continuations repeat their last token out to ``k`` — a
        cheap bet that loops keep looping). No recurrence at all →
        propose the last token repeated, which costs nothing when
        rejected: a verify step always emits at least one token."""
        req = self.sched.slots[slot]
        ctx = list(req.prompt) + list(req.generated)
        for n in range(min(self.spec_ngram, len(ctx) - 1), 0, -1):
            pat = ctx[-n:]
            for i in range(len(ctx) - n - 1, -1, -1):
                if ctx[i: i + n] == pat:
                    cont = ctx[i + n: i + n + k]
                    while len(cont) < k:
                        cont.append(cont[-1])
                    return cont
        return [ctx[-1]] * k

    def _do_verify_decode(self, active: list[int], stats: StepStats) -> None:
        """Speculative decode step: draft ``spec_k`` tokens per slot,
        verify every slot's drafts in ONE chunked-prefill-shaped step,
        emit each slot's accepted prefix plus its correction/bonus
        token, and roll rejected suffixes back through the block table
        (kv_cache.BlockAllocator.release_tail — a refcount edit, never
        a device copy). Greedy emission is bit-identical to the
        non-speculative path (sampling.spec_verify_greedy docstring);
        the per-token ``_deliver`` loop keeps every scheduler/telemetry
        invariant of single-token decode, including discarding tokens
        drafted past a mid-burst finish."""
        bs = self.block_size
        cap = self._oob  # positions a slot's table can address
        drafts: dict[int, list[int]] = {}
        for slot in active:
            if self.sched.slots[slot] is None:
                continue  # a peer's _ensure_blocks preempted it
            w = int(self._written[slot])
            ks = max(min(self.spec_k, cap - 1 - w), 0)
            drafts[slot] = self._draft(slot, ks) if ks else []
            # writable span: the pending token at w plus every draft
            self._ensure_blocks(slot, w, w + len(drafts[slot]) + 1)
        active = [s for s in active if self.sched.slots[s] is not None]
        if not active:
            return
        stats.decoded_slots = len(active)
        S = self.spec_k + 1
        toks = np.zeros((self.sched.num_slots, S), np.int32)
        pos = np.full((self.sched.num_slots, S), self._oob, np.int32)
        for slot in active:
            d = drafts[slot]
            w = int(self._written[slot])
            toks[slot, 0] = self._last[slot]
            toks[slot, 1: 1 + len(d)] = d
            pos[slot, : 1 + len(d)] = np.arange(w, w + 1 + len(d))
        mbu = self._mb_bucket(max(len(self._blocks[s]) for s in active))
        logits, self.cache = self._verify(
            self.params, self.cache, jnp.asarray(self._table[:, :mbu]),
            jnp.asarray(toks), jnp.asarray(pos),
        )
        logits = np.asarray(logits)
        for slot in active:
            d = drafts[slot]
            w = int(self._written[slot])
            rows = logits[slot, : len(d) + 1]
            if self.temperature <= 0.0:
                emitted, accepted = sampling.spec_verify_greedy(rows, d)
            else:
                emitted, accepted = sampling.spec_verify_sample(
                    rows, d, self._spec_gen,
                    temperature=self.temperature, top_k=self.top_k,
                )
            # the verify wrote K/V at w..w+len(d); everything past
            # w+accepted is rejected-draft garbage — retreat the write
            # index over it (future writes overwrite in place, masked
            # until then) and give wholly-garbage tail blocks back
            self._written[slot] = w + accepted + 1
            keep = -(-int(self._written[slot]) // bs)
            if len(self._blocks[slot]) > keep:
                self.alloc.release_tail(self._blocks[slot], keep)
                self._table[slot, keep:] = self.cache.num_blocks
            self._spec_proposed += len(d)
            self._spec_accepted += accepted
            if d:
                self._m_spec_prop.inc(len(d))
            if accepted:
                self._m_spec_acc.inc(accepted)
            req = self.sched.slots[slot]
            req.spec_accepted += accepted
            self.flightrec.emit("serve_spec_step", uid=req.uid, slot=slot,
                                proposed=len(d), accepted=accepted)
            self._last[slot] = emitted[-1]
            for tok in emitted:
                self._deliver(slot, tok, stats)
                if self.sched.slots[slot] is None:
                    break  # finished mid-burst; trailing tokens discarded
        if self._spec_proposed:
            self._m_spec_rate.set(
                self._spec_accepted / self._spec_proposed)

    def _do_decode(self, active: list[int], stats: StepStats) -> None:
        if self.paged and self.spec_k > 0:
            self._do_verify_decode(active, stats)
            return
        if self.paged:
            # make each decoding slot's write position privately owned
            # (fresh block at a boundary, COW off a shared block);
            # allocation pressure may preempt the youngest residents, so
            # re-filter afterwards
            for slot in active:
                if self.sched.slots[slot] is not None:
                    w = int(self._written[slot])
                    self._ensure_blocks(slot, w, w + 1)
            active = [s for s in active if self.sched.slots[s] is not None]
            if not active:
                return
        stats.decoded_slots = len(active)
        if self.paged:
            # non-decoding slots write through the past-the-table
            # sentinel — their garbage token must not touch a live
            # (possibly shared) block
            lens = np.full(self.sched.num_slots, self._oob, np.int32)
            for slot in active:
                lens[slot] = self._written[slot]
            mbu = self._mb_bucket(
                max(len(self._blocks[s]) for s in active))
            logits, self.cache = self._decode(
                self.params, self.cache, jnp.asarray(self._table[:, :mbu]),
                jnp.asarray(self._last), jnp.asarray(lens),
            )
        else:
            logits, self.cache = self._decode(
                self.params, self.cache,
                jnp.asarray(self._last), jnp.asarray(self._written),
            )
        toks = np.asarray(
            sampling.sample(
                logits, self._next_rng(),
                temperature=self.temperature, top_k=self.top_k,
            )
        )
        for slot in active:
            self._written[slot] += 1  # the decode wrote k/v at the old index
            tok = int(toks[slot])
            self._last[slot] = tok
            self._deliver(slot, tok, stats)
