"""ServeEngine — the user-facing submit/step/stream loop.

Ties the pieces together: a jit-compiled prefill and decode step
(decode.py) over one resident KVCache (kv_cache.py), driven by the
continuous-batching scheduler (scheduler.py), with sampling.py choosing
tokens. One engine ``step()`` is the serving analog of one train step:

1. **Admit + prefill.** Every request the scheduler can place into a
   free slot is prefilled (one compiled program per prompt bucket), and
   its first token is sampled from the last prompt position's logits.
2. **Decode.** One fused decode step advances EVERY slot by one token
   ([num_slots, 1] inputs — idle slots compute garbage that is never
   delivered, keeping a single compiled program hot at any occupancy).
3. **Deliver + evict.** Sampled tokens are appended via the scheduler,
   which evicts finished requests (EOS / max-new / max-len) so their
   slots are re-admissible on the NEXT step's admit phase.

Everything device-side is shape-static; everything dynamic (queue
state, per-slot write indices, request lifetimes) lives host-side in
plain Python/numpy — the same host-drives/device-computes split as the
training loop.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import Transformer, TransformerConfig, make_init_fn
from ..obs import flightrec as flightrec_lib
from ..obs.registry import Registry
from . import decode as decode_lib
from . import sampling
from .kv_cache import KVCache, init_cache
from .scheduler import (
    FINISH_REASONS,
    Request,
    Scheduler,
)


@dataclasses.dataclass
class StepStats:
    """What one engine step did (tools/bench_serve.py aggregates these)."""

    admitted: int = 0
    decoded_slots: int = 0
    occupancy: float = 0.0
    #: (uid, token) pairs in delivery order — a uid can appear twice in
    #: one step (its prefill token AND its first decode token)
    tokens: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    finished: list[int] = dataclasses.field(default_factory=list)
    #: host wall-clock split of this step: prefill phase (all admits,
    #: compile-warm), decode phase (one fused step), and the whole call.
    #: Timings block on sampled-token transfer, so they are real compute
    #: latencies, not dispatch times.
    wall_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0


class ServeEngine:
    """KV-cached continuous-batching inference over a causal Transformer.

    >>> eng = ServeEngine.with_random_params(cfg, num_slots=4)
    >>> uid = eng.submit([5, 17, 3], max_new_tokens=16)
    >>> for tok in eng.stream([5, 17, 3]):
    ...     print(tok)
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        *,
        num_slots: int = 4,
        max_len: int | None = None,
        max_queue: int | None = None,
        cache_dtype=None,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        registry: Registry | None = None,
        clock: Callable[[], float] = time.perf_counter,
        flightrec=None,
    ):
        if not cfg.causal:
            raise ValueError("ServeEngine requires a causal (decoder) model")
        self.cfg = cfg
        self.params = params
        self.model = Transformer(cfg)
        self.cache: KVCache = init_cache(
            cfg, num_slots, max_len=max_len, dtype=cache_dtype
        )
        self.clock = clock
        # one recorder feeds the scheduler's admit/evict events and the
        # engine's drain event, so the postmortem timeline interleaves
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.sched = Scheduler(num_slots, self.cache.max_len, clock=clock,
                               max_queue=max_queue, flightrec=self.flightrec)
        self.temperature = temperature
        self.top_k = top_k
        self._rng = jax.random.PRNGKey(seed)
        # per-slot host state: cache write index and most recent token
        self._written = np.zeros(num_slots, np.int32)
        self._last = np.zeros(num_slots, np.int32)
        self._prefill = decode_lib.jit_prefill(self.model)
        self._decode = decode_lib.jit_decode_step(self.model)
        # telemetry: one registry per engine by default (isolated,
        # mergeable upstream); pass obs.default_registry() to publish
        # into the process-wide scrape surface. Handles are resolved
        # once here — the decode hot loop only does .observe()/.inc().
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        self._m_queue_wait = r.histogram(
            "serve_queue_wait_seconds", "submit → slot admission")
        self._m_ttft = r.histogram(
            "serve_ttft_seconds", "submit → first token delivered")
        self._m_tpot = r.histogram(
            "serve_tpot_seconds",
            "mean per-output-token decode latency of a finished request")
        self._m_step = r.histogram(
            "serve_step_seconds", "one engine step (admit+prefill+decode)")
        self._m_prefill = r.histogram(
            "serve_prefill_seconds", "prefill phase of an engine step")
        self._m_decode = r.histogram(
            "serve_decode_seconds", "fused decode phase of an engine step")
        self._m_occupancy = r.gauge(
            "serve_occupancy", "active slots / num_slots at last decode")
        self._m_admitted = r.counter(
            "serve_admitted_total", "requests admitted into a slot")
        self._m_tokens = r.counter(
            "serve_tokens_total", "tokens delivered (prefill + decode)")
        self._m_finished = {
            reason: r.counter(
                "serve_finished_total", "finished requests by eviction reason",
                reason=reason)
            for reason in FINISH_REASONS
        }

    @classmethod
    def with_random_params(
        cls, cfg: TransformerConfig, *, seed: int = 0, **kw
    ) -> "ServeEngine":
        """Random-weight engine for demos/benches (examples/serve.py)."""
        params, _ = make_init_fn(Transformer(cfg), min(8, cfg.max_len))(
            jax.random.PRNGKey(seed)
        )
        return cls(cfg, params, seed=seed, **kw)

    # -- public API --------------------------------------------------------

    def submit(
        self,
        prompt: Iterable[int],
        max_new_tokens: int = 32,
        eos_id: int | None = None,
        deadline_s: float | None = None,
    ) -> int:
        """Enqueue a request (raises ``scheduler.QueueFull`` under
        backpressure, ``scheduler.SchedulerClosed`` after drain)."""
        return self.sched.submit(prompt, max_new_tokens, eos_id,
                                 deadline_s=deadline_s)

    def cancel(self, uid: int) -> bool:
        """Cancel a queued or in-flight request (``FINISH_CANCELLED``);
        returns False if the uid is unknown or already finished."""
        req = self.sched.cancel(uid)
        if req is None:
            return False
        self._observe_finish(req, None)
        self._park_idle_written()
        return True

    def step(self) -> StepStats:
        """Enforce deadlines, admit + prefill newly placed requests,
        then advance every active slot by one decode token. Returns
        per-step stats and records them into ``self.registry``."""
        stats = StepStats()
        t0 = self.clock()
        expired = self.sched.expire()
        for req in expired:
            self._observe_finish(req, stats)
        if expired:
            self._park_idle_written()
        for slot, req in self.sched.admit():
            stats.admitted += 1
            self._m_admitted.inc()
            self._m_queue_wait.observe(req.t_admit - req.t_submit)
            self._do_prefill(slot, req, stats)
        t1 = self.clock()
        active = self.sched.active_slots()
        if active:
            self._do_decode(active, stats)
        t2 = self.clock()
        stats.prefill_s = t1 - t0
        stats.decode_s = t2 - t1
        stats.wall_s = t2 - t0
        self._m_step.observe(stats.wall_s)
        if stats.admitted:
            self._m_prefill.observe(stats.prefill_s)
        if active:
            self._m_decode.observe(stats.decode_s)
            self._m_occupancy.set(stats.occupancy)
        return stats

    def stream(
        self,
        prompt: Iterable[int],
        max_new_tokens: int = 32,
        eos_id: int | None = None,
        deadline_s: float | None = None,
    ) -> Iterator[int]:
        """Submit one request and yield its tokens as they are decoded
        (other queued requests keep making progress in the same steps).
        A ``deadline_s`` expiry simply ends the stream after whatever
        tokens made it out (``finish_reason`` on the Request says why)."""
        uid = self.submit(prompt, max_new_tokens, eos_id,
                          deadline_s=deadline_s)
        # hold the Request object itself: its identity is stable across
        # queue → slot → finished, and stays valid even if a concurrent
        # drain() hands the finished map to its caller — the stream can
        # still deliver the tokens drain() decoded, instead of KeyError
        req = self._find(uid)
        delivered = 0
        while True:
            self.step()
            while delivered < len(req.generated):
                yield req.generated[delivered]
                delivered += 1
            if req.done:
                self.sched.finished.pop(uid, None)  # delivered in full
                return

    def run(self) -> dict[int, Request]:
        """Drain queue + slots to completion; returns (and forgets)
        uid → Request, so repeated run() calls don't accumulate."""
        while self.sched.has_work:
            self.step()
        return self.sched.drain_finished()

    def drain(self) -> dict[int, Request]:
        """Graceful shutdown: stop admission (further ``submit`` raises
        ``SchedulerClosed``), cancel everything still queued, decode the
        resident requests to completion, and leave telemetry flushed
        (final occupancy 0, every request's terminal counter bumped).
        Returns (and forgets) uid → Request for everything finished."""
        for req in self.sched.close():
            self._observe_finish(req, None)
        while any(r is not None for r in self.sched.slots):
            self.step()
        self._park_idle_written()
        self._m_occupancy.set(0.0)
        done = self.sched.drain_finished()
        self.flightrec.emit("serve_drain", finished=len(done))
        return done

    # -- internals ---------------------------------------------------------

    def _park_idle_written(self) -> None:
        """Idle slots park their write index at 0 (the convention
        ``_deliver`` keeps for token-driven evictions); timeout/cancel
        evictions free slots outside ``append_token``, so re-park here."""
        for i, req in enumerate(self.sched.slots):
            if req is None:
                self._written[i] = 0

    def _observe_finish(self, req: Request, stats: StepStats | None) -> None:
        """The ONE terminal observation per finished request, whatever
        ended it (token-driven eviction, timeout, cancel) — the PR-2
        invariant lives here and only here: every finished request
        contributes exactly one TTFT and one TPOT observation, so their
        counts equal Σ serve_finished_total. TPOT is the mean decode
        latency per output token (a single-token request has no decode
        interval → observes 0). A request aborted before its first token
        observes time-to-abort as TTFT — the latency the client actually
        experienced — and 0 TPOT; one aborted mid-decode already
        observed TTFT at first token and records its realized decode
        latency here."""
        if stats is not None:
            stats.finished.append(req.uid)
        self._m_finished[req.finish_reason].inc()
        if req.t_first_token is None:
            self._m_ttft.observe(req.t_finish - req.t_submit)
            self._m_tpot.observe(0.0)
        else:
            g = len(req.generated)
            self._m_tpot.observe(
                (req.t_finish - req.t_first_token) / max(g - 1, 1)
            )

    def _find(self, uid: int) -> Request:
        req = self.sched.finished.get(uid)
        if req is not None:
            return req
        for r in self.sched.slots:
            if r is not None and r.uid == uid:
                return r
        for r in self.sched.queue:
            if r.uid == uid:
                return r
        raise KeyError(f"unknown request uid {uid}")

    def _next_rng(self) -> jax.Array | None:
        if self.temperature <= 0.0:
            return None
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _deliver(self, slot: int, token: int, stats: StepStats) -> None:
        req = self.sched.slots[slot]
        stats.tokens.append((req.uid, token))
        self._m_tokens.inc()
        finished = self.sched.append_token(slot, token)
        if len(req.generated) == 1:
            self._m_ttft.observe(req.t_first_token - req.t_submit)
        if finished is not None:
            self._written[slot] = 0  # idle slots park their write index at 0
            self._observe_finish(finished, stats)

    def _do_prefill(self, slot: int, req: Request, stats: StepStats) -> None:
        P = len(req.prompt)
        bucket = min(decode_lib.prefill_bucket(P), self.cache.max_len)
        toks = np.zeros(bucket, np.int32)
        toks[:P] = req.prompt
        logits, self.cache = self._prefill(
            self.params, self.cache, slot, toks, P
        )
        tok = int(
            sampling.sample(
                logits, self._next_rng(),
                temperature=self.temperature, top_k=self.top_k,
            )
        )
        self._written[slot] = P
        self._last[slot] = tok
        self._deliver(slot, tok, stats)

    def _do_decode(self, active: list[int], stats: StepStats) -> None:
        stats.decoded_slots = len(active)
        stats.occupancy = len(active) / self.sched.num_slots
        logits, self.cache = self._decode(
            self.params, self.cache,
            jnp.asarray(self._last), jnp.asarray(self._written),
        )
        toks = np.asarray(
            sampling.sample(
                logits, self._next_rng(),
                temperature=self.temperature, top_k=self.top_k,
            )
        )
        for slot in active:
            self._written[slot] += 1  # the decode wrote k/v at the old index
            tok = int(toks[slot])
            self._last[slot] = tok
            self._deliver(slot, tok, stats)
