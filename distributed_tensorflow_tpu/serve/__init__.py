"""TPU-native inference serving: KV-cached decode with continuous batching.

The fifth subsystem — the first that makes the framework an inference
stack rather than a trainer. Composition over the existing layers, per
the TF-Replicator thin-layer lesson (PAPERS.md): the cache is an
ordinary pytree placed by parallel/sharding.py rules, the decode path is
the SAME ``models.Transformer`` with a ``kv_cache`` argument, attention
falls back to the masked dense form where the flash kernel doesn't apply
(ops.attention.cached_attention), and the engine is a host-drives/
device-computes loop like train/loop.py. Above the single engine sits
the serve FLEET (fleet.py + router.py): N replica engines behind a
prefix-aware, SLO-laned router under heartbeat supervision — the
serving twin of resilience/fleet.py. See docs/serving.md.
"""

from .decode import (  # noqa: F401
    copy_block,
    decode_step,
    jit_copy_block,
    jit_decode_step,
    jit_paged_decode_step,
    jit_paged_prefill_chunk,
    jit_prefill,
    paged_decode_step,
    paged_prefill_chunk,
    prefill,
    prefill_bucket,
)
from .engine import ServeEngine, StepStats  # noqa: F401
from .fleet import (  # noqa: F401
    EngineBridge,
    LocalReplica,
    ServeFleetExhausted,
    ServeFleetSupervisor,
    SubprocessReplica,
)
from .kv_cache import (  # noqa: F401
    CACHE_LOGICAL,
    PAGED_CACHE_LOGICAL,
    BlockAllocator,
    KVCache,
    NoFreeBlocks,
    PagedKVCache,
    cache_specs,
    init_cache,
    init_paged_cache,
    paged_cache_specs,
    shard_cache,
    shard_paged_cache,
)
from .router import (  # noqa: F401
    LANE_BATCH,
    LANE_INTERACTIVE,
    LANES,
    FleetRequest,
    Router,
    UnknownLane,
)
from .sampling import sample  # noqa: F401
from .scheduler import (  # noqa: F401
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_MAX_LEN,
    FINISH_MAX_NEW,
    FINISH_REASONS,
    FINISH_TIMEOUT,
    QueueFull,
    Request,
    Scheduler,
    SchedulerClosed,
)
