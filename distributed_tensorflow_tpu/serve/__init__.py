"""TPU-native inference serving: KV-cached decode with continuous batching.

The fifth subsystem — the first that makes the framework an inference
stack rather than a trainer. Composition over the existing layers, per
the TF-Replicator thin-layer lesson (PAPERS.md): the cache is an
ordinary pytree placed by parallel/sharding.py rules, the decode path is
the SAME ``models.Transformer`` with a ``kv_cache`` argument, attention
falls back to the masked dense form where the flash kernel doesn't apply
(ops.attention.cached_attention), and the engine is a host-drives/
device-computes loop like train/loop.py. See docs/serving.md.
"""

from .decode import (  # noqa: F401
    decode_step,
    jit_decode_step,
    jit_prefill,
    prefill,
    prefill_bucket,
)
from .engine import ServeEngine, StepStats  # noqa: F401
from .kv_cache import (  # noqa: F401
    CACHE_LOGICAL,
    KVCache,
    cache_specs,
    init_cache,
    shard_cache,
)
from .sampling import sample  # noqa: F401
from .scheduler import (  # noqa: F401
    FINISH_CANCELLED,
    FINISH_EOS,
    FINISH_MAX_LEN,
    FINISH_MAX_NEW,
    FINISH_REASONS,
    FINISH_TIMEOUT,
    QueueFull,
    Request,
    Scheduler,
    SchedulerClosed,
)
