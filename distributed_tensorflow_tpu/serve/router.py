"""Fleet-front router — SLO lanes, prefix-aware placement, survivor
re-prefill (docs/serving.md "Serve fleet").

The router is the request-side half of the serve fleet: it owns every
request NOT currently resident on a replica, and decides (a) *when* a
request is offered to the fleet (lane order + per-replica outstanding
caps = admission/backpressure) and (b) *where* it lands (prefix-aware
or random placement). It is deliberately jax-free and engine-free —
replicas are just integer ids with a capacity; the supervisor
(serve/fleet.py) bridges dispatch orders to real engines — so every
routing invariant is testable without a model.

State machine of one request (``FleetRequest``)::

    submit ──> queued(lane) ──dispatch──> in-flight(replica) ──> finished
                   ^                           │
                   └──── requeue_replica ──────┘   (replica died; back
                         at the HEAD of its lane, original FIFO order)

- **Lanes.** Two disjoint FIFO queues, ``interactive`` and ``batch``.
  Dispatch drains interactive completely before offering batch, and
  batch rides at engine priority 0 vs interactive 1 — so on a replica
  under block pressure the batch lane absorbs preemption first
  (engine._youngest_resident picks lowest priority), and under fleet
  backpressure batch is the lane that waits.
- **Prefix-aware placement.** Requests carry ``prefix_len`` — the
  length of their shared system prompt. The first request of a prefix
  picks the least-loaded replica and pins the prefix there; later
  requests follow it while it stays live (a hit: the replica's LRU
  prefix cache already holds those blocks, counted by the engine as
  ``prefix_reuse_hits_total`` and here as ``router_prefix_hits_total``).
  ``policy="random"`` is the control arm: seeded uniform placement over
  replicas with capacity, same admission order.
- **Death → requeue → re-prefill.** When the supervisor declares a
  replica dead it calls ``requeue_replica``: that replica's in-flight
  requests go back to the HEAD of their lanes in original dispatch
  order, each carrying the tokens already streamed to the client. The
  next dispatch re-prefills ``prompt + delivered`` on a survivor with
  the remaining token budget — exactly the engine's own preemption
  path (serve/engine.py re-prefills prompt+generated), one level up.
  Greedy decode is deterministic, so the resumed stream continues the
  uncontended stream bit-identically.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Iterable, Sequence

from collections import deque

from ..obs import flightrec as flightrec_lib
from ..obs.registry import Registry, default_registry

logger = logging.getLogger(__name__)

#: SLO lanes (closed set — the scheduler's admission seam and the
#: observability labels both key on these literals)
LANE_INTERACTIVE = "interactive"
LANE_BATCH = "batch"
LANES = (LANE_INTERACTIVE, LANE_BATCH)

#: engine-level priority each lane submits at: interactive residents
#: are preempted LAST on block exhaustion (engine._youngest_resident)
LANE_PRIORITY = {LANE_INTERACTIVE: 1, LANE_BATCH: 0}

#: metric names (documented in docs/observability.md "Serve fleet")
ROUTER_REQUESTS_TOTAL = "router_requests_total"
ROUTER_DISPATCHES_TOTAL = "router_dispatches_total"
ROUTER_REQUEUES_TOTAL = "router_requeues_total"
ROUTER_PREFIX_HITS_TOTAL = "router_prefix_hits_total"
ROUTER_QUEUE_DEPTH = "router_queue_depth"
ROUTER_INFLIGHT = "router_inflight"
ROUTER_TTFT_SECONDS = "router_ttft_seconds"
ROUTER_TPOT_SECONDS = "router_tpot_seconds"


class UnknownLane(ValueError):
    """Lane label outside the closed set LANES."""


@dataclasses.dataclass
class FleetRequest:
    """One routed request across its whole fleet lifetime — survives
    replica deaths (``delivered`` is the resume point)."""

    rid: int
    prompt: tuple[int, ...]
    max_new_tokens: int
    lane: str = LANE_INTERACTIVE
    #: length of the shared system-prompt prefix (0 = no shared prefix);
    #: the placement key is ``prompt[:prefix_len]``
    prefix_len: int = 0
    eos_id: int | None = None
    #: tokens already streamed to the client — on re-dispatch these ride
    #: in the prompt (re-prefill) and shrink the remaining budget
    delivered: list[int] = dataclasses.field(default_factory=list)
    #: current replica (None while queued), and dispatch bookkeeping
    replica: int | None = None
    requeues: int = 0
    finish_reason: str | None = None
    # lifecycle timestamps (router clock): TTFT/TPOT are measured HERE,
    # across deaths — a requeue does not reset t_submit, so the tail a
    # client actually sees (including the re-prefill detour) is what
    # the lane histograms record
    t_submit: float | None = None
    t_first_token: float | None = None
    t_finish: float | None = None

    @property
    def done(self) -> bool:
        return self.finish_reason is not None

    @property
    def prefix(self) -> tuple[int, ...]:
        return self.prompt[: self.prefix_len]

    def payload(self) -> dict:
        """The dispatch order a replica executes: re-prefill everything
        the client has already seen, generate only the remainder."""
        return {
            "rid": self.rid,
            "prompt": list(self.prompt) + list(self.delivered),
            "max_new_tokens": self.max_new_tokens - len(self.delivered),
            "eos_id": self.eos_id,
            "priority": LANE_PRIORITY[self.lane],
            "lane": self.lane,
            # which dispatch generation this order belongs to — the
            # replica's ingest span copies it, making (rid, requeue) the
            # pair key the request-ledger clock alignment anchors on
            "requeues": self.requeues,
        }


class Router:
    """Lane-ordered, placement-aware request front for N replicas.

    The router never talks to an engine: ``dispatch`` RETURNS
    ``(replica, FleetRequest)`` orders and the caller (the supervisor)
    delivers them, then feeds replica output back through
    ``on_token``/``on_finish`` and deaths through ``requeue_replica``.
    Single-threaded by design — the supervisor's pump loop is the only
    caller, so ordering is deterministic.
    """

    def __init__(self, *, policy: str = "prefix",
                 max_outstanding: int = 4, seed: int = 0,
                 registry: Registry | None = None, flightrec=None,
                 clock: Callable[[], float] = time.monotonic,
                 reqtrace=None):
        if policy not in ("prefix", "random"):
            raise ValueError(f"unknown placement policy {policy!r}")
        if max_outstanding < 1:
            raise ValueError("max_outstanding must be >= 1")
        self.policy = policy
        #: per-replica cap on dispatched-but-unfinished requests — the
        #: fleet-level backpressure knob (replica engines additionally
        #: gate admission on actual KV blocks)
        self.max_outstanding = max_outstanding
        self.clock = clock  # injectable for deterministic latency tests
        self._rng = random.Random(seed)  # seeded: placement is replayable
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        #: per-request span ledger (obs/reqtrace.py), None = untraced —
        #: the router-process side of the end-to-end request trace
        self.reqtrace = reqtrace
        r = registry if registry is not None else default_registry()
        self.registry = r
        self.lanes: dict[str, deque[FleetRequest]] = {
            lane: deque() for lane in LANES}
        #: rid → request, for every request not yet finished
        self.requests: dict[int, FleetRequest] = {}
        #: replica → rids in dispatch order (the order requeue preserves)
        self.outstanding: dict[int, list[int]] = {}
        self.finished: dict[int, FleetRequest] = {}
        self._next_rid = 0
        #: prefix → home replica (prefix policy); entries for dead
        #: replicas are repinned on the next dispatch of that prefix
        self._prefix_home: dict[tuple[int, ...], int] = {}
        #: True while the order being emitted (re)pinned its prefix —
        #: a first placement, not a cache-warm hit
        self._fresh_pin = False
        self._m_requests = {
            lane: r.counter(ROUTER_REQUESTS_TOTAL,
                            "requests accepted by the router", lane=lane)
            for lane in LANES
        }
        self._m_dispatches = {
            lane: r.counter(ROUTER_DISPATCHES_TOTAL,
                            "dispatch orders issued to replicas (requeued "
                            "requests dispatch again)", lane=lane)
            for lane in LANES
        }
        self._m_requeues = r.counter(
            ROUTER_REQUEUES_TOTAL,
            "in-flight requests returned to their lane head by a "
            "replica death")
        self._m_prefix_hits = r.counter(
            ROUTER_PREFIX_HITS_TOTAL,
            "dispatches placed on the live home replica of their "
            "shared prefix")
        self._m_depth = {
            lane: r.gauge(ROUTER_QUEUE_DEPTH,
                          "requests waiting in the lane", lane=lane)
            for lane in LANES
        }
        self._m_inflight = r.gauge(
            ROUTER_INFLIGHT, "requests dispatched and not yet finished")
        self._m_ttft = {
            lane: r.histogram(ROUTER_TTFT_SECONDS,
                              "seconds from router submit to first "
                              "delivered token, across replica deaths",
                              lane=lane)
            for lane in LANES
        }
        self._m_tpot = {
            lane: r.histogram(ROUTER_TPOT_SECONDS,
                              "seconds per generated token after the "
                              "first (decode cadence)", lane=lane)
            for lane in LANES
        }

    # -- intake ------------------------------------------------------------

    def submit(self, prompt: Iterable[int], max_new_tokens: int = 32,
               *, lane: str = LANE_INTERACTIVE, prefix_len: int = 0,
               eos_id: int | None = None) -> int:
        """Queue a request on its lane; returns its rid."""
        if lane not in LANES:
            raise UnknownLane(f"lane {lane!r} not in {LANES}")
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0 <= prefix_len <= len(prompt):
            raise ValueError(f"prefix_len {prefix_len} outside the prompt")
        req = FleetRequest(self._next_rid, prompt, int(max_new_tokens),
                           lane=lane, prefix_len=int(prefix_len),
                           eos_id=eos_id, t_submit=self.clock())
        self._next_rid += 1
        self.requests[req.rid] = req
        self.lanes[lane].append(req)
        self._m_requests[lane].inc()
        if self.reqtrace is not None:
            self.reqtrace.transition(req.rid, "queue_wait", lane=lane)
        self._sync_gauges()
        return req.rid

    # -- replica membership ------------------------------------------------

    def add_replica(self, replica: int) -> None:
        """A replica joined (launch or elastic scale-up): it becomes a
        placement target on the very next ``dispatch`` — no drain."""
        self.outstanding.setdefault(int(replica), [])

    def remove_replica(self, replica: int) -> None:
        """Forget a replica WITHOUT requeueing (clean scale-down after
        its outstanding set drained). Use ``requeue_replica`` for
        deaths."""
        left = self.outstanding.pop(int(replica), [])
        if left:
            raise RuntimeError(
                f"replica {replica} removed with {len(left)} in-flight "
                f"requests; requeue_replica is the death path")
        self._prefix_home = {p: w for p, w in self._prefix_home.items()
                             if w != replica}

    # -- placement + dispatch ----------------------------------------------

    def dispatch(self) -> list[tuple[int, FleetRequest]]:
        """Drain the lanes onto replicas with capacity: ALL of
        interactive before ANY of batch (batch is the lane that waits
        under fleet backpressure). Returns the issued orders; the
        caller delivers each payload to its replica."""
        orders: list[tuple[int, FleetRequest]] = []
        for lane in LANES:  # interactive first — the SLO tier order
            q = self.lanes[lane]
            while q:
                target = self._place(q[0])
                if target is None:
                    break  # no capacity: everything behind the head waits
                req = q.popleft()
                req.replica = target
                self.outstanding[target].append(req.rid)
                self._m_dispatches[lane].inc()
                self.flightrec.emit(
                    "serve_route", rid=req.rid, lane=lane, replica=target,
                    hit=bool(req.prefix_len
                             and self._prefix_home.get(req.prefix) == target
                             and not self._fresh_pin))
                if self.reqtrace is not None:
                    # requeue attr = dispatch generation: pairs this span
                    # with the replica's ingest span for clock alignment
                    self.reqtrace.transition(
                        req.rid, "route", replica=target, lane=lane,
                        requeue=req.requeues)
                orders.append((target, req))
        self._sync_gauges()
        return orders

    def _place(self, req: FleetRequest) -> int | None:
        """Pick a live replica with capacity for ``req`` (None = none).
        Sets ``self._fresh_pin`` when a prefix was (re)pinned rather
        than followed — the distinction between a hit and a first
        placement."""
        self._fresh_pin = False
        free = [w for w, rids in sorted(self.outstanding.items())
                if len(rids) < self.max_outstanding]
        if not free:
            return None
        if self.policy == "random":
            return self._rng.choice(free)
        if req.prefix_len:
            home = self._prefix_home.get(req.prefix)
            if home is not None and home in self.outstanding:
                if home not in free:
                    return None  # wait for the home replica, keep warmth
                self._m_prefix_hits.inc()
                return home
            # first placement (or the home died): pin to least loaded
            target = min(free, key=lambda w: (len(self.outstanding[w]), w))
            self._prefix_home[req.prefix] = target
            self._fresh_pin = True
            return target
        return min(free, key=lambda w: (len(self.outstanding[w]), w))

    # -- replica feedback --------------------------------------------------

    def on_token(self, rid: int, token: int) -> None:
        """One generated token reached the client."""
        req = self.requests[rid]
        if req.t_first_token is None:
            req.t_first_token = self.clock()
            self._m_ttft[req.lane].observe(req.t_first_token - req.t_submit)
        req.delivered.append(int(token))
        if self.reqtrace is not None:
            # one span per delivered token: the gaps between them ARE
            # the client-visible decode cadence (TPOT attribution)
            self.reqtrace.transition(rid, "decode_gap",
                                     n=len(req.delivered))

    def on_finish(self, rid: int, reason: str) -> None:
        """The replica evicted the request as finished."""
        req = self.requests.pop(rid)
        req.finish_reason = reason
        req.t_finish = self.clock()
        if req.replica is not None:
            self.outstanding[req.replica].remove(rid)
        req.replica = None
        if req.t_first_token is not None and len(req.delivered) > 1:
            self._m_tpot[req.lane].observe(
                (req.t_finish - req.t_first_token)
                / (len(req.delivered) - 1))
        if self.reqtrace is not None:
            self.reqtrace.finish(rid, reason)
        self.finished[rid] = req
        self._sync_gauges()

    def requeue_replica(self, replica: int) -> list[int]:
        """The death path: every request in flight on ``replica`` goes
        back to the HEAD of its lane, original dispatch order preserved
        (FIFO within the lane survives the death), ready to re-prefill
        on a survivor. Returns the requeued rids."""
        rids = self.outstanding.pop(int(replica), [])
        per_lane: dict[str, list[FleetRequest]] = {l: [] for l in LANES}
        for rid in rids:
            req = self.requests[rid]
            req.replica = None
            req.requeues += 1
            per_lane[req.lane].append(req)
            self._m_requeues.inc()
            self.flightrec.emit(
                "serve_requeue", rid=rid, lane=req.lane, replica=replica,
                delivered=len(req.delivered))
            if self.reqtrace is not None:
                self.reqtrace.transition(
                    rid, "requeue_reprefill", replica=replica,
                    delivered=len(req.delivered), cause="replica_dead")
        for lane, reqs in per_lane.items():
            # extendleft reverses, so feed it reversed dispatch order:
            # the queue head ends up [oldest, ..., newest, prior queue]
            self.lanes[lane].extendleft(reversed(reqs))
        # drop the dead replica's prefix pins: the next dispatch of each
        # prefix repins it on a survivor (and counts no false hit)
        self._prefix_home = {p: w for p, w in self._prefix_home.items()
                             if w != replica}
        self._sync_gauges()
        return rids

    # -- introspection -----------------------------------------------------

    @property
    def idle(self) -> bool:
        """No request queued or in flight."""
        return not self.requests

    def queued(self, lane: str) -> int:
        return len(self.lanes[lane])

    def inflight(self) -> int:
        return sum(len(v) for v in self.outstanding.values())

    def _sync_gauges(self) -> None:
        for lane in LANES:
            self._m_depth[lane].set(len(self.lanes[lane]))
        self._m_inflight.set(self.inflight())
