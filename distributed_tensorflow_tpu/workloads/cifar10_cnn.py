"""Workload 2 — CIFAR-10 CNN, sync data-parallel ×8 (BASELINE.json:8).

The reference's SyncReplicasOptimizer showcase (accumulator + token-queue
protocol, SURVEY.md §3.1); here the same semantics are one psum on the data
axis."""

from __future__ import annotations

from ..data import DataConfig, make_dataset
from ..models import CNN, CNNConfig, common
from ..parallel import MeshSpec
from ..train import OptimizerConfig
from .runner import RunConfig, TrainSection, WorkloadParts


def default_config() -> RunConfig:
    return RunConfig(
        workload="cifar10_cnn",
        model=CNNConfig(channels=(32, 64, 128), num_classes=10),
        mesh=MeshSpec(data=8),
        data=DataConfig(
            dataset="synthetic", global_batch_size=256,
            image_size=32, channels=3, num_classes=10,
        ),
        optimizer=OptimizerConfig(
            name="momentum", learning_rate=0.05, momentum=0.9,
            schedule="cosine", total_steps=2000,
        ),
        train=TrainSection(num_steps=2000, log_every=100),
    )


def build(cfg: RunConfig, mesh=None) -> WorkloadParts:
    model = CNN(cfg.model)
    input_shape = (cfg.data.image_size, cfg.data.image_size, cfg.data.channels)
    from ..models.cnn import flops_per_example

    return WorkloadParts(
        init_fn=common.make_init_fn(model, input_shape),
        loss_fn=common.classification_loss_fn(model),
        eval_fn=common.classification_eval_fn(model),
        dataset_fn=lambda start: make_dataset(cfg.data, index_offset=start),
        eval_dataset_fn=lambda n: make_dataset(
            cfg.data, n, index_offset=10**6, train=False),
        flops_per_step=flops_per_example(cfg.model, cfg.data.image_size)
        * cfg.data.global_batch_size,
        batch_size=cfg.data.global_batch_size,
    )
