"""Workload 4 — BERT-base MLM pretraining, pod-scale config
(BASELINE.json:10).

Reference analog: the harness's BERT script — PS/worker sync replicas at
512 tokens (SURVEY.md §2a). TPU-native: one jit SPMD step over a
data×fsdp×model mesh; tensor parallelism via the megatron path rules
(models/transformer.TP_PATH_RULES), optional sequence parallelism
(cfg.model.seq_impl + mesh seq axis) for long-context variants
(SURVEY.md §5.7: 512-token baseline doesn't need SP; the plumbing is
first-class here and gated by config)."""

from __future__ import annotations

from ..data import TextDataConfig, make_text_dataset
from ..models import transformer as tfm
from ..parallel import MeshSpec
from ..train import OptimizerConfig
from ..utils import flops as flops_lib
from .runner import RunConfig, TrainSection, WorkloadParts


def default_config() -> RunConfig:
    model = tfm.bert_base()
    return RunConfig(
        workload="bert_pretrain",
        model=model,
        mesh=MeshSpec(data=-1),
        data=TextDataConfig(
            dataset="synthetic_mlm", global_batch_size=256,
            seq_len=model.max_len, vocab_size=model.vocab_size,
        ),
        optimizer=OptimizerConfig(
            name="adamw", learning_rate=1e-4, weight_decay=0.01,
            warmup_steps=1000, schedule="linear", total_steps=10000,
        ),
        train=TrainSection(num_steps=10000, log_every=100),
    )


def build(cfg: RunConfig, mesh=None) -> WorkloadParts:
    mcfg: tfm.TransformerConfig = cfg.model
    if cfg.data.seq_len > mcfg.max_len:
        raise ValueError(
            f"data.seq_len={cfg.data.seq_len} exceeds model.max_len={mcfg.max_len}"
        )
    if cfg.data.vocab_size != mcfg.vocab_size:
        # out-of-range ids would be silently clamped by jnp.take under jit
        raise ValueError(
            f"data.vocab_size={cfg.data.vocab_size} != "
            f"model.vocab_size={mcfg.vocab_size}"
        )
    fwd_flops = tfm.flops_per_example(mcfg, cfg.data.seq_len)
    common = dict(
        dataset_fn=lambda start: make_text_dataset(cfg.data, index_offset=start),
        flops_per_step=fwd_flops * cfg.data.global_batch_size,
        batch_size=cfg.data.global_batch_size,
    )

    from ..parallel import mesh as mesh_lib

    pipe = mesh.shape.get(mesh_lib.PIPE, 1) if mesh is not None else 1
    if pipe > 1:
        # --mesh.pipe=S engages the pipelined family (parallel/pipeline.py
        # schedule; deterministic — dropout off inside the island). A
        # model axis on top runs manual megatron TP inside each stage
        # (PP×TP, Block.tp_shards). Stacked [S(,V),lc,...] leaves shard
        # via explicit specs instead of path rules; FSDP on the stacked
        # layout is not composed here.
        import jax

        tp = mesh.shape.get(mesh_lib.MODEL, 1) > 1
        n_virtual = cfg.train.pipeline_virtual
        n_micro = cfg.train.pipeline_microbatches or 2 * pipe * n_virtual
        init_fn = tfm.make_pipelined_init_fn(
            mcfg, n_stages=pipe, seq_len=cfg.data.seq_len,
            n_virtual=n_virtual,
        )
        return WorkloadParts(
            init_fn=init_fn,
            loss_fn=tfm.pipelined_mlm_loss_fn(
                mcfg, mesh, n_microbatches=n_micro, n_virtual=n_virtual,
            ),
            param_specs=tfm.pipeline_param_specs(
                jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0], tp=tp,
            ),
            **common,
        )

    model = tfm.Transformer(mcfg, mesh)
    return WorkloadParts(
        init_fn=tfm.make_init_fn(model, cfg.data.seq_len),
        loss_fn=tfm.mlm_loss_fn(model),
        param_rules=tfm.tp_rules(),
        fsdp=True,
        **common,
    )
