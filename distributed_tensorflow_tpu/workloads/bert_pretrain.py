"""Workload 4 — BERT-base MLM pretraining, pod-scale config
(BASELINE.json:10).

Reference analog: the harness's BERT script — PS/worker sync replicas at
512 tokens (SURVEY.md §2a). TPU-native: one jit SPMD step over a
data×fsdp×model mesh; tensor parallelism via the megatron path rules
(models/transformer.TRANSFORMER_RULES), optional sequence parallelism
(cfg.model.seq_impl + mesh seq axis) for long-context variants
(SURVEY.md §5.7: 512-token baseline doesn't need SP; the plumbing is
first-class here and gated by config)."""

from __future__ import annotations

from ..data import TextDataConfig
from ..models import transformer as tfm
from ..parallel import MeshSpec
from ..train import OptimizerConfig
from ._transformer_common import transformer_parts
from .runner import RunConfig, TrainSection, WorkloadParts


def default_config() -> RunConfig:
    model = tfm.bert_base()
    return RunConfig(
        workload="bert_pretrain",
        model=model,
        mesh=MeshSpec(data=-1),
        data=TextDataConfig(
            dataset="synthetic_mlm", global_batch_size=256,
            seq_len=model.max_len, vocab_size=model.vocab_size,
            # gathered MLM head (masked_lm_positions format): head +
            # vocab projection on ~77 predicted positions, not all 512 —
            # the [B,S,vocab] logits tensor was the dominant memory term
            # (tools/pipeline_memory_analysis.py)
            max_predictions=-1,
        ),
        optimizer=OptimizerConfig(
            name="adamw", learning_rate=1e-4, weight_decay=0.01,
            warmup_steps=1000, schedule="linear", total_steps=10000,
        ),
        train=TrainSection(num_steps=10000, log_every=100),
    )


def build(cfg: RunConfig, mesh=None) -> WorkloadParts:
    return transformer_parts(cfg, mesh, mlm=True)
