"""Workload registry — the five BASELINE.json configs as presets, plus
gpt_lm (causal LM / long-context, beyond the reference set).

Each workload module exposes ``default_config() -> RunConfig`` and
``build(cfg, mesh) -> WorkloadParts``; the shared runner (runner.py) does
the rest. Registered lazily so importing the registry doesn't pull every
model.
"""

from __future__ import annotations

import importlib

from .runner import (
    RunConfig,
    RunResult,
    TrainSection,
    WorkloadParts,
    evaluate,
    evaluate_from_checkpoint,
    run,
)

_REGISTRY: dict[str, str] = {
    # name -> module (BASELINE.json:7-11 order)
    "mnist_mlp": ".mnist_mlp",
    "cifar10_cnn": ".cifar10_cnn",
    "resnet50_imagenet": ".resnet50_imagenet",
    "bert_pretrain": ".bert_pretrain",
    "wide_deep": ".wide_deep",
    # beyond the reference's five: causal LM with a long-context preset
    "gpt_lm": ".gpt_lm",
}


def available() -> list[str]:
    return sorted(_REGISTRY)


def get(name: str):
    """Returns the workload module (default_config, build)."""
    if name not in _REGISTRY:
        raise ValueError(f"Unknown workload '{name}'; available: {available()}")
    try:
        return importlib.import_module(_REGISTRY[name], __package__)
    except ModuleNotFoundError as e:
        raise ValueError(
            f"Workload '{name}' is registered but not implemented yet ({e})"
        ) from e


def run_workload(name: str, overrides: list[str] | None = None,
                 **run_kwargs) -> RunResult:
    from ..utils import config as config_lib

    mod = get(name)
    cfg = mod.default_config()
    if overrides:
        cfg = config_lib.apply_overrides(cfg, overrides)
    return run(cfg, mod.build, **run_kwargs)


def eval_workload(name: str, overrides: list[str] | None = None,
                  **eval_kwargs) -> dict:
    """Standalone eval-from-checkpoint entry (SURVEY.md §3.5): restores
    the latest checkpoint in --checkpoint.directory and evaluates, without
    training."""
    from ..utils import config as config_lib

    mod = get(name)
    cfg = mod.default_config()
    if overrides:
        cfg = config_lib.apply_overrides(cfg, overrides)
    return evaluate_from_checkpoint(cfg, mod.build, **eval_kwargs)
