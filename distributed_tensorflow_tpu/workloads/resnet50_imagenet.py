"""Workload 3 — ResNet-50 / ImageNet (BASELINE.json:9): the primary-metric
run (images/sec/chip; ≥50% MFU north star on v4-32, BASELINE.json:5).

Reference analog: ResNet-50 PS/worker script whose structural bottleneck was
two gRPC round trips per variable per step (SURVEY.md §3.1). Here: bf16
SPMD step over the data axis; input pipeline synthetic by default (real
ImageNet plugs in via npz:/grain on the TPU-VM host)."""

from __future__ import annotations

from ..data import DataConfig, make_dataset
from ..models import common
from ..models.resnet import (
    RESNET_RULES, ResNet50, ResNetConfig, flops_per_example,
)
from ..parallel import MeshSpec
from ..train import OptimizerConfig
from .runner import RunConfig, TrainSection, WorkloadParts


def default_config() -> RunConfig:
    return RunConfig(
        workload="resnet50_imagenet",
        # space_to_depth conv0 (the MLPerf TPU stem) + bf16 BN output:
        # +28% images/sec over the naive stem/f32-BN config (PERF_NOTES.md).
        model=ResNetConfig(stem="space_to_depth"),
        mesh=MeshSpec(data=-1),
        data=DataConfig(
            dataset="synthetic", global_batch_size=1024,
            image_size=224, channels=3, num_classes=1000,
        ),
        # 90-epoch ImageNet recipe at bs=1024: lr = 0.1 * bs/256 (linear
        # scaling), 5-epoch warmup, cosine to zero over 90 * 1.281e6 / 1024
        # ≈ 112590 steps.
        # weight decay rides the optimizer (coupled L2 on kernels, fused
        # into the update pass) rather than the loss graph — same math,
        # one fewer full-parameter pass per step
        optimizer=OptimizerConfig(
            name="momentum", learning_rate=0.4, momentum=0.9,
            schedule="warmup_cosine", warmup_steps=6255, total_steps=112590,
            weight_decay=1e-4,
        ),
        train=TrainSection(num_steps=112590, log_every=100),
    )


def build(cfg: RunConfig, mesh=None) -> WorkloadParts:
    model = ResNet50(cfg.model, mesh)
    input_shape = (cfg.data.image_size, cfg.data.image_size, cfg.data.channels)
    return WorkloadParts(
        init_fn=common.make_init_fn(model, input_shape),
        loss_fn=common.classification_loss_fn(model, label_smoothing=0.1),
        eval_fn=common.classification_eval_fn(model),
        dataset_fn=lambda start: make_dataset(cfg.data, index_offset=start),
        eval_dataset_fn=lambda n: make_dataset(
            cfg.data, n, index_offset=10**6, train=False),
        flops_per_step=flops_per_example(cfg.model, cfg.data.image_size)
        * cfg.data.global_batch_size,
        # pure DP: the one-row catch-all table — same replicated layout
        # as before, but now DECLARED through the rules engine
        param_rules=RESNET_RULES,
        batch_size=cfg.data.global_batch_size,
    )
