"""Workload 1 — MNIST MLP, single-worker sync SGD (BASELINE.json:7).

The reference's smallest harness script: MLP under replica_device_setter,
plain sync SGD (SURVEY.md §2a). The TPU-native minimum end-to-end slice
(SURVEY.md §7 M6)."""

from __future__ import annotations

from ..data import DataConfig, make_dataset
from ..models import MLP, MLPConfig, common
from ..parallel import MeshSpec
from ..train import OptimizerConfig
from .runner import RunConfig, TrainSection, WorkloadParts


def default_config() -> RunConfig:
    return RunConfig(
        workload="mnist_mlp",
        model=MLPConfig(hidden_sizes=(512, 512), num_classes=10),
        mesh=MeshSpec(data=-1),
        data=DataConfig(
            dataset="synthetic", global_batch_size=128,
            image_size=28, channels=1, num_classes=10,
        ),
        optimizer=OptimizerConfig(name="sgd", learning_rate=0.1),
        train=TrainSection(num_steps=500, log_every=50),
    )


def build(cfg: RunConfig, mesh=None) -> WorkloadParts:
    model = MLP(cfg.model)
    input_shape = (cfg.data.image_size, cfg.data.image_size, cfg.data.channels)
    input_dim = cfg.data.image_size**2 * cfg.data.channels
    from ..models.mlp import flops_per_example

    return WorkloadParts(
        init_fn=common.make_init_fn(model, input_shape),
        loss_fn=common.classification_loss_fn(model),
        eval_fn=common.classification_eval_fn(model),
        dataset_fn=lambda start: make_dataset(cfg.data, index_offset=start),
        eval_dataset_fn=lambda n: make_dataset(
            cfg.data, n, index_offset=10**6, train=False),
        flops_per_step=flops_per_example(cfg.model, input_dim)
        * cfg.data.global_batch_size,
        batch_size=cfg.data.global_batch_size,
    )
