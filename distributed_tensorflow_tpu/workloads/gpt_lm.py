"""Workload 6 — decoder-only causal LM (GPT-2-small shape), beyond the
reference's five configs (BASELINE.json:7-11).

Exists because the long-context surface (SURVEY.md §5.7 — absent from
the reference, first-class here) deserves a CLI workload: the default
preset is GPT-small at 1k tokens; ``long_context()`` scales to 8k+ with
ring-attention sequence parallelism over the `seq` mesh axis plus
per-block rematerialization. Like bert_pretrain, ``--mesh.pipe=S``
switches to the pipelined family (PP×TP with ``--mesh.model=T``).

Everything is shared plumbing: the Transformer family (models/
transformer.py), the text pipeline (data/text.py), the shared builder
(_transformer_common.py), the runner."""

from __future__ import annotations

import dataclasses

from ..data import TextDataConfig
from ..models import transformer as tfm
from ..parallel import MeshSpec
from ..train import OptimizerConfig
from ._transformer_common import transformer_parts
from .runner import RunConfig, TrainSection, WorkloadParts


def default_config() -> RunConfig:
    # xent_chunk: GPT-2's 50k vocab makes dense [B, S, vocab] loss
    # logits the dominant memory term (13 GB f32 at B=128, S=512);
    # the chunked loss is numerically identical (transformer.py)
    model = dataclasses.replace(
        tfm.gpt_small(causal_len=1024), xent_chunk=256)
    return RunConfig(
        workload="gpt_lm",
        model=model,
        mesh=MeshSpec(data=-1),
        data=TextDataConfig(
            dataset="synthetic_lm", global_batch_size=64,
            seq_len=model.max_len, vocab_size=model.vocab_size,
        ),
        optimizer=OptimizerConfig(
            name="adamw", learning_rate=3e-4, weight_decay=0.1,
            warmup_steps=2000, schedule="cosine", total_steps=100000,
        ),
        train=TrainSection(num_steps=100000, log_every=100),
    )


def long_context(seq_len: int = 8192) -> RunConfig:
    """Ring-attention + remat preset: run with ``--mesh.seq=K`` (K divides
    seq_len) so K/V blocks rotate around the seq axis over ICI
    (parallel/ring_attention.py; SURVEY.md §5.7). Most devices belong on
    the seq axis at this length; data stays at 1 unless overridden."""
    cfg = default_config()
    model = dataclasses.replace(
        cfg.model, max_len=seq_len, seq_impl="ring", remat=True,
    )
    data = dataclasses.replace(cfg.data, seq_len=seq_len,
                               global_batch_size=8)
    return dataclasses.replace(
        cfg, model=model, data=data, mesh=MeshSpec(data=1, seq=-1),
    )


def build(cfg: RunConfig, mesh=None) -> WorkloadParts:
    if not cfg.model.causal:
        raise ValueError("gpt_lm is a causal workload; set model.causal=True")
    return transformer_parts(cfg, mesh, mlm=False)
