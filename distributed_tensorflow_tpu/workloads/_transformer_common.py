"""Shared build logic for the transformer workloads (bert_pretrain,
gpt_lm): dataset/flops plumbing and the --mesh.pipe=S switch into the
pipelined family (PP×TP with --mesh.model=T) — one implementation so the
two workloads cannot drift."""

from __future__ import annotations

from ..data import make_text_dataset
from ..models import transformer as tfm
from ..parallel import mesh as mesh_lib
from .runner import RunConfig, WorkloadParts


def transformer_parts(cfg: RunConfig, mesh, *, mlm: bool) -> WorkloadParts:
    """WorkloadParts for a Transformer workload. ``mlm`` selects the
    masked-LM loss (encoder) vs next-token loss (causal decoder); the
    pipelined variants engage when the mesh has a pipe axis > 1
    (deterministic — dropout off inside the island; FSDP on the stacked
    layout is not composed)."""
    mcfg: tfm.TransformerConfig = cfg.model
    if cfg.data.seq_len > mcfg.max_len:
        raise ValueError(
            f"data.seq_len={cfg.data.seq_len} exceeds "
            f"model.max_len={mcfg.max_len}"
        )
    if cfg.data.vocab_size != mcfg.vocab_size:
        # out-of-range ids would be silently clamped by jnp.take under jit
        raise ValueError(
            f"data.vocab_size={cfg.data.vocab_size} != "
            f"model.vocab_size={mcfg.vocab_size}"
        )
    from ..data.text import resolved_max_predictions

    n_pred = resolved_max_predictions(cfg.data) if mlm else 0
    fwd_flops = tfm.flops_per_example(
        mcfg, cfg.data.seq_len, n_predictions=n_pred or None)
    common = dict(
        dataset_fn=lambda start: make_text_dataset(
            cfg.data, index_offset=start
        ),
        # Eval stream at a disjoint index range (the mnist/wide_deep
        # convention). Truly held-out for the synthetic families (index-
        # keyed generation); for tokens:<path> corpora TokenFileLM samples
        # random windows of the SAME corpus, so this is train-corpus
        # perplexity — bring a separate eval corpus for generalization.
        eval_dataset_fn=lambda n: make_text_dataset(
            cfg.data, num_batches=n, index_offset=10**6
        ),
        flops_per_step=fwd_flops * cfg.data.global_batch_size,
        batch_size=cfg.data.global_batch_size,
    )

    pipe = mesh.shape.get(mesh_lib.PIPE, 1) if mesh is not None else 1
    if pipe > 1:
        import jax

        if not mlm and mcfg.xent_chunk > 0:
            # the pipelined loss computes its [microbatch, S, vocab]
            # logits inside the schedule — microbatching already bounds
            # the logits tier at B/M, so the chunked head is simply not
            # needed there. Info, not a warning: xent_chunk is a stock
            # default (gpt_lm), and a default must not warn about itself.
            import logging

            logging.getLogger(__name__).info(
                "pipelined path: model.xent_chunk=%d not applied — the "
                "schedule's per-microbatch logits already bound the "
                "logits tier at B/M", mcfg.xent_chunk)

        tp = mesh.shape.get(mesh_lib.MODEL, 1) > 1
        n_virtual = cfg.train.pipeline_virtual
        n_micro = cfg.train.pipeline_microbatches or 2 * pipe * n_virtual
        init_fn = tfm.make_pipelined_init_fn(
            mcfg, n_stages=pipe, seq_len=cfg.data.seq_len,
            n_virtual=n_virtual,
        )
        piped_loss = (tfm.pipelined_mlm_loss_fn if mlm
                      else tfm.pipelined_lm_loss_fn)
        return WorkloadParts(
            init_fn=init_fn,
            loss_fn=piped_loss(
                mcfg, mesh, n_microbatches=n_micro, n_virtual=n_virtual,
            ),
            eval_fn=tfm.pipelined_eval_fn(
                mcfg, mesh, n_microbatches=n_micro, n_virtual=n_virtual,
                mlm=mlm,
            ),
            param_specs=tfm.pipeline_param_specs(
                jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0], tp=tp,
            ),
            **common,
        )

    model = tfm.Transformer(mcfg, mesh)
    return WorkloadParts(
        init_fn=tfm.make_init_fn(model, cfg.data.seq_len),
        loss_fn=(tfm.mlm_loss_fn(model) if mlm
                 else tfm.causal_lm_loss(model, mcfg.xent_chunk)),
        eval_fn=(tfm.mlm_eval_fn(model) if mlm
                 else tfm.lm_eval_fn(model, mcfg.xent_chunk)),
        param_rules=tfm.transformer_rules(mcfg),
        fsdp=True,
        **common,
    )
