"""Workload runner — the L4 'train script' layer (SURVEY.md §1), one
implementation for all workloads.

A reference train script did: parse flags → ClusterSpec/Server → device
placement scope → model fn → SyncReplicasOptimizer → MonitoredTrainingSession
loop (SURVEY.md §3.1). `run()` is that whole stack TPU-native: config →
mesh → sharded init-or-restore → jit step → callback loop. Each workload
module contributes a preset config and a builder; everything else is shared.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Iterable

import jax
import numpy as np

from ..data import DataConfig, Prefetcher
from ..parallel import MeshSpec, build_mesh, cluster, describe
from ..train import (
    CheckpointConfig,
    Checkpointer,
    OptimizerConfig,
    ShardedEvaluator,
    StepOptions,
    Trainer,
    callbacks as cb,
    derive_metrics,
    init_or_restore,
    init_train_state,
    make_optimizer,
    make_train_step,
)
from ..utils import config as config_lib

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TrainSection:
    num_steps: int = 1000
    log_every: int = 100
    grad_accum_steps: int = 1
    seed: int = 0
    eval_every: int = 0  # 0 = no mid-train eval
    # Pipeline schedule (engaged when mesh.pipe > 1 on a workload that
    # supports it): microbatches per step (0 = auto, 2x stages) and the
    # interleaved-schedule virtual-chunk count (1 = plain GPipe).
    pipeline_microbatches: int = 0
    pipeline_virtual: int = 1
    # Pipeline-memory guard (VERDICT r4 item 8a): before a pipelined run
    # on an accelerator backend, estimate the per-device working set via
    # XLA's memory analysis (CPU-backend subprocess, layout-portable to
    # ~10% — tools/pipeline_memory_analysis.py) and WARN with the
    # measured mitigation (grad_accum_steps=2) when it presses HBM. The
    # estimate costs one CPU compile (~1-2 min for BERT-base) against a
    # run that is hours; set False to skip it.
    check_pipeline_memory: bool = True
    eval_batches: int = 16
    profile: bool = False
    profile_dir: str = "/tmp/dtf_tpu_profile"
    # Non-empty = write TensorBoard scalar event files there (chief-only,
    # log_every cadence) — the reference's SummarySaverHook surface.
    summary_dir: str = ""
    # Adds grad_norm + grads_finite to the step metrics — an extra pass over
    # every gradient leaf per step; off in production (PERF_NOTES.md).
    debug_metrics: bool = False
    # > 0: clip gradients to this global norm (the transformer-pretrain
    # standard). Side benefit: the norm's finiteness doubles as a FREE
    # same-step grads_finite signal for NaNGuard (train/step.py), closing
    # the one-step-delayed-loss window without debug_metrics' extra pass.
    clip_grad_norm: float = 0.0
    # Numeric-anomaly defense (docs/resilience.md "Numeric anomalies"):
    # the in-graph no-update-on-nonfinite guard plus the AnomalyPolicy —
    # a non-finite batch is skipped device-side (old state survives
    # bit-identically), blamed by raw (seed, index) into quarantine.json
    # next to the checkpoints, and re-seeked AROUND on every later
    # incarnation. Requires checkpoint.directory (the quarantine file
    # lives there). Trades the dispatch-ahead overlap for the per-step
    # flag fetch; prefetch is bypassed so the blamed index is exact.
    anomaly_defense: bool = False
    # non-finite batches skipped before escalating to the poisoned path
    anomaly_skip_budget: int = 8


@dataclasses.dataclass(frozen=True)
class FleetSection:
    """This process's membership in a FleetSupervisor gang
    (resilience/fleet.py). ``dir`` is the fleet control dir
    (INCARNATION / RESTORE_STEP / SHARD_PLAN / heartbeats); empty =
    standalone run. With ``elastic`` the runner reads the current
    SHARD_PLAN at startup — worker-sharded data via
    ``data/pipeline.ElasticStream``, mesh respec'd through
    ``parallel.rescale_for_world`` — and follows live resizes from the
    step seam (``callbacks.ElasticCallback``). One jax process per fleet
    worker: the worker shard replaces process-count data sharding."""

    dir: str = ""
    worker: int = 0
    elastic: bool = False
    # worker-side budget for an abandoned resize hold. SIZE AT OR ABOVE
    # the fleet's FleetConfig.hold_timeout_s: if the worker gives up
    # first, a legitimate slow resize turns into an attempt restart
    # while the fleet still counts this worker as holding.
    hold_timeout_s: float = 120.0

    def __post_init__(self):
        if self.worker < 0:
            raise ValueError("fleet.worker must be >= 0")
        if self.elastic and not self.dir:
            raise ValueError("fleet.elastic=true needs fleet.dir (the "
                             "SHARD_PLAN lives there)")
        if self.hold_timeout_s <= 0:
            raise ValueError("fleet.hold_timeout_s must be > 0")


@dataclasses.dataclass(frozen=True)
class RunConfig:
    workload: str = "mnist_mlp"
    model: Any = None  # workload-specific config dataclass, set by preset
    cluster: cluster.ClusterConfig = cluster.ClusterConfig()
    mesh: MeshSpec = MeshSpec()
    data: DataConfig = DataConfig()
    optimizer: OptimizerConfig = OptimizerConfig()
    train: TrainSection = TrainSection()
    checkpoint: CheckpointConfig = CheckpointConfig()
    fleet: FleetSection = FleetSection()


@dataclasses.dataclass
class WorkloadParts:
    """What a workload module's build() returns."""

    init_fn: Callable  # rng -> (params, model_state)
    loss_fn: Callable  # engine LossFn
    # start_step -> host-batch iterable; the runner calls it with the
    # restored step so resume continues the data stream, not batch 0.
    dataset_fn: Callable[[int], Iterable] = None
    eval_fn: Callable | None = None
    eval_dataset_fn: Callable[[int], Iterable] | None = None
    flops_per_step: float | None = None  # analytic, for MFU
    param_rules: Any = None  # sharding path rules
    # explicit spec tree (wins over rules — init_train_state contract);
    # the pipelined paths use this for their stacked [S,...] layouts
    param_specs: Any = None
    # workload-supplied optimizer (e.g. a make_multi_optimizer split);
    # None = runner builds one from cfg.optimizer
    tx: Any = None
    fsdp: bool = False
    batch_size: int | None = None  # examples/step for throughput logs
    # Prefix for the eval AUC key (e.g. "train_" when the workload's eval
    # stream draws from the training file — wide_deep ctr: fallback)
    eval_metric_prefix: str = ""
    # Did build() consult cfg.data.eval_dataset? Workloads that honor the
    # flag set this True; the runner rejects an explicit eval_dataset the
    # workload would silently ignore (no silent eval-source degradation).
    consumed_eval_dataset: bool = False
    # cached ShardedEvaluator (train/evaluation.py) — built on first
    # eval so repeated mid-train evals never retrace
    _jit_eval: Any = dataclasses.field(default=None, repr=False)


def _pipeline_memory_guard(cfg: RunConfig, mesh) -> None:
    """Warn before a pipelined transformer run whose estimated per-device
    working set presses v5e HBM (VERDICT r4 item 8a).

    The estimator is XLA's own memory analysis of the REAL pipelined
    step, compiled for the CPU backend in a subprocess (allocation sizes
    are layout-portable within ~10% — tools/pipeline_memory_analysis.py
    docstring). The measured grid (artifacts/podshape_r4/
    memory_grid.jsonl) showed the M=64 pod rows NOT fitting, with
    ``train.grad_accum_steps=2`` the tested mitigation (halves the
    per-accumulation-step batch, hence the in-flight microbatch set).
    Best-effort: any estimator failure logs and continues."""
    from ..parallel import mesh as mesh_lib

    pipe = mesh.shape.get(mesh_lib.PIPE, 1)
    if (pipe <= 1 or not cfg.train.check_pipeline_memory
            or not cluster.is_chief()):
        return
    if jax.default_backend() == "cpu":
        return  # test/demo rig: the run itself is the CPU evidence
    from ..models.transformer import TransformerConfig

    if not isinstance(cfg.model, TransformerConfig):
        return  # estimator covers the transformer pipeline paths only
    import json
    import os
    import subprocess
    import sys

    from ..utils import config as config_lib

    data_shards = max(
        1, int(np.prod([mesh.shape.get(ax, 1) for ax in mesh_lib.BATCH_AXES])))
    n_virtual = cfg.train.pipeline_virtual
    req = {
        "model": config_lib.to_dict(cfg.model),
        "S": pipe, "V": n_virtual,
        # the same auto rule the workload builder applies
        "M": cfg.train.pipeline_microbatches or 2 * pipe * n_virtual,
        "batch": cfg.data.global_batch_size // data_shards,
        "seq": cfg.data.seq_len,
        "mlm": cfg.workload != "gpt_lm",
    }
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "tools", "pipeline_memory_analysis.py")
    env = {k: v for k, v in os.environ.items()
           # the CPU estimate must never touch the accelerator: drop the
           # axon bootstrap gate (env pin alone is NOT enough here — see
           # tools/chip_session.sh) on top of the tool's own config pin
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, tool, "--check", json.dumps(req)],
            capture_output=True, text=True, timeout=600, env=env,
        )
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        if row.get("fits_v5e"):
            logger.info("pipeline memory estimate: %.1f GiB/device "
                        "(fits v5e)", row["gib"])
        else:
            logger.warning(
                "pipeline memory estimate %.1f GiB/device EXCEEDS the "
                "~14.4 GiB usable v5e HBM (S=%d V=%d M=%d per-shard "
                "batch %d). Measured mitigation: train.grad_accum_steps"
                "=2 (artifacts/podshape_r4/memory_grid.jsonl; exact-"
                "parity tested). Set train.check_pipeline_memory=false "
                "to silence.", row["gib"], req["S"], req["V"], req["M"],
                req["batch"])
    except Exception as e:  # noqa: BLE001 — guard must never kill a run
        logger.info("pipeline memory estimate unavailable: %s", e)


@dataclasses.dataclass
class RunResult:
    state: Any
    history: list[dict]
    eval_metrics: dict | None
    mesh: Any


def run(cfg: RunConfig, build: Callable[[RunConfig, Any], WorkloadParts],
        extra_callbacks: Iterable[cb.Callback] = ()) -> RunResult:
    """``build(cfg, mesh) -> WorkloadParts``: every workload takes the mesh
    (models embedding collective schedules — seq-parallel attention,
    pipeline stages — need it at construction; others ignore it)."""
    cluster.initialize(cfg.cluster)
    fleet_writer = fleet_plan = None
    mesh_spec = cfg.mesh
    if cfg.fleet.dir:
        from ..parallel import rescale_for_world
        from ..resilience import fleet as fleet_lib

        fleet_writer = fleet_lib.HeartbeatWriter(
            fleet_lib.heartbeat_path(cfg.fleet.dir, cfg.fleet.worker),
            incarnation=fleet_lib.read_incarnation(cfg.fleet.dir))
        if cfg.fleet.elastic:
            fleet_plan = fleet_lib.read_shard_plan(cfg.fleet.dir)
            if fleet_plan is not None:
                # the config's mesh is authored for the NOMINAL fleet;
                # a shrunken gang gets the batch axes rescaled to the
                # surviving world (parameter axes never resize)
                mesh_spec = rescale_for_world(
                    cfg.mesh, fleet_plan.fleet_size or fleet_plan.world,
                    fleet_plan.world)
    mesh = build_mesh(mesh_spec)
    if cluster.is_chief():
        logger.info("mesh: %s", describe(mesh))
        logger.info("config:\n%s", config_lib.to_json(cfg))

    parts = build(cfg, mesh)
    _check_eval_dataset_consumed(cfg, parts)
    _pipeline_memory_guard(cfg, mesh)
    tx = parts.tx if parts.tx is not None else make_optimizer(cfg.optimizer)
    rng = jax.random.PRNGKey(cfg.train.seed)

    ckpt = None
    if cfg.checkpoint.directory:
        # heartbeat: saves beat phase "save" so the fleet's elastic path
        # can tell a mid-checkpoint death (gang-stop) from a clean one
        ckpt = Checkpointer(cfg.checkpoint, mesh, heartbeat=fleet_writer)
        state, specs, restored = init_or_restore(
            ckpt, parts.init_fn, tx, mesh, rng,
            param_rules=parts.param_rules, param_specs=parts.param_specs,
            fsdp=parts.fsdp,
        )
        ckpt.save_config(cfg)
    else:
        state, specs = init_train_state(
            parts.init_fn, tx, mesh, rng,
            param_rules=parts.param_rules, param_specs=parts.param_specs,
            fsdp=parts.fsdp,
        )

    metrics_logger = cb.MetricsLogger(
        every_n=cfg.train.log_every,
        batch_size=parts.batch_size or cfg.data.global_batch_size,
        model_flops_per_step=parts.flops_per_step,
        history=True,
    )
    callbacks: list[cb.Callback] = [metrics_logger, cb.NaNGuard()]
    if cfg.train.summary_dir:
        # after metrics_logger so `last` is fresh at shared cadence
        callbacks.append(cb.SummaryWriter(
            cfg.train.summary_dir, every_n=cfg.train.log_every,
            metrics_logger=metrics_logger,
        ))
    if ckpt is not None:
        callbacks.append(cb.CheckpointCallback(ckpt))
    if cfg.train.profile:
        callbacks.append(cb.Profiler(cfg.train.profile_dir))
    callbacks.extend(extra_callbacks)

    step_fn = make_train_step(
        parts.loss_fn, tx,
        StepOptions(
            grad_accum_steps=cfg.train.grad_accum_steps,
            compute_grad_norm=cfg.train.debug_metrics,
            check_grads_finite=cfg.train.debug_metrics,
            clip_grad_norm=cfg.train.clip_grad_norm or None,
            skip_nonfinite=cfg.train.anomaly_defense,
        ),
    )

    start_step = int(state.step)
    policy = None
    if cfg.train.anomaly_defense and cfg.fleet.elastic:
        raise ValueError(
            "train.anomaly_defense and fleet.elastic are mutually "
            "exclusive: both must own the raw stream cursor (the blame "
            "index and the reshard barrier bind to it) — run the elastic "
            "fleet with the in-graph guard alone, or the anomaly defense "
            "outside an elastic gang")
    if cfg.train.anomaly_defense:
        if not cfg.checkpoint.directory:
            raise ValueError(
                "train.anomaly_defense needs checkpoint.directory — the "
                "quarantine file lives next to the checkpoints")
        from ..data.pipeline import QuarantineFilter
        from ..resilience.anomaly import AnomalyConfig, AnomalyPolicy
        from ..resilience.anomaly import load_quarantine

        # no Prefetcher here: the policy blames through the stream's raw
        # cursor, and a prefetch depth would run it ahead of the step
        # being blamed (data/pipeline.QuarantineFilter docstring)
        data = QuarantineFilter(
            parts.dataset_fn, load_quarantine(cfg.checkpoint.directory),
            start_step=start_step,
        )
        policy = AnomalyPolicy(
            cfg.checkpoint.directory,
            AnomalyConfig(skip_budget=cfg.train.anomaly_skip_budget),
            index_fn=lambda: data.raw,
        )
    elif cfg.fleet.elastic:
        from ..data.pipeline import ElasticStream, WorkerShard
        from ..resilience import fleet as fleet_lib

        from ..parallel import BATCH_AXES, mesh_axis_size

        batch_extent = mesh_axis_size(mesh, BATCH_AXES)

        def _check_world(world: int) -> None:
            # WorkerShard tolerates ragged slices, but the device
            # placement path does not: put_host_batch shards the batch
            # dim over the mesh batch axes, so every worker's slice must
            # be uniform AND divide the mesh's batch-axes extent — fail
            # at config/reshard time with the fix named, not at the
            # first step with a shape error
            if cfg.data.global_batch_size % world != 0:
                raise ValueError(
                    f"data.global_batch_size={cfg.data.global_batch_size} "
                    f"not divisible by elastic world={world}: worker "
                    f"slices must be uniform to shard across the mesh "
                    f"batch axes — pick a global batch divisible by "
                    f"every fleet size the gang can shrink to")
            local = cfg.data.global_batch_size // world
            if local % batch_extent != 0:
                raise ValueError(
                    f"per-worker slice {local} "
                    f"(global_batch_size={cfg.data.global_batch_size} / "
                    f"world={world}) not divisible by the mesh batch-axes "
                    f"extent {batch_extent}: pick a global batch whose "
                    f"per-world slices divide the mesh for every fleet "
                    f"size the gang can shrink to")

        shard = None
        if fleet_plan is not None:
            _check_world(fleet_plan.world)
            rank = fleet_plan.ranks.get(cfg.fleet.worker)
            if rank is not None:
                shard = WorkerShard(rank, fleet_plan.world)

        def _on_reshard(rank, world, at):
            _check_world(world)
            data.reshard(
                WorkerShard(rank, world) if rank is not None else None, at)

        # no Prefetcher: a prefetch depth would run the stream cursor
        # past the barrier a live reshard binds to (ElasticStream
        # docstring — same rule as the anomaly defense's blame cursor)
        data = ElasticStream(parts.dataset_fn, shard,
                             start_index=start_step)
        elastic_client = fleet_lib.ElasticWorker(
            cfg.fleet.dir, cfg.fleet.worker, fleet_writer,
            on_reshard=_on_reshard,
            hold_timeout_s=cfg.fleet.hold_timeout_s)
        if (fleet_plan is not None
                and fleet_plan.phase == fleet_lib.PLAN_STEADY):
            # pre-ack ONLY a steady plan. A PLAN_HOLD naming this worker
            # must go through poll() -> _hold at train start: pre-acking
            # it would skip the barrier handshake and stall the fleet's
            # resize until hold_timeout_s (restarted-worker-races-resize)
            elastic_client.applied_version = fleet_plan.version
            fleet_writer.note_plan(fleet_plan.version, fleet_plan.world)
        # before the CheckpointCallback: a resize hold must land between
        # steps, never between a step and its cadence save
        ckpt_at = next(
            (i for i, c in enumerate(callbacks)
             if isinstance(c, cb.CheckpointCallback)), len(callbacks))
        callbacks.insert(ckpt_at, cb.ElasticCallback(elastic_client))
    else:
        data = Prefetcher(parts.dataset_fn(start_step), depth=2)
    if fleet_writer is not None:
        # first: the heartbeat must record the step even when a later
        # callback raises (PreemptionSaved skips the rest of the round)
        callbacks.insert(0, cb.HeartbeatCallback(fleet_writer))

    trainer = Trainer(step_fn, state, mesh, specs, callbacks=callbacks,
                      anomaly_policy=policy)

    if cfg.train.eval_every > 0 and parts.eval_fn is not None:
        trainer.callbacks.append(_EvalCallback(cfg, parts))

    state = trainer.fit(data, num_steps=cfg.train.num_steps)

    eval_metrics = None
    if parts.eval_fn is not None and parts.eval_dataset_fn is not None:
        eval_metrics = evaluate(
            trainer, parts, cfg.train.eval_batches
        )
        if cluster.is_chief():
            logger.info("final eval: %s", eval_metrics)
    if ckpt is not None:
        ckpt.wait()
        ckpt.close()
    if fleet_writer is not None:
        fleet_writer.close()
    return RunResult(state, metrics_logger.history, eval_metrics, mesh)


def _check_eval_dataset_consumed(cfg: RunConfig, parts: WorkloadParts) -> None:
    """An explicit --data.eval_dataset the workload does not support must
    error, not silently evaluate on the default stream (the same
    no-masquerade rule as wide_deep's train_auc tagging)."""
    # getattr: text workloads swap in TextDataConfig, which defines its
    # own eval convention (held-out token files) and has no such field
    ev = getattr(cfg.data, "eval_dataset", "")
    if ev and not parts.consumed_eval_dataset:
        raise ValueError(
            f"workload {cfg.workload!r} does not support "
            f"data.eval_dataset (got {ev!r}); its eval "
            "stream is workload-defined — drop the flag or use a "
            "workload that honors it (wide_deep)")


def _run_eval(state: Any, mesh, parts: WorkloadParts,
              num_batches: int, step: int | None = None,
              flightrec=None) -> dict:
    """Shared eval loop — DISTRIBUTED: batches shard over the mesh's
    batch axes and every device evaluates its chunk with the full
    weights, with the cross-shard reduction done host-side in a fixed
    order so the result is bit-identical to a serial evaluator
    (train/evaluation.py has the construction). The evaluator (and its
    jitted step) is cached on parts so repeated mid-train evals don't
    retrace. Summed sufficient statistics — scalars AND fixed-size
    arrays (e.g. the AUC histograms, utils/metrics.py) — merge by
    addition; ratio metrics derive via the shared
    ``evaluation.derive_metrics``."""
    if parts._jit_eval is None:
        parts._jit_eval = ShardedEvaluator(parts.eval_fn, mesh,
                                           flightrec=flightrec)
    totals = parts._jit_eval.run(
        state, parts.eval_dataset_fn(num_batches), num_batches, step=step)
    return derive_metrics(totals, parts.eval_metric_prefix)


def evaluate(trainer: Trainer, parts: WorkloadParts, num_batches: int) -> dict:
    """Eval from live trainer state; shares the mesh and runs sharded
    across it (distributed eval — the train state never moves)."""
    return _run_eval(trainer.state, trainer.mesh, parts, num_batches,
                     step=int(trainer.state.step),
                     flightrec=trainer.flightrec)


def evaluate_from_checkpoint(
    cfg: RunConfig, build: Callable[[RunConfig, Any], WorkloadParts],
    num_batches: int | None = None,
) -> dict:
    """Standalone eval-from-checkpoint — no Trainer (SURVEY.md §3.5: the
    reference ran eval single-process from `latest_checkpoint`,
    $TF checkpoint_management.py:329). Restores the latest (or ``step``)
    checkpoint from cfg.checkpoint.directory, runs classification_eval_fn
    over the eval split, returns the metric dict."""
    if not cfg.checkpoint.directory:
        raise ValueError("evaluate_from_checkpoint needs checkpoint.directory")
    cluster.initialize(cfg.cluster)
    mesh = build_mesh(cfg.mesh)
    parts = build(cfg, mesh)
    _check_eval_dataset_consumed(cfg, parts)
    if parts.eval_fn is None or parts.eval_dataset_fn is None:
        raise ValueError(f"workload {cfg.workload!r} has no eval surface")

    # same tx resolution as run(): the restored opt_state's structure
    # must match the workload's optimizer (e.g. wide_deep's multi split)
    tx = parts.tx if parts.tx is not None else make_optimizer(cfg.optimizer)
    ckpt = Checkpointer(cfg.checkpoint, mesh)
    try:
        state, _, restored = init_or_restore(
            ckpt, parts.init_fn, tx, mesh, jax.random.PRNGKey(cfg.train.seed),
            param_rules=parts.param_rules, param_specs=parts.param_specs,
            fsdp=parts.fsdp,
        )
        if not restored:
            raise FileNotFoundError(
                f"no checkpoint found in {cfg.checkpoint.directory}"
            )

        n = num_batches if num_batches is not None else cfg.train.eval_batches
        metrics = _run_eval(state, mesh, parts, n, step=int(state.step))
        metrics["step"] = int(state.step)
        if cluster.is_chief():
            logger.info("eval from checkpoint @ step %d: %s",
                        int(state.step), metrics)
        return metrics
    finally:
        ckpt.close()


class _EvalCallback(cb.Callback):
    """Periodic distributed eval from the step seam. The eval pass runs
    sharded over the training mesh (no state movement, no second
    evaluator process) and its wall time is reported to every
    ``note_pause``-aware callback so the cadence meters —
    ``train_step_seconds``, steps/sec, the goodput ledger — keep
    measuring the train loop, not the eval pauses interleaved with it."""

    def __init__(self, cfg, parts, clock=time.perf_counter):
        self.cfg, self.parts = cfg, parts
        self.clock = clock

    def on_step_end(self, trainer, step, metrics):
        if step % self.cfg.train.eval_every == 0:
            t0 = self.clock()
            m = evaluate(trainer, self.parts, self.cfg.train.eval_batches)
            pause = self.clock() - t0
            for other in trainer.callbacks:
                note = getattr(other, "note_pause", None)
                if note is not None:
                    note(pause)
            if cluster.is_chief():
                logger.info("eval @ step %d: %s", step, m)
