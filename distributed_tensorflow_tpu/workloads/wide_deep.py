"""Workload 5 — Wide&Deep CTR, embedding-parallel (BASELINE.json:11).

Reference shape (SURVEY.md §2a/§2c): wide linear + deep MLP with the
embedding tables as sparse PS variables. Here tables are vocab-sharded over
the `model` mesh axis (models/wide_deep.py, ops/embedding.py) and the batch
rides (data, fsdp) — the SURVEY.md §7 M9 milestone.
"""

from __future__ import annotations

import dataclasses

from ..data import DataConfig
from ..data.recsys import CTRRecordDataset, RecsysConfig, SyntheticCTR
from ..models import wide_deep as wd
from ..parallel import MeshSpec
from ..train import OptimizerConfig
from .runner import RunConfig, TrainSection, WorkloadParts


def default_config() -> RunConfig:
    model = wd.WideDeepConfig()
    return RunConfig(
        workload="wide_deep",
        model=model,
        # embedding-parallel over `model`, DP over the rest
        mesh=MeshSpec(data=-1, model=2),
        data=DataConfig(dataset="synthetic_ctr", global_batch_size=256),
        # name="auto" selects the workload-canonical split below (FTRL on
        # the wide linear part, AdaGrad on the deep net + tables — the
        # reference's DNNLinearCombinedClassifier defaults,
        # $TF/python/estimator linear_optimizer='Ftrl'/dnn_optimizer=
        # 'Adagrad'); any explicit --optimizer.name overrides it wholesale.
        optimizer=OptimizerConfig(name="auto", learning_rate=0.02),
        train=TrainSection(num_steps=500, log_every=50),
    )


def _recsys_cfg(cfg: RunConfig) -> RecsysConfig:
    return RecsysConfig(
        vocab_sizes=tuple(cfg.model.vocab_sizes),
        dense_features=cfg.model.dense_features,
        global_batch_size=cfg.data.global_batch_size,
        seed=cfg.data.seed,
    )


def _canonical_tx(cfg: RunConfig):
    """FTRL(wide) + AdaGrad(deep/tables) when optimizer.name == "auto"."""
    if cfg.optimizer.name != "auto":
        return None
    from ..train import make_multi_optimizer

    # matches wide_table_* (sparse linear weights) and wide_dense; user
    # l1/l2/lr from the config carry through, defaulting l1 on if unset
    ftrl_cfg = dataclasses.replace(
        cfg.optimizer, name="ftrl",
        l1=cfg.optimizer.l1 if cfg.optimizer.l1 > 0 else 1e-4,
    )
    return make_multi_optimizer(
        rules=((r"(^|/)wide_", ftrl_cfg),),
        default=dataclasses.replace(cfg.optimizer, name="adagrad"),
    )


def _dataset_fn(cfg: RunConfig, rcfg: RecsysConfig):
    ds = cfg.data.dataset
    if ds.startswith("ctr:"):
        # real CTR records (tools/make_ctr_records.py) via the native
        # fixed-record loader; synthetic stays the default teacher stream
        return lambda start: CTRRecordDataset(
            ds[4:], rcfg, index_offset=start)
    return lambda start: SyntheticCTR(rcfg, index_offset=start)


def _eval_dataset_fn(cfg: RunConfig, rcfg: RecsysConfig):
    """Returns ``(dataset_fn, metric_prefix)`` — ONE decision point for
    both the eval source and the honesty tag, so they cannot drift."""
    ds = cfg.data.dataset
    ev = cfg.data.eval_dataset
    if ev.startswith("ctr:"):
        # explicit held-out record file: the honest generalization metric
        return (lambda n: CTRRecordDataset(
            ev[4:], rcfg, num_batches=n, seed=rcfg.seed + 101)), ""
    if ev:
        # an explicit-but-unrecognized eval source must not silently
        # degrade to a train-set metric
        raise ValueError(
            f"wide_deep: unsupported data.eval_dataset={ev!r} "
            "(expected 'ctr:<path>' or empty)")
    if ds.startswith("ctr:"):
        # No eval file given: fall back to the TRAINING file with a
        # distinct shuffle seed (with the training seed, eval batches
        # 0..n-1 would be byte-identical to the FIRST-trained batches —
        # pure memorization signal). The "train_" prefix tags the metric
        # so this train-set number can't masquerade as generalization;
        # pass --data.eval_dataset=ctr:<path> for a real held-out AUC.
        return (lambda n: CTRRecordDataset(
            ds[4:], rcfg, num_batches=n, seed=rcfg.seed + 101)), "train_"
    return (lambda n: SyntheticCTR(rcfg, n, index_offset=10**6)), ""


def build(cfg: RunConfig, mesh=None) -> WorkloadParts:
    model = wd.WideDeep(cfg.model, mesh)
    rcfg = _recsys_cfg(cfg)
    eval_fn_, eval_prefix = _eval_dataset_fn(cfg, rcfg)
    return WorkloadParts(
        tx=_canonical_tx(cfg),
        init_fn=wd.make_init_fn(cfg.model, mesh),
        loss_fn=wd.ctr_loss_fn(model),
        eval_fn=wd.ctr_eval_fn(model),
        dataset_fn=_dataset_fn(cfg, rcfg),
        eval_dataset_fn=eval_fn_,
        flops_per_step=wd.flops_per_example(cfg.model)
        * cfg.data.global_batch_size,
        param_rules=wd.WIDE_DEEP_RULES,
        batch_size=cfg.data.global_batch_size,
        # "train_" when eval draws from the training ctr file — a
        # train-set metric must not masquerade as generalization
        eval_metric_prefix=eval_prefix,
        consumed_eval_dataset=True,
    )
