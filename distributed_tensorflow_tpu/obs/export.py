"""Exporters: Prometheus text exposition + append-only JSONL event log.

Two surfaces, zero dependencies:

- ``render(registry)`` produces Prometheus text-exposition format
  (version 0.0.4) as a string — counters as-is, gauges as-is,
  histograms as cumulative ``_bucket{le=...}`` series plus ``_sum`` /
  ``_count``. ``serve_http`` wraps it in a stdlib ``http.server``
  scrape endpoint on a daemon thread; nothing outside the stdlib is
  imported, so the export path works on a bare CI box.
- ``JsonlLogger`` appends one JSON object per line to a local file —
  the structured-event analog of the reference's summary event files,
  for runs with no Prometheus to scrape. Chief-only by default
  (parallel/cluster.is_chief), matching every other singleton-host
  writer in the framework (checkpoint metadata, TensorBoard events):
  N hosts × identical registries would be N copies of the same data.

Merge-then-render is the multi-host story: registries are mergeable
sufficient statistics (obs/registry.py), so a fleet aggregator can
``merge()`` per-host snapshots and render once — percentiles stay exact
to bucket resolution across hosts, unlike averaging per-host p99s.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any

from .registry import Histogram, Registry, default_registry

__all__ = ["render", "serve_http", "JsonlLogger"]


def _fmt(v: float) -> str:
    """Prometheus sample value: shortest exact-ish decimal. Non-finite
    values render as the format's NaN/+Inf/-Inf tokens — a diverged-loss
    gauge must not kill the scrape endpoint."""
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(labels, extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = tuple(labels) + tuple(extra)
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in pairs
    )
    return "{" + body + "}"


def render(registry: Registry | None = None) -> str:
    """Prometheus text exposition of every metric in the registry.

    ``# HELP``/``# TYPE`` emitted once per metric name (label children
    share them, as the format requires).
    """
    registry = registry or default_registry()
    lines: list[str] = []
    seen_header: set[str] = set()
    for m in registry.collect():
        if m.name not in seen_header:
            seen_header.add(m.name)
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            cum = 0
            for bound, c in zip(m.bounds, m.counts):
                cum += int(c)
                lines.append(
                    f"{m.name}_bucket"
                    f"{_label_str(m.labels, (('le', _fmt(bound)),))} {cum}"
                )
            lines.append(
                f"{m.name}_bucket"
                f"{_label_str(m.labels, (('le', '+Inf'),))} {m.count}"
            )
            lines.append(f"{m.name}_sum{_label_str(m.labels)} {_fmt(m.sum)}")
            lines.append(f"{m.name}_count{_label_str(m.labels)} {m.count}")
        else:
            lines.append(f"{m.name}{_label_str(m.labels)} {_fmt(m.value)}")
    return "\n".join(lines) + "\n"


def serve_http(registry: Registry | None = None, port: int = 9464,
               addr: str = "127.0.0.1"):
    """Start a daemon-thread scrape endpoint; GET /metrics renders the
    registry live. Returns the ``http.server`` instance (call
    ``.shutdown()`` to stop; port 0 picks a free port, read it back from
    ``server.server_address``)."""
    import http.server

    reg = registry or default_registry()

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API name)
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            body = render(reg).encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # scrapes are not log events
            pass

    server = http.server.ThreadingHTTPServer((addr, port), _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="obs-metrics-http")
    t.start()
    return server


class JsonlLogger:
    """Append-only JSONL event log, chief-gated.

    Each ``event()`` writes one line ``{"t": <unix time>, "event": kind,
    ...fields}``; ``write_snapshot()`` dumps the full registry as one
    event, giving a greppable time series without any scrape
    infrastructure. Non-chief processes construct fine and no-op, so
    call sites need no rank checks.
    """

    def __init__(self, path: str, registry: Registry | None = None,
                 chief_only: bool = True, clock=time.time):
        self.path = path
        self.registry = registry or default_registry()
        self.clock = clock
        if chief_only:
            from ..parallel import cluster

            self.enabled = cluster.is_chief()
        else:
            self.enabled = True
        self._fh = open(path, "a") if self.enabled else None
        self._lock = threading.Lock()

    def event(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return  # non-chief: skip the encode entirely
        rec = {"t": round(self.clock(), 6), "event": kind, **fields}
        # the enabled-check belongs inside the critical section: close()
        # nulls the handle under the same lock, so an event racing a
        # close is either fully written or cleanly dropped — never a
        # write on a closed file (dtflint: lock-discipline)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
            self._fh.flush()

    def write_snapshot(self, **fields: Any) -> None:
        """One event carrying the whole registry state."""
        self.event("snapshot", metrics=self.registry.snapshot(), **fields)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
