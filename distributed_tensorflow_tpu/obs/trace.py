"""Host-side span tracer — nested wall-clock spans with optional
jax.profiler pass-through.

The train/serve loops are host-drives/device-computes: device time shows
up in jax.profiler's XPlane traces, but HOST decisions (admission,
prefill bucketing, checkpoint blocking, data stalls) are invisible
there. A ``Span`` is the host-side unit: a named context manager that
records wall-clock duration, nesting depth, and a dotted path
("step.prefill.sample"), and — when ``annotate=True`` and a jax profiler
trace is active — wraps the region in ``jax.profiler.TraceAnnotation``
so the same name appears on the device timeline in TensorBoard, lining
host spans up against the XLA programs they dispatched.

Spans can feed an obs.registry.Registry: every completed span observes
its duration into a ``trace_span_seconds{span=<path>}`` histogram, so
p50/p99 of any instrumented region falls out of the same export path as
the serve/train metrics.

Thread model: the active-span stack is a ``threading.local`` — each
thread gets independent nesting; a shared Tracer aggregates all of them
(registry updates are mergeable statistics, see obs/registry.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from contextlib import contextmanager

from .registry import Registry

__all__ = ["Span", "Tracer", "span", "default_tracer"]

SPAN_HISTOGRAM = "trace_span_seconds"


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed region: ``path`` is the dot-joined ancestry."""

    name: str
    path: str
    start: float  # tracer-clock timestamp (perf_counter origin)
    duration: float
    depth: int


class Tracer:
    """Collects completed spans (bounded ring) and optionally mirrors
    durations into a metrics registry.

    >>> tr = Tracer(registry=reg)
    >>> with tr.span("step"):
    ...     with tr.span("prefill"):
    ...         ...
    >>> tr.events[-1].path
    'step'
    """

    def __init__(
        self,
        registry: Registry | None = None,
        *,
        max_events: int = 4096,
        annotate: bool = True,
        clock=time.perf_counter,
    ):
        self.registry = registry
        self.annotate = annotate
        self.clock = clock
        #: completed spans, oldest dropped past ``max_events``
        self.events: deque[Span] = deque(maxlen=max_events)
        self.dropped = 0
        self._tls = threading.local()

    def _stack(self) -> list[str]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    @property
    def current_path(self) -> str:
        """Dotted path of the innermost open span ('' at top level)."""
        return ".".join(self._stack())

    @contextmanager
    def span(self, name: str):
        """Open a nested span; records on exit (exceptions included —
        a span that dies still reports its duration)."""
        stack = self._stack()
        stack.append(name)
        path = ".".join(stack)
        depth = len(stack) - 1
        annotation = None
        if self.annotate:
            try:
                import jax.profiler

                annotation = jax.profiler.TraceAnnotation(path)
                annotation.__enter__()
            except Exception:  # no jax / profiler backend: host-only span
                annotation = None
        t0 = self.clock()
        try:
            yield self
        finally:
            dt = self.clock() - t0
            if annotation is not None:
                annotation.__exit__(None, None, None)
            stack.pop()
            if len(self.events) == self.events.maxlen:
                self.dropped += 1
            self.events.append(Span(name, path, t0, dt, depth))
            if self.registry is not None:
                self.registry.histogram(
                    SPAN_HISTOGRAM,
                    "wall-clock duration of host trace spans",
                    span=path,
                ).observe(dt)


_default = Tracer()


def default_tracer() -> Tracer:
    return _default


def span(name: str):
    """Module-level convenience: a span on the default tracer."""
    return _default.span(name)
