"""Goodput + MFU accounting — where the wall-clock actually went.

The MLPerf TPU-pod scaling work and TF-Replicator both treat step-time
breakdown and utilization as first-class framework outputs; here they
were ad-hoc prints inside bench.py until this module factored them out.
Two jobs:

- **One MFU definition.** ``train_mfu`` is THE consumer site of the
  framework FLOPs contract (utils/flops.py): model ``flops_per_example``
  counts are FORWARD-only, and the fwd+bwd ×3 multiplier is applied
  exactly here — so ``bench.py``'s JSON line, ``MetricsLogger``'s log
  line, and the exported ``mfu`` gauge can never disagree.
  ``flops_per_step_from_compiled`` derives the per-step FLOP count from
  a compiled step's cost analysis (utils/compat.cost_analysis_dict) for
  models without an analytic count.

- **Goodput accounting.** Wall-clock partitioned into a productive
  bucket (steps that advanced training) and wasted buckets
  (``compile_warmup`` — first step of an attempt plus attempt
  construction, ``retry_backoff`` — retry_call sleep, ``restart_recovery``
  — supervisor backoff + restart-boundary rebuild). All buckets are
  COUNTERS (seconds), so they obey the registry's merge-not-reset
  invariant and stay exact across supervised restarts; the derived
  ``goodput_fraction`` gauge is refreshed on every note.

Exported names (docs/observability.md):

    goodput_productive_seconds_total          counter
    wasted_seconds_total{cause=…}             counter family
    goodput_fraction                          gauge  (productive / tracked)
    mfu                                       gauge

Module top-level imports nothing heavy — jax/flops enter lazily inside
``train_mfu``, so the scheduler- and registry-level consumers stay
device-free.
"""

from __future__ import annotations

from .registry import Histogram, Registry, default_registry

__all__ = [
    "PRODUCTIVE_SECONDS",
    "WASTED_SECONDS",
    "GOODPUT_FRACTION",
    "MFU",
    "WASTE_COMPILE_WARMUP",
    "WASTE_RETRY_BACKOFF",
    "WASTE_RESTART_RECOVERY",
    "WASTE_ELASTIC_RESIZE",
    "WASTE_ASYNC_CKPT",
    "WASTE_CAUSES",
    "note_productive",
    "note_wasted",
    "goodput_fraction",
    "train_mfu",
    "flops_per_step_from_compiled",
    "latency_percentiles_ms",
]

#: metric names (docs/observability.md "Goodput & MFU")
PRODUCTIVE_SECONDS = "goodput_productive_seconds_total"
WASTED_SECONDS = "wasted_seconds_total"
GOODPUT_FRACTION = "goodput_fraction"
MFU = "mfu"

#: the wasted-time vocabulary — every cause label the family may carry
WASTE_COMPILE_WARMUP = "compile_warmup"
WASTE_RETRY_BACKOFF = "retry_backoff"
WASTE_RESTART_RECOVERY = "restart_recovery"
WASTE_ELASTIC_RESIZE = "elastic_resize"
WASTE_ASYNC_CKPT = "async_checkpoint"
WASTE_CAUSES = (
    WASTE_COMPILE_WARMUP, WASTE_RETRY_BACKOFF, WASTE_RESTART_RECOVERY,
    WASTE_ELASTIC_RESIZE, WASTE_ASYNC_CKPT,
)


def _productive(reg: Registry):
    return reg.counter(
        PRODUCTIVE_SECONDS,
        "wall seconds spent in steps that advanced training")


def _wasted_total(reg: Registry) -> float:
    # the cause vocabulary is CLOSED, so three keyed lookups replace a
    # Registry.total() scan of every metric — note_productive runs once
    # per train step, and this keeps that hot path O(1)
    return sum(
        reg.counter(WASTED_SECONDS, "wall seconds lost, by cause",
                    cause=c).value
        for c in WASTE_CAUSES
    )


def _refresh_fraction(reg: Registry) -> None:
    productive = _productive(reg).value
    total = productive + _wasted_total(reg)
    if total > 0:
        reg.gauge(
            GOODPUT_FRACTION,
            "productive-step seconds / tracked wall seconds",
        ).set(productive / total)


def note_productive(seconds: float, registry: Registry | None = None) -> None:
    """Account ``seconds`` of wall-clock as productive training time and
    refresh the ``goodput_fraction`` gauge."""
    reg = registry if registry is not None else default_registry()
    _productive(reg).inc(max(float(seconds), 0.0))
    _refresh_fraction(reg)


def note_wasted(cause: str, seconds: float,
                registry: Registry | None = None) -> None:
    """Account ``seconds`` of wall-clock as wasted, bucketed by
    ``cause`` (one of ``WASTE_CAUSES``)."""
    if cause not in WASTE_CAUSES:
        raise ValueError(
            f"unknown waste cause {cause!r} (known: {WASTE_CAUSES})")
    reg = registry if registry is not None else default_registry()
    reg.counter(
        WASTED_SECONDS, "wall seconds lost, by cause", cause=cause,
    ).inc(max(float(seconds), 0.0))
    _refresh_fraction(reg)


def goodput_fraction(registry: Registry | None = None) -> float:
    """Productive seconds over total tracked seconds (productive +
    every wasted bucket); nan when nothing has been tracked yet."""
    reg = registry if registry is not None else default_registry()
    productive = _productive(reg).value
    total = productive + _wasted_total(reg)
    return productive / total if total > 0 else float("nan")


# ---------------------------------------------------------------------------
# MFU
# ---------------------------------------------------------------------------


def train_mfu(
    fwd_flops_per_step: float,
    steps_per_sec: float,
    n_chips: int | None = None,
    peak_per_chip: float | None = None,
    device=None,
    registry: Registry | None = None,
) -> float:
    """Training MFU from a FORWARD FLOP count — the single place the
    fwd+bwd training multiplier is applied (utils/flops.py contract).

    ``n_chips``/``peak_per_chip`` default from the live jax backend
    (pass both explicitly to stay device-free). When ``registry`` is
    given the value is also published as the ``mfu`` gauge — callers
    that print it (bench.py's JSON line) and scrapers read one number.
    """
    from ..utils import flops as flops_lib  # lazy: pulls jax

    if n_chips is None:
        import jax

        n_chips = jax.device_count()
    if peak_per_chip is None:
        peak_per_chip = flops_lib.peak_flops_per_chip(device)
    value = flops_lib.mfu(
        fwd_flops_per_step * flops_lib.train_flops_multiplier(),
        steps_per_sec, n_chips, peak_per_chip,
    )
    if registry is not None:
        registry.gauge(
            MFU, "model FLOPs utilization of the train step"
        ).set(value)
    return value


def flops_per_step_from_compiled(compiled) -> float | None:
    """Per-step FLOPs from a compiled executable's cost analysis
    (``jax.jit(...).lower(...).compile()``), via the cross-version shim
    ``utils/compat.cost_analysis_dict``. None when the backend offers no
    analysis — callers fall back to the model's analytic count."""
    from ..utils.compat import cost_analysis_dict  # lazy: pulls jax

    flops = cost_analysis_dict(compiled).get("flops")
    return float(flops) if flops else None


# ---------------------------------------------------------------------------
# Percentile read-back (the benches' single source)
# ---------------------------------------------------------------------------


def latency_percentiles_ms(
    registry: Registry,
    name: str,
    quantiles: tuple[float, ...] = (0.5, 0.99),
    **labels,
) -> dict[str, float]:
    """Read quantiles of a latency histogram back in milliseconds:
    ``{"p50_ms": …, "p99_ms": …}``. One helper for every bench/report
    site, so a printed p99 and the registry histogram can never use
    different math. Raises KeyError when the histogram doesn't exist."""
    h = registry.get(name, **labels)
    if not isinstance(h, Histogram):
        raise KeyError(f"no histogram {name!r} (labels={labels}) in registry")
    return {
        f"p{q * 100:g}_ms": round(float(h.percentile(q)) * 1e3, 3)
        for q in quantiles
    }
