"""Unified telemetry: metrics registry, host span tracing, exporters.

The observability layer the reference substrate scattered across session
hooks (StepCounterHook / SummarySaverHook / ProfilerHook on
MonitoredTrainingSession.run) rebuilt as one subsystem with a single
design rule: every metric is a MERGEABLE SUFFICIENT STATISTIC (counters
and histogram buckets add; quantiles are derived at read time from
fixed log-spaced buckets). serve/engine.py, train/callbacks.py, and the
recovery layer (resilience/retry.py's ``retry_*_total{site}``,
resilience/supervisor.py's ``supervisor_restarts_total{cause}``) record
into a Registry; obs/export.py renders Prometheus text exposition or
appends JSONL events, chief-gated. Registries MERGE across supervised
restarts (never reset), so counters stay exact over attempt boundaries;
``Registry.total`` sums a labeled family for invariant checks. See
docs/observability.md.
"""

from .registry import (  # noqa: F401
    LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    log_buckets,
)
from .trace import Span, Tracer, default_tracer, span  # noqa: F401
from .export import JsonlLogger, render, serve_http  # noqa: F401
