"""Unified telemetry: metrics registry, host span tracing, exporters.

The observability layer the reference substrate scattered across session
hooks (StepCounterHook / SummarySaverHook / ProfilerHook on
MonitoredTrainingSession.run) rebuilt as one subsystem with a single
design rule: every metric is a MERGEABLE SUFFICIENT STATISTIC (counters
and histogram buckets add; quantiles are derived at read time from
fixed log-spaced buckets). serve/engine.py, train/callbacks.py, and the
recovery layer (resilience/retry.py's ``retry_*_total{site}``,
resilience/supervisor.py's ``supervisor_restarts_total{cause}``) record
into a Registry; obs/export.py renders Prometheus text exposition or
appends JSONL events, chief-gated. Registries MERGE across supervised
restarts (never reset), so counters stay exact over attempt boundaries;
``Registry.total`` sums a labeled family for invariant checks.

Two layers answer the questions counters can't: obs/flightrec.py is the
bounded ring of causal events (what happened, in what order — dumped as
a JSONL postmortem on abnormal exits, rendered by tools/postmortem.py)
and obs/goodput.py is the wall-clock ledger (productive-step vs
compile-warmup/retry-backoff/restart-recovery buckets, the
``goodput_fraction``/``mfu`` gauges, and the one shared MFU/percentile
arithmetic). See docs/observability.md.
"""

from .registry import (  # noqa: F401
    LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    log_buckets,
)
from .trace import Span, Tracer, default_tracer, span  # noqa: F401
from .export import JsonlLogger, render, serve_http  # noqa: F401
from .flightrec import (  # noqa: F401
    EVENT_KINDS,
    FlightRecorder,
    contains_in_order,
    default_recorder,
    validate_dump,
)
from . import goodput  # noqa: F401
from . import scaling  # noqa: F401
from . import fleetview  # noqa: F401
from . import reqtrace  # noqa: F401
from .reqtrace import PHASES, ReqTrace  # noqa: F401
