"""Scaling reports + provenance stamping — no context-free perf numbers.

BENCH_r01 measured 1922 img/s/chip on a real TPU v5; rounds r02–r05
silently fell back to CPU (relay down) and their JSON rows looked just
as authoritative. The lesson (ROADMAP item 4, and the MLPerf-0.6
TPU-pod paper's practice of reporting every number with its pod shape):
**every performance number must carry its platform and scaling context
as first-class data.** This module owns that contract:

- ``provenance(mesh=None)`` — one dict every perf artifact embeds: jax
  backend, device platform/kind/count, mesh shape, git sha, hostname.
  ``bench.py``, ``tools/bench_serve.py``, and ``tools/sweep.py`` all
  stamp through here, so a CPU fallback can never masquerade as a TPU
  number again.
- the ``dtf-scaling-1`` report schema (``make_report`` /
  ``write_report`` / ``validate_scaling_report``) — a sweep over the
  mesh-config × workload matrix, one provenance-stamped cell per
  (mesh, workload), with derived per-axis scaling efficiency and
  explicit pass/fail gates. The validator is the CI gate shared with
  ``tools/obs_check.py``.
- ``scaling_efficiency(cells)`` — measured-vs-ideal throughput per
  axis. The ideal is platform-aware: on real accelerators each device
  adds silicon, so ideal(N) = N × 1-dev throughput (``per_device``
  basis); on a host-shared rig (fake CPU devices partitioning ONE
  host's cores) N devices do N× the work on the same silicon, so the
  honest ideal is flat throughput and the measurement is partitioning
  OVERHEAD (``shared_host`` basis). The basis is recorded in every
  efficiency entry — a number without it would be exactly the
  context-free reporting this module exists to end.

Exported metric names (docs/observability.md "Scaling sweeps"):

    sweep_cells_total           counter
    scaling_efficiency          gauge family {cell, workload}

Module top level imports nothing heavy — jax enters lazily inside
``provenance``, so the validator stays usable from device-free tools.
"""

from __future__ import annotations

import json
import math
import os
import socket
import subprocess
from typing import Any, Mapping, Sequence

from .registry import Registry, default_registry

__all__ = [
    "SCHEMA",
    "SWEEP_CELLS",
    "SCALING_EFFICIENCY",
    "PROVENANCE_KEYS",
    "CELL_KEYS",
    "git_sha",
    "provenance",
    "stamp_provenance",
    "note_cell",
    "scaling_efficiency",
    "make_report",
    "write_report",
    "validate_scaling_report",
]

#: report schema tag — bump when the layout changes
SCHEMA = "dtf-scaling-1"

#: metric names (docs/observability.md "Scaling sweeps")
SWEEP_CELLS = "sweep_cells_total"
SCALING_EFFICIENCY = "scaling_efficiency"

#: every provenance block must carry all of these
PROVENANCE_KEYS = (
    "backend", "platform", "device_kind", "device_count",
    "hostname", "git_sha",
)

#: every report cell must carry all of these
CELL_KEYS = (
    "cell", "workload", "axis", "n_devices", "mesh", "global_batch",
    "steps", "steps_per_sec", "examples_per_sec", "provenance",
)

#: efficiency bases (see module docstring)
BASIS_PER_DEVICE = "per_device"
BASIS_SHARED_HOST = "shared_host"


def git_sha(repo_dir: str | None = None) -> str:
    """The tree's HEAD sha (``unknown`` outside a git checkout) — ties a
    measured number to the exact code that produced it."""
    if repo_dir is None:
        repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", repo_dir, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return "unknown"
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else "unknown"


def provenance(mesh=None) -> dict:
    """The provenance block: backend truth read from the LIVE jax
    runtime at measurement time — never from flags or intent, which is
    how the r02–r05 CPU fallbacks got recorded as if they were TPU rows.

    With ``mesh``, ``device_count``/``mesh`` describe the devices the
    measurement actually ran on (a sweep cell may use a subset of the
    host's devices); without one, the process's full visible device set.
    """
    import jax  # lazy: the validator/report side stays device-free

    devices = list(mesh.devices.flat) if mesh is not None else jax.devices()
    d0 = devices[0]
    prov = {
        "backend": jax.default_backend(),
        "platform": d0.platform,
        "device_kind": getattr(d0, "device_kind", ""),
        "device_count": len(devices),
        "hostname": socket.gethostname(),
        "git_sha": git_sha(),
        "pid": os.getpid(),
    }
    if mesh is not None:
        prov["mesh"] = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    return prov


def stamp_provenance(payload: dict, mesh=None) -> dict:
    """Add the provenance block to a result dict IN PLACE (and return
    it) — the one-call helper ``bench.py`` / ``tools/bench_serve.py``
    use on their JSON outputs."""
    payload["provenance"] = provenance(mesh)
    return payload


def note_cell(registry: Registry | None = None) -> None:
    """Count one completed sweep cell."""
    reg = registry if registry is not None else default_registry()
    reg.counter(SWEEP_CELLS, "mesh-config x workload sweep cells "
                             "measured").inc()


def _is_shared_host(cell: Mapping) -> bool:
    # fake host-platform devices partition one host's silicon: flat
    # throughput is the ideal there, N× is physically impossible
    return cell["provenance"].get("platform") == "cpu"


def scaling_efficiency(cells: Sequence[Mapping],
                       registry: Registry | None = None) -> list[dict]:
    """Per-cell scaling efficiency vs the same workload's 1-device
    baseline cell: ``throughput_N / (ideal_scale × throughput_1)``,
    where ``ideal_scale`` is ``n_devices`` on real accelerators
    (``per_device`` basis) and 1 on a host-shared CPU rig
    (``shared_host`` basis — the number then measures partitioning
    overhead; see module docstring). Cells without a baseline are
    skipped. When ``registry`` is given, each value is also published
    as the ``scaling_efficiency`` gauge."""
    baselines = {c["workload"]: c for c in cells if c["n_devices"] == 1}
    out = []
    for c in cells:
        if c["n_devices"] == 1:
            continue
        base = baselines.get(c["workload"])
        if base is None or not base["examples_per_sec"]:
            continue
        shared = _is_shared_host(c) and _is_shared_host(base)
        scale = 1 if shared else c["n_devices"]
        value = c["examples_per_sec"] / (scale * base["examples_per_sec"])
        entry = {
            "cell": c["cell"],
            "workload": c["workload"],
            "axis": c["axis"],
            "n_devices": c["n_devices"],
            "basis": BASIS_SHARED_HOST if shared else BASIS_PER_DEVICE,
            "value": round(value, 4),
        }
        out.append(entry)
        if registry is not None:
            registry.gauge(
                SCALING_EFFICIENCY,
                "measured / ideal throughput vs the 1-device baseline",
                cell=c["cell"], workload=c["workload"],
            ).set(value)
    return out


def make_report(cells: Sequence[Mapping],
                efficiency: Sequence[Mapping] = (),
                gates: Sequence[Mapping] = (),
                extra: Mapping | None = None) -> dict:
    """Assemble a ``dtf-scaling-1`` report dict (validate/write it with
    ``write_report``). The header provenance describes the whole
    process; each cell additionally carries its own (same run, but with
    the cell's mesh shape and device subset)."""
    report = {
        "schema": SCHEMA,
        "provenance": provenance(),
        "cells": list(cells),
        "efficiency": list(efficiency),
        "gates": list(gates),
    }
    if extra:
        report.update(extra)
    return report


def write_report(path: str, report: Mapping) -> str:
    """Validate, then atomically write the report as JSON. Raises
    ``ValueError`` on an invalid report — a sweep must never publish a
    file the CI validator would reject."""
    failures = validate_scaling_report(report)
    if failures:
        raise ValueError(
            "refusing to write an invalid scaling report:\n  "
            + "\n  ".join(failures))
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # a torn report must not look complete
    return path


def _check_provenance(prov: Any, where: str) -> list[str]:
    if not isinstance(prov, Mapping):
        return [f"{where}: provenance is not a dict"]
    failures = []
    for key in PROVENANCE_KEYS:
        if key not in prov:
            failures.append(f"{where}: provenance missing {key!r}")
    platform = prov.get("platform")
    if "platform" in prov and (not isinstance(platform, str) or not platform):
        failures.append(f"{where}: provenance platform must be a non-empty "
                        f"string, got {platform!r}")
    count = prov.get("device_count")
    if "device_count" in prov and (not isinstance(count, int)
                                   or isinstance(count, bool) or count < 1):
        failures.append(f"{where}: provenance device_count must be a "
                        f"positive int, got {count!r}")
    return failures


def validate_scaling_report(report: Mapping | str) -> list[str]:
    """Schema-check a ``dtf-scaling-1`` report (dict or JSON file path);
    returns failures (empty == pass).

    Checks: schema tag; header provenance complete; ≥1 cell, each with
    the required keys, finite positive throughput, a mesh whose axis
    sizes multiply to ``n_devices``, and a provenance block whose
    platform/device_kind/git_sha AGREE with the header's — the
    anti-masquerade invariant: one run, one backend, so a cell claiming
    a different platform than the process that produced the report is
    exactly the CPU-fallback-as-TPU-number failure this schema exists
    to make impossible. Gate entries must be internally consistent
    (``passed == value >= threshold``)."""
    if isinstance(report, str):
        try:
            with open(report) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            return [f"unreadable report: {e}"]
    failures: list[str] = []
    if report.get("schema") != SCHEMA:
        failures.append(f"schema {report.get('schema')!r} != {SCHEMA!r}")
    failures += _check_provenance(report.get("provenance"), "header")
    head_prov = report.get("provenance") or {}

    cells = report.get("cells")
    if not isinstance(cells, list) or not cells:
        failures.append("report has no cells")
        cells = []
    for i, cell in enumerate(cells):
        where = f"cell {i} ({cell.get('cell', '?')})" \
            if isinstance(cell, Mapping) else f"cell {i}"
        if not isinstance(cell, Mapping):
            failures.append(f"{where}: not a dict")
            continue
        for key in CELL_KEYS:
            if key not in cell:
                failures.append(f"{where}: missing {key!r}")
        for key in ("steps_per_sec", "examples_per_sec"):
            v = cell.get(key)
            if key in cell and (not isinstance(v, (int, float))
                                or isinstance(v, bool)
                                or not math.isfinite(v) or v <= 0):
                failures.append(
                    f"{where}: {key} must be a finite positive number, "
                    f"got {v!r}")
        mesh = cell.get("mesh")
        n = cell.get("n_devices")
        if isinstance(mesh, Mapping) and isinstance(n, int):
            sizes = [v for v in mesh.values()
                     if isinstance(v, int) and not isinstance(v, bool)]
            if len(sizes) != len(mesh) or math.prod(sizes) != n:
                failures.append(
                    f"{where}: mesh {dict(mesh)} does not multiply to "
                    f"n_devices={n}")
        failures += _check_provenance(cell.get("provenance"), where)
        prov = cell.get("provenance")
        if isinstance(prov, Mapping):
            for key in ("platform", "device_kind", "git_sha"):
                if key in prov and key in head_prov \
                        and prov[key] != head_prov[key]:
                    failures.append(
                        f"{where}: provenance {key} {prov[key]!r} "
                        f"disagrees with the header's "
                        f"{head_prov[key]!r} — one run has one backend; "
                        f"a mismatched cell is a masqueraded number")

    for i, gate in enumerate(report.get("gates", [])):
        if not isinstance(gate, Mapping):
            failures.append(f"gate {i}: not a dict")
            continue
        value, thr = gate.get("value"), gate.get("threshold")
        if not isinstance(value, (int, float)) \
                or not isinstance(thr, (int, float)):
            failures.append(f"gate {i}: needs numeric value + threshold")
            continue
        if bool(gate.get("passed")) != (value >= thr):
            failures.append(
                f"gate {i}: passed={gate.get('passed')!r} inconsistent "
                f"with value {value} vs threshold {thr}")
    return failures
