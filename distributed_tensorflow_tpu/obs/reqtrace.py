"""Request ledger — end-to-end per-request tracing across the serve fleet.

The fleet observatory (obs/fleetview.py) and the serve-fleet metrics
answer AGGREGATE questions — fleet p99 TTFT, requeue counts, merged
causal postmortems — but cannot explain one request: when the chaos
bench reports a bad interactive p99, nothing says whether that tail
request burned its budget in lane queueing, admission block-wait,
chunked prefill, preemption, or a death-requeue hop to a survivor.

This module is the per-request causal record. Every request carries its
router trace id (``rid``) from ``Router.submit`` through dispatch,
replica ingest, admission, each prefill chunk, decode residency,
preemption, death-requeue, and finish; each lifecycle transition becomes
a **span** in a ``ReqTrace`` ledger. A transition *closes* the open span
and *opens* the next one, so one request's spans form a gap-free,
overlap-free partition of its wall time by construction — the property
the tail-attribution report (tools/trace_view.py) relies on: the named
phase durations of a request SUM to its measured latency, exactly.

The phase vocabulary is CLOSED (``PHASES``): ``transition`` rejects
unknown phases, and dtflint's ``closed-vocab`` rule checks every literal
``transition()`` phase statically — the same contract as flightrec's
``EVENT_KINDS``.

Cross-process merge. Router and replica processes each keep their own
ledger on their own monotonic clock; ``merge_traces`` aligns them with
the PR 15 clock-anchor protocol, reusing the ``serve_route``
dispatch/ACK handshake that already orders the processes: the router's
``route`` span for ``(rid, requeue)`` opens strictly before the
replica's ``admission_block`` span for the same pair (dispatch
happens-before ingest), giving an offset LOWER bound, and the replica
samples a request's first token strictly before the router delivers it
(its first ``decode_gap`` span), giving an UPPER bound. The merger takes
the largest lower bound, so every replica span lands at-or-before its
true router-clock position and all anchored orderings are preserved —
one causally consistent per-request timeline even when the request
hopped processes through a death-requeue.

Dumps follow the flight-recorder discipline: JSONL, one header line
(schema ``dtf-reqtrace-1``, identity, counts) then one line per request,
written tmp+fsync+``os.replace`` so a torn dump never looks complete.
``validate_dump`` is the schema gate (``tools/obs_check.py`` feeds it
must-fail corpora). Nothing here imports jax — plain stdlib, usable
from the router's pure-host tests and subprocess replicas alike.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Mapping, Sequence

__all__ = [
    "PHASES",
    "SCHEMA",
    "MERGED_SCHEMA",
    "ReqTrace",
    "validate_dump",
    "load_dump",
    "merge_traces",
    "write_merged",
    "phase_partition",
    "attribute_window",
    "span_chain_matches",
]

#: dump header schema tag — bump when the record layout changes
SCHEMA = "dtf-reqtrace-1"
#: merged-trace header schema tag (tools/trace_view.py output)
MERGED_SCHEMA = "dtf-reqtrace-merged-1"

#: the closed phase vocabulary (docs/observability.md has the table).
#: Each name is the state a request ENTERS at a lifecycle transition;
#: the span lasts until the next transition for the same rid.
PHASES = (
    "queue_wait",        # submitted (or re-dispatched): waiting in its SLO lane
    "route",             # dispatch order issued, in flight to the replica
    "admission_block",   # ingested by the replica, blocked on KV admission
    "prefill_chunks",    # admitted to a slot, chunked prefill running
    "decode_gap",        # resident, between delivered decode tokens
    "preempted",         # evicted to the queue head on block exhaustion
    "requeue_reprefill", # replica died: requeued for re-prefill on a survivor
)

_KNOWN_PHASES = frozenset(PHASES)
#: span keys a transition attr may not shadow
_RESERVED = frozenset(("rid", "phase", "t0", "t1", "src", "spans"))


class ReqTrace:
    """Lock-protected per-request span ledger for ONE process.

    ``transition`` is the single write path: it stamps the clock
    *inside* the lock (flightrec's rule — span order is timestamp order
    even under concurrent emitters), closes the rid's open span at that
    instant, and opens the next one. The ledger is bounded: when
    ``capacity`` distinct requests are resident the oldest record is
    evicted and counted, so a week of serving costs what a smoke test
    costs.
    """

    def __init__(self, src: str = "local", capacity: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.src = src
        self.capacity = capacity
        self.clock = clock
        self._lock = threading.Lock()
        self._recs: dict[int, dict] = {}  # rid -> record, insertion order
        self._dropped = 0
        self._seq = 0  # bumps on every mutation (dirty tracking for dumpers)

    # -- write -------------------------------------------------------------

    def transition(self, rid: int, phase: str, **attrs: Any) -> None:
        """Record that request ``rid`` entered ``phase`` now. Closes the
        rid's open span at the same instant; attrs are free-form
        JSON-able fields attached to the span being opened."""
        if phase not in _KNOWN_PHASES:
            raise ValueError(
                f"unknown request-trace phase {phase!r} "
                f"(extend PHASES to add one)")
        bad = _RESERVED.intersection(attrs)
        if bad:
            raise ValueError(f"attrs shadow reserved keys: {sorted(bad)}")
        with self._lock:
            t = float(self.clock())  # clock INSIDE the lock
            rec = self._recs.get(rid)
            if rec is None:
                if len(self._recs) >= self.capacity:
                    oldest = next(iter(self._recs))
                    del self._recs[oldest]
                    self._dropped += 1
                rec = {"rid": int(rid), "spans": [], "finish_reason": None}
                self._recs[rid] = rec
            spans = rec["spans"]
            if spans and spans[-1]["t1"] is None:
                spans[-1]["t1"] = t
            span: dict = {"phase": phase, "t0": t, "t1": None}
            span.update(attrs)
            spans.append(span)
            self._seq += 1

    def finish(self, rid: int, reason: str | None = None) -> None:
        """Close the rid's open span now and mark the record finished.
        Unknown rids are ignored (a bounded ledger may have evicted the
        record — the finish must not crash the serving path)."""
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return
            t = float(self.clock())
            spans = rec["spans"]
            if spans and spans[-1]["t1"] is None:
                spans[-1]["t1"] = t
            rec["finish_reason"] = reason
            self._seq += 1

    # -- read --------------------------------------------------------------

    @property
    def seq(self) -> int:
        """Mutation counter — dumpers compare it to skip clean rewrites."""
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def records(self) -> list[dict]:
        """Snapshot copy, oldest request first."""
        with self._lock:
            return [
                {**rec, "spans": [dict(s) for s in rec["spans"]]}
                for rec in self._recs.values()
            ]

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)

    # -- dump --------------------------------------------------------------

    def dump(self, path: str, reason: str = "",
             extra: Mapping[str, Any] | None = None) -> str:
        """Write the ledger as JSONL: one header line (schema, identity,
        counts) then one line per request, oldest first — tmp+fsync+
        ``os.replace``, the flight-recorder dump discipline, so a
        replica killed mid-dump leaves the previous trace readable,
        never a torn one. ``extra`` adds identity fields to the header
        (``worker``/``incarnation``); core keys win on collision."""
        records = self.records()
        with self._lock:
            dropped = self._dropped
        header = dict(extra or {})
        header.update({
            "schema": SCHEMA,
            "src": self.src,
            "reason": reason,
            "dumped_t": float(self.clock()),
            "records": len(records),
            "dropped": dropped,
            "pid": os.getpid(),
        })
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(header, sort_keys=True, default=repr) + "\n")
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True, default=repr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # a torn dump must not look complete
        return path


# ---------------------------------------------------------------------------
# Dump validation (shared by tools/trace_view.py, tools/obs_check.py, CI)
# ---------------------------------------------------------------------------


def _check_spans(spans: Any, where: str, failures: list[str]) -> None:
    if not isinstance(spans, list) or not spans:
        failures.append(f"{where}: missing/empty spans list")
        return
    prev_t1: float | None = None
    for j, span in enumerate(spans):
        w = f"{where} span {j}"
        if not isinstance(span, dict):
            failures.append(f"{w}: not an object")
            continue
        phase = span.get("phase")
        if phase not in _KNOWN_PHASES:
            failures.append(f"{w}: unknown phase {phase!r}")
        t0, t1 = span.get("t0"), span.get("t1")
        if not isinstance(t0, (int, float)) or isinstance(t0, bool):
            failures.append(f"{w}: missing/non-numeric t0")
            continue
        if t1 is None:
            # an open span is legal only as the LAST span (a record that
            # died mid-phase — e.g. on a SIGKILLed replica)
            if j != len(spans) - 1:
                failures.append(f"{w}: open span is not last")
        elif not isinstance(t1, (int, float)) or isinstance(t1, bool):
            failures.append(f"{w}: non-numeric t1")
        elif t1 < t0:
            failures.append(f"{w}: span end {t1} before start {t0}")
        if prev_t1 is not None and t0 < prev_t1:
            failures.append(
                f"{w}: overlaps previous span (t0 {t0} < prev t1 {prev_t1})")
        if t1 is not None and isinstance(t1, (int, float)) \
                and not isinstance(t1, bool) and t1 >= t0:
            prev_t1 = float(t1)


def validate_dump(path: str, schema: str = SCHEMA) -> list[str]:
    """Schema-check a request-trace dump; returns failures (empty ==
    pass). Checks: header schema tag, record count agreement, per
    record: int rid, no duplicate rid within the dump, spans a
    non-empty list of known-phase spans with numeric ``t0 <= t1``, open
    span only in last position, no overlap between consecutive spans."""
    failures: list[str] = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"unreadable dump: {e}"]
    if not lines:
        return ["empty dump (no header line)"]
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        return [f"header is not JSON: {e}"]
    if header.get("schema") != schema:
        failures.append(f"header schema {header.get('schema')!r} != {schema!r}")
    n_records = len(lines) - 1
    if header.get("records") != n_records:
        failures.append(
            f"header says {header.get('records')} records, "
            f"dump has {n_records} (torn dump?)")
    seen: set[int] = set()
    for i, line in enumerate(lines[1:], 2):
        try:
            rec = json.loads(line)
        except ValueError as e:
            failures.append(f"line {i}: not JSON ({e}) — torn dump?")
            continue
        rid = rec.get("rid")
        if not isinstance(rid, int) or isinstance(rid, bool):
            failures.append(f"line {i}: missing/non-int rid")
            continue
        if rid in seen:
            failures.append(f"line {i}: duplicate rid {rid} within dump")
        seen.add(rid)
        _check_spans(rec.get("spans"), f"line {i} (rid {rid})", failures)
    return failures


def load_dump(path: str) -> tuple[dict, list[dict]]:
    """Read a validated-shape dump: (header, records). Raises
    ``ValueError`` on a structurally unusable file — callers wanting
    soft failures run ``validate_dump`` first."""
    with open(path) as f:
        lines = f.read().splitlines()
    if not lines:
        raise ValueError(f"{path}: empty dump")
    header = json.loads(lines[0])
    records = [json.loads(line) for line in lines[1:]]
    return header, records


# ---------------------------------------------------------------------------
# Cross-process merge — the clock-anchor protocol, per request
# ---------------------------------------------------------------------------


def _index_lives(records: list[dict], phase: str) -> dict[tuple[int, int], dict]:
    """Map ``(rid, requeue)`` -> first span of ``phase`` in that
    request-life. Router lives are keyed by the ``requeue`` attr its
    ``route`` spans carry; replica lives by the ``requeue`` attr the
    ingest (``admission_block``) span copied from the payload."""
    out: dict[tuple[int, int], dict] = {}
    for rec in records:
        for span in rec.get("spans", ()):
            if span.get("phase") != phase:
                continue
            key = (rec["rid"], int(span.get("requeue", 0)))
            out.setdefault(key, span)
    return out


def _first_decode_by_life(records: list[dict]) -> dict[tuple[int, int], float]:
    """Map ``(rid, requeue)`` -> t0 of the first ``decode_gap`` span
    following that life's opening span (``route`` on the router side,
    ``admission_block`` on the replica side)."""
    out: dict[tuple[int, int], float] = {}
    for rec in records:
        life = 0
        for span in rec.get("spans", ()):
            phase = span.get("phase")
            if phase in ("route", "admission_block"):
                life = int(span.get("requeue", life))
            elif phase == "decode_gap":
                out.setdefault((rec["rid"], life), float(span["t0"]))
    return out


def _offset_bounds(router_records: list[dict],
                   replica_records: list[dict]) -> tuple[float, float, int]:
    """Offset bounds mapping a replica clock onto the router clock
    (``t_router = t_replica + off``), from the per-request anchors:

    - dispatch happens-before ingest: the router's ``route`` span for
      ``(rid, requeue)`` opens before the replica's ``admission_block``
      span for the same pair → ``off >= t_route - t_ingest`` (low);
    - sample happens-before delivery: the replica opens a life's first
      ``decode_gap`` span before the router observes that life's first
      delivered token → ``off <= t_router_tok - t_replica_tok`` (high).

    Returns ``(lo, hi, n_anchors)``; ``lo`` is ``-inf`` with no anchor.
    """
    routes = _index_lives(router_records, "route")
    ingests = _index_lives(replica_records, "admission_block")
    lo, n = float("-inf"), 0
    for key, ingest in ingests.items():
        route = routes.get(key)
        if route is None:
            continue
        lo = max(lo, float(route["t0"]) - float(ingest["t0"]))
        n += 1
    hi = float("inf")
    router_tok = _first_decode_by_life(router_records)
    for key, t_rep in _first_decode_by_life(replica_records).items():
        t_rtr = router_tok.get(key)
        if t_rtr is not None:
            hi = min(hi, t_rtr - t_rep)
    return lo, hi, n


#: tie-break rank for transitions landing at the same aligned instant —
#: causal lifecycle order, so a fake-clock test with coincident stamps
#: still yields the canonical chain
_PHASE_RANK = {
    "queue_wait": 0, "requeue_reprefill": 0, "route": 1,
    "admission_block": 2, "prefill_chunks": 3, "preempted": 3,
    "decode_gap": 4,
}


def merge_traces(router_path: str, replica_paths: Sequence[str],
                 reason: str = "") -> tuple[dict, list[dict], list[str]]:
    """Merge one router-process trace dump with any number of
    replica-process dumps into ONE per-request timeline on the router
    clock. Returns ``(header, merged_records, failures)``; a non-empty
    failures list means the merge is NOT trustworthy.

    Per replica dump the offset is the largest lower bound over its
    dispatch→ingest anchors (checked consistent against the
    sample→delivery upper bounds); aligned replica transitions are then
    interleaved with the router's, and each request's spans are REBUILT
    as the partition between consecutive transitions — gap-free and
    overlap-free by construction, covering submit → finish.
    """
    failures: list[str] = []
    try:
        router_header, router_records = load_dump(router_path)
    except (OSError, ValueError) as e:
        return {}, [], [f"router dump {router_path}: {e}"]
    if router_header.get("schema") != SCHEMA:
        failures.append(
            f"router dump schema {router_header.get('schema')!r} != {SCHEMA!r}")

    # rid -> list of (t_aligned, phase, src, span-attrs)
    transitions: dict[int, list[tuple[float, str, str, dict]]] = {}
    finish: dict[int, tuple[float | None, Any]] = {}

    def _add(records: list[dict], src: str, off: float) -> None:
        for rec in records:
            rows = transitions.setdefault(rec["rid"], [])
            for span in rec.get("spans", ()):
                attrs = {k: v for k, v in span.items()
                         if k not in ("phase", "t0", "t1")}
                rows.append(
                    (float(span["t0"]) + off, span["phase"], src, attrs))
            if src == "router":
                last = rec.get("spans") or [{}]
                t1 = last[-1].get("t1")
                finish[rec["rid"]] = (
                    None if t1 is None else float(t1) + off,
                    rec.get("finish_reason"))

    _add(router_records, "router", 0.0)

    offsets: dict[str, float] = {}
    seen_src: set[str] = {"router"}
    for path in replica_paths:
        fails = validate_dump(path)
        if fails:
            failures.extend(f"{path}: {f}" for f in fails)
            continue
        header, records = load_dump(path)
        src = str(header.get("src", path))
        if src in seen_src:
            failures.append(f"{path}: source label {src!r} collides")
            continue
        seen_src.add(src)
        lo, hi, n = _offset_bounds(router_records, records)
        if n == 0:
            failures.append(
                f"{path}: no dispatch→ingest anchor pairs the router "
                f"(cannot align clocks)")
            continue
        if lo > hi:
            failures.append(
                f"{path}: inconsistent clock anchors (lower bound {lo:.6f} "
                f"> upper bound {hi:.6f})")
            continue
        offsets[src] = lo
        _add(records, src, lo)

    merged: list[dict] = []
    for rid in sorted(transitions):
        rows = sorted(
            transitions[rid],
            key=lambda r: (r[0], _PHASE_RANK.get(r[1], 9)))
        t_end, freason = finish.get(rid, (None, None))
        if t_end is None:
            t_end = rows[-1][0]
        spans = []
        for i, (t0, phase, src, attrs) in enumerate(rows):
            t1 = rows[i + 1][0] if i + 1 < len(rows) else t_end
            span = {"phase": phase, "t0": t0, "t1": max(t1, t0), "src": src}
            span.update(attrs)
            spans.append(span)
        merged.append({"rid": rid, "spans": spans, "finish_reason": freason,
                       "sources": sorted({r[2] for r in rows})})

    header = {
        "schema": MERGED_SCHEMA,
        "reason": reason,
        "router": router_path,
        "sources": sorted(seen_src),
        "offsets": {k: round(v, 9) for k, v in sorted(offsets.items())},
        "records": len(merged),
    }
    return header, merged, failures


def write_merged(path: str, header: dict, records: list[dict]) -> str:
    """Atomically write a merged trace (same JSONL shape as a dump)."""
    header = dict(header)
    header["records"] = len(records)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(header, sort_keys=True, default=repr) + "\n")
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True, default=repr) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Attribution arithmetic (tools/trace_view.py, the trace-continuity tests)
# ---------------------------------------------------------------------------


def phase_partition(record: Mapping) -> list[tuple[str, float, float]]:
    """A record's spans as ``(phase, t0, t1)`` rows; raises
    ``ValueError`` if they do not partition the request's wall time
    (a gap or an overlap between consecutive spans)."""
    rows: list[tuple[str, float, float]] = []
    prev_t1: float | None = None
    for span in record.get("spans", ()):
        t0 = float(span["t0"])
        t1 = span.get("t1")
        t1 = t0 if t1 is None else float(t1)
        if prev_t1 is not None and abs(t0 - prev_t1) > 1e-9:
            raise ValueError(
                f"rid {record.get('rid')}: spans do not partition wall time "
                f"(prev ends {prev_t1}, next starts {t0})")
        rows.append((str(span["phase"]), t0, t1))
        prev_t1 = t1
    return rows


def attribute_window(record: Mapping, t_lo: float,
                     t_hi: float) -> dict[str, float]:
    """Decompose the window ``[t_lo, t_hi]`` of a request's timeline
    into per-phase seconds. Because spans partition wall time, the
    returned values sum to ``t_hi - t_lo`` exactly (up to float
    rounding) — the tail-attribution soundness property."""
    out: dict[str, float] = {}
    for phase, t0, t1 in phase_partition(record):
        overlap = min(t1, t_hi) - max(t0, t_lo)
        if overlap > 0:
            out[phase] = out.get(phase, 0.0) + overlap
    return out


def first_token_t(record: Mapping) -> float | None:
    """Aligned time the request entered its first ``decode_gap`` span —
    the TTFT boundary — or None if no token was ever delivered."""
    for span in record.get("spans", ()):
        if span.get("phase") == "decode_gap":
            return float(span["t0"])
    return None


def span_chain_matches(record: Mapping,
                       specs: Sequence[tuple[str, Mapping[str, Any]] | str],
                       ) -> bool:
    """True when the record's span sequence (plus a virtual terminal
    ``finish`` entry carrying ``reason``) contains a subsequence
    matching ``specs`` — each a phase name or ``(phase, {attr: value})``
    with attrs compared as strings (flightrec's ``contains_in_order``
    contract, applied to one request's lifecycle)."""
    entries: list[dict] = [dict(s) for s in record.get("spans", ())]
    if record.get("finish_reason") is not None:
        entries.append({"phase": "finish",
                        "reason": record["finish_reason"]})
    it = iter(entries)
    for spec in specs:
        phase, attrs = (spec, {}) if isinstance(spec, str) else spec
        for e in it:
            if e.get("phase") != phase:
                continue
            if all(str(e.get(k)) == str(v) for k, v in attrs.items()):
                break
        else:
            return False
    return True
