"""Process-wide metrics registry — Counter / Gauge / Histogram.

Design rule, inherited from utils/metrics.py's AUC histograms: every
metric is a MERGEABLE SUFFICIENT STATISTIC. Counters and histogram
buckets merge by addition, so per-engine, per-thread, or per-process
registries aggregate exactly — the same contract that lets eval shards
sum confusion-matrix buckets. Percentiles (p50/p90/p99 TTFT, step
latency) are derived from fixed log-spaced buckets at READ time, never
accumulated as unmergeable running quantiles.

Histogram buckets are log-spaced because serving latencies span four
decades (sub-ms decode token to multi-second queue wait): with ratio
``r`` between consecutive upper bounds, any derived quantile is within a
factor ``r`` of the true value regardless of the distribution's shape.
The default latency ladder uses 8 buckets/decade (r ≈ 1.33) over
100 µs..100 s.

Nothing here imports jax — the registry is plain numpy + stdlib, usable
from the scheduler's pure-host tests and from tools that never touch a
device. Rendering lives in obs/export.py; span timing in obs/trace.py.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, Mapping

import numpy as np

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "LATENCY_BUCKETS",
    "log_buckets",
    "default_registry",
]

_NAME_OK = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(f"invalid metric name {name!r}")
    return name


def log_buckets(lo: float, hi: float, per_decade: int = 8) -> tuple[float, ...]:
    """Log-spaced histogram upper bounds covering [lo, hi].

    ``per_decade`` sets the resolution/width trade-off: quantiles read
    back from the buckets are exact to within one bucket ratio
    ``10**(1/per_decade)``.
    """
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got ({lo}, {hi})")
    n = int(math.ceil(per_decade * math.log10(hi / lo))) + 1
    return tuple(lo * 10 ** (i / per_decade) for i in range(n))


#: default latency ladder: 100 µs .. 100 s, 8 buckets/decade (49 buckets)
LATENCY_BUCKETS = log_buckets(1e-4, 100.0, per_decade=8)


class _Metric:
    """Base: identity is (name, sorted label pairs)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Mapping[str, str] | None = None):
        self.name = _check_name(name)
        self.help = help
        self.labels: tuple[tuple[str, str], ...] = tuple(
            sorted((str(k), str(v)) for k, v in (labels or {}).items())
        )

    def _check_mergeable(self, other: "_Metric") -> None:
        if type(other) is not type(self) or other.name != self.name \
                or other.labels != self.labels:
            raise ValueError(
                f"cannot merge {other.kind} {other.name}{dict(other.labels)} "
                f"into {self.kind} {self.name}{dict(self.labels)}"
            )


class Counter(_Metric):
    """Monotone accumulator; merges by addition."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        self.value += amount

    def reset(self) -> None:
        self.value = 0.0

    def merge_from(self, other: "Counter") -> None:
        self._check_mergeable(other)
        self.value += other.value


class Gauge(_Metric):
    """Last-written instantaneous value (occupancy, queue depth).

    Merge takes the other side's value when it has been set more
    recently (per-metric monotone sequence number) — "latest write
    wins", the only coherent cross-registry rule for a point-in-time
    reading.
    """

    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0.0
        self._seq = 0  # bumps on every set(); 0 = never written

    def set(self, value: float) -> None:
        self.value = float(value)
        self._seq += 1

    def reset(self) -> None:
        self.value = 0.0
        self._seq = 0

    def merge_from(self, other: "Gauge") -> None:
        self._check_mergeable(other)
        if other._seq >= self._seq and other._seq > 0:
            self.value = other.value
        # max, NOT sum: summing would inflate self past any future
        # source seq, freezing the value after repeated merges from the
        # same live registry (the scrape-aggregator pattern).
        self._seq = max(self._seq, other._seq)


class Histogram(_Metric):
    """Fixed-bucket histogram: per-bucket counts + sum + count.

    Buckets are UPPER BOUNDS (Prometheus ``le`` semantics); one implicit
    overflow bucket catches everything above the last bound. Counts are
    stored non-cumulative so merge is plain addition; export.py
    cumulates at render time.
    """

    kind = "histogram"

    def __init__(self, name, help="", labels=None,
                 buckets: Iterable[float] = LATENCY_BUCKETS):
        super().__init__(name, help, labels)
        self.bounds = tuple(float(b) for b in buckets)
        if not self.bounds or list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("buckets must be non-empty, sorted, unique")
        self.counts = np.zeros(len(self.bounds) + 1, np.int64)
        self.sum = 0.0

    @property
    def count(self) -> int:
        return int(self.counts.sum())

    def observe(self, value: float) -> None:
        value = float(value)
        self.counts[int(np.searchsorted(self.bounds, value, side="left"))] += 1
        self.sum += value

    def reset(self) -> None:
        self.counts[:] = 0
        self.sum = 0.0

    def percentile(self, q: float) -> float:
        """Quantile q ∈ [0, 1] read back from the buckets.

        Linear interpolation inside the containing bucket; exact to
        within one bucket width (one bucket RATIO for the log ladder).
        Returns nan when empty; the last finite bound when q lands in
        the overflow bucket (a floor, flagged by the caller if needed).
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        total = self.count
        if total == 0:
            return float("nan")
        target = q * total
        cum = np.cumsum(self.counts)
        b = int(np.searchsorted(cum, target, side="left"))
        if b >= len(self.bounds):
            return self.bounds[-1]  # overflow: best available floor
        lo = self.bounds[b - 1] if b > 0 else 0.0
        hi = self.bounds[b]
        below = cum[b - 1] if b > 0 else 0
        inside = self.counts[b]
        frac = (target - below) / inside if inside else 1.0
        return lo + (hi - lo) * min(max(frac, 0.0), 1.0)

    def merge_from(self, other: "Histogram") -> None:
        self._check_mergeable(other)
        if other.bounds != self.bounds:
            raise ValueError(
                f"histogram {self.name}: bucket mismatch "
                f"({len(self.bounds)} vs {len(other.bounds)} bounds)"
            )
        self.counts += other.counts
        self.sum += other.sum


class Registry:
    """Get-or-create metric store, keyed by (name, labels).

    Thread-safe on registration and merge (serve engines and the train
    loop may share one registry across threads); individual metric
    updates are plain float/int ops on the single hot path and are NOT
    locked — per-CPython-op atomicity is enough for statistics whose
    consumers tolerate one-update skew.
    """

    def __init__(self):
        self._metrics: dict[tuple, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labels, **kw) -> _Metric:
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, labels, **kw)
                self._metrics[key] = m
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = LATENCY_BUCKETS,
                  **labels) -> Histogram:
        h = self._get_or_create(Histogram, name, help, labels,
                                buckets=buckets)
        if h.bounds != tuple(float(b) for b in buckets):
            raise ValueError(
                f"histogram {name!r} re-registered with different buckets"
            )
        return h

    def _sorted_unlocked(self) -> list[_Metric]:
        return sorted(self._metrics.values(),
                      key=lambda m: (m.name, m.labels))

    def collect(self) -> list[_Metric]:
        """All metrics, stable order: by name, then label values."""
        with self._lock:
            return self._sorted_unlocked()

    def get(self, name: str, **labels) -> _Metric | None:
        # under the lock: merge() may be inserting adopted metrics into
        # the table concurrently (dtflint: lock-discipline)
        with self._lock:
            return self._metrics.get(
                (name, tuple(sorted(labels.items())))
            )

    def total(self, name: str) -> float:
        """Sum a metric family across ALL label sets — e.g.
        Σ ``supervisor_restarts_total{cause=…}`` or
        Σ ``retry_exhausted_total{site=…}``. Counters/gauges contribute
        their value, histograms their observation count; 0.0 when the
        name was never registered."""
        with self._lock:
            ms = [m for m in self._metrics.values() if m.name == name]
        return float(sum(
            m.count if isinstance(m, Histogram) else m.value for m in ms
        ))

    def reset(self) -> None:
        """Zero every metric IN PLACE (handles stay valid — benches call
        this after warmup so compile-time observations don't pollute
        steady-state percentiles)."""
        with self._lock:
            for m in self._metrics.values():
                m.reset()

    def merge(self, other: "Registry") -> None:
        """Fold ``other`` into self (counters/histograms add, gauges take
        the freshest write); missing metrics are adopted as copies."""
        import copy

        # Snapshot other's table under ITS lock (a live registry may
        # register new metrics mid-merge), then fold under ours —
        # sequential, not nested, so concurrent a.merge(b) / b.merge(a)
        # cannot deadlock. Individual metric values may still move while
        # we fold: the same one-update skew the class tolerates.
        with other._lock:
            items = list(other._metrics.items())
        with self._lock:
            for key, om in items:
                mine = self._metrics.get(key)
                if mine is None:
                    self._metrics[key] = copy.deepcopy(om)
                else:
                    mine.merge_from(om)

    def snapshot(self) -> dict:
        """JSON-able dump (the JSONL exporter's payload).

        Reads every metric UNDER the registry lock: ``merge`` mutates a
        histogram's ``counts`` then ``sum`` while holding this lock, so
        a snapshot taken lock-free could capture the counts of one merge
        and the sum of another (torn ``sum``/``count``). Holding the
        lock for the whole read makes the snapshot a consistent cut
        w.r.t. merges; lock-free hot-path ``observe()`` keeps its
        documented one-update skew."""
        with self._lock:
            return self._snapshot_unlocked()

    def _snapshot_unlocked(self) -> dict:
        out = {}
        for m in self._sorted_unlocked():
            key = m.name if not m.labels else (
                m.name + "{" + ",".join(f"{k}={v}" for k, v in m.labels) + "}"
            )
            if isinstance(m, Histogram):
                out[key] = {
                    "kind": m.kind, "sum": m.sum, "count": m.count,
                    "bounds": list(m.bounds),
                    "counts": m.counts.tolist(),
                }
            else:
                out[key] = {"kind": m.kind, "value": m.value}
            if m.labels:
                # structured identity alongside the flattened key, so a
                # cross-process consumer (Registry.from_snapshot) never
                # has to re-parse label values out of the key string
                out[key]["name"] = m.name
                out[key]["labels"] = dict(m.labels)
        return out

    @classmethod
    def from_snapshot(cls, snap: Mapping, labels: Mapping[str, str]
                      | None = None,
                      kinds: Iterable[str] | None = None) -> "Registry":
        """Reconstruct a Registry from a ``snapshot()`` dict — the
        cross-PROCESS half of the merge contract: a worker ships its
        snapshot as JSON (obs/fleetview.py), the fleet rebuilds it here
        and folds it with ``merge()``, so fleet-wide percentiles come
        from summed buckets, never from averaged percentiles.

        ``labels`` are added to every metric (the fleet's ``worker=``
        convention; they override same-named labels from the snapshot).
        ``kinds`` restricts reconstruction (e.g. ``("counter",
        "histogram")`` for a fleet-wide union, where summing is exact
        but a "latest" gauge across processes is meaningless). Raises
        ``ValueError`` on a malformed snapshot."""
        reg = cls()
        extra = {str(k): str(v) for k, v in (labels or {}).items()}
        for key, entry in snap.items():
            try:
                kind = entry["kind"]
                if kinds is not None and kind not in kinds:
                    continue
                name = entry.get("name") or key.partition("{")[0]
                lab = dict(entry.get("labels") or {})
                lab.update(extra)
                if kind == "counter":
                    reg.counter(name, **lab).inc(float(entry["value"]))
                elif kind == "gauge":
                    reg.gauge(name, **lab).set(float(entry["value"]))
                elif kind == "histogram":
                    h = reg.histogram(name, buckets=entry["bounds"], **lab)
                    counts = entry["counts"]
                    if len(counts) != len(h.counts):
                        raise ValueError(
                            f"{len(counts)} counts for "
                            f"{len(h.bounds)} bounds")
                    h.counts[:] = np.asarray(counts, np.int64)
                    h.sum = float(entry["sum"])
                else:
                    raise ValueError(f"unknown metric kind {kind!r}")
            except (KeyError, TypeError, ValueError) as e:
                raise ValueError(
                    f"malformed snapshot entry {key!r}: {e}") from e
        return reg

    def delta(self, baseline: Mapping) -> dict:
        """What changed since ``baseline`` (a dict from ``snapshot()``),
        as a snapshot-shaped dict.

        The per-interval isolation primitive for sweep/bench harnesses:
        take ``snapshot()`` before an interval, ``delta(snap)`` after,
        and read only that interval's counters/histogram observations —
        WITHOUT a mid-run ``reset()``, which would break the registry's
        merge-not-reset invariant for every concurrent consumer (the
        supervised-restart ledger, a live scrape endpoint).

        Semantics per kind: counters and histograms report the
        DIFFERENCE (counts/sums are mergeable sufficient statistics, so
        subtraction is exact); gauges report their CURRENT value — a
        point-in-time reading has no meaningful diff — and are included
        only when the value differs from the baseline's (a rewrite of
        the same value is indistinguishable and omitted). Metrics absent
        from the baseline diff against zero.

        Reads the live table under the registry lock — the same
        consistent-cut guarantee as ``snapshot()``, so a concurrent
        ``merge`` cannot tear a histogram's counts/sum apart. Raises
        ``ValueError`` when the baseline is ahead of the live registry
        (a counter went down / histogram shrank): that means it came
        from a different registry or a ``reset()`` intervened, and a
        silently-negative delta would corrupt every derived rate."""
        with self._lock:
            current = self._snapshot_unlocked()
        out: dict = {}
        for key, now in current.items():
            old = baseline.get(key)
            if old is not None and old.get("kind") != now["kind"]:
                raise ValueError(
                    f"delta baseline kind mismatch for {key!r}: "
                    f"{old.get('kind')} vs {now['kind']}")
            if now["kind"] == "histogram":
                old_counts = old["counts"] if old else [0] * len(now["counts"])
                if len(old_counts) != len(now["counts"]):
                    raise ValueError(
                        f"delta baseline bucket mismatch for {key!r}")
                counts = [a - b for a, b in zip(now["counts"], old_counts)]
                if any(c < 0 for c in counts):
                    raise ValueError(
                        f"histogram {key!r} shrank since the baseline — "
                        f"not a baseline of this registry (or reset() "
                        f"intervened)")
                if any(counts):
                    out[key] = {
                        "kind": "histogram",
                        "sum": now["sum"] - (old["sum"] if old else 0.0),
                        "count": sum(counts),
                        "bounds": list(now["bounds"]),
                        "counts": counts,
                    }
            elif now["kind"] == "counter":
                diff = now["value"] - (old["value"] if old else 0.0)
                if diff < 0:
                    raise ValueError(
                        f"counter {key!r} went down since the baseline — "
                        f"not a baseline of this registry (or reset() "
                        f"intervened)")
                if diff != 0:
                    out[key] = {"kind": "counter", "value": diff}
            else:  # gauge: point-in-time reading, no meaningful diff
                if old is None or old["value"] != now["value"]:
                    out[key] = {"kind": now["kind"], "value": now["value"]}
        return out


_default = Registry()


def default_registry() -> Registry:
    """The process-wide registry (what export.serve_http scrapes when not
    given one explicitly)."""
    return _default
