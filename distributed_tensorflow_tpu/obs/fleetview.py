"""Fleet observatory — cross-worker telemetry snapshots, fleet-wide
aggregation, and causally merged postmortem timelines.

Every observability surface before this module — the mergeable Registry,
the flight-recorder ring, the goodput ledger — is per-process, but the
interesting failures of an N-worker elastic gang (gang stops, shrinks,
split-gang near-misses) span processes. Three pieces close the gap:

- **SnapshotExporter** (worker side): periodically writes an atomic,
  schema-versioned telemetry snapshot (``dtf-fleetsnap-1``: registry
  dump + flight-recorder tail + identity) next to the worker's
  heartbeat, tmp+fsync+replace so a worker killed mid-export leaves the
  previous snapshot readable. Driven from the step seam by
  ``train.callbacks.FleetSnapshotCallback``; the clock is injectable,
  so the export path is wall-clock-free in the seam.
- **FleetAggregator** (fleet side): folds the per-worker snapshots into
  ONE view through the ``Registry.merge`` contract — counters and
  histogram buckets add, so a fleet-wide p99 read from the merged
  buckets is the p99 of the union stream (to bucket resolution), which
  averaging per-worker p99s can never give. The view carries every
  worker metric re-labeled ``worker=<i>`` plus the unlabeled fleet-wide
  union (counters/histograms only: a "latest write" gauge has no
  cross-process union), and is REBUILT from the current snapshots on
  every poll — folding a live counter into an accumulating registry
  twice would double-count it. Derived gauges
  (``fleet_goodput_fraction``, per-worker
  ``fleet_worker_staleness_seconds`` judged on the aggregator's OWN
  clock) go to the fleet's registry; the merged view renders over the
  existing export/scrape path (``obs.render`` / ``obs.serve_http``).
- **merge_timelines**: renders ONE causally consistent timeline from N
  per-process flight-recorder dumps. Per-process monotonic clocks do
  not compare, so alignment anchors on control-plane events both sides
  already record: a worker's whole life follows its ``fleet_launch``
  (lower bound on the clock offset), and the fleet's observations of
  the worker — a ``fleetsnap_merge`` of its export, the relayed
  ``ckpt_restore``, the resize handshake (``fleet_hold`` →
  ``elastic_hold`` → ``fleet_shrink``/``fleet_rejoin`` →
  ``elastic_release``), ``fleet_worker_dead``, ``fleet_done`` — bound
  it from above. The merger takes the LARGEST lower bound, so every
  worker event lands at-or-before its true fleet-clock position: any
  true "worker event before fleet event" relation is preserved, and the
  anchored "fleet event before worker event" relations are forced —
  which is exactly what makes ``postmortem.py --merge --expect`` a
  sound cross-process causal gate. Inconsistent or missing anchors are
  merge FAILURES, never silently absorbed.

Nothing here imports jax — plain stdlib + the registry, usable from the
fleet control plane and from tools that never touch a device.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Iterable, Mapping, Sequence

from . import goodput
from .flightrec import EVENT_KINDS, FlightRecorder, default_recorder
from .registry import Registry, default_registry

__all__ = [
    "SCHEMA",
    "MERGED_SCHEMA",
    "FLEETSNAP_EXPORTS_TOTAL",
    "FLEETSNAP_MERGES_TOTAL",
    "FLEET_GOODPUT_FRACTION",
    "FLEET_WORKER_STALENESS",
    "fleetsnap_path",
    "SnapshotExporter",
    "read_snapshot",
    "validate_snapshot",
    "FleetAggregator",
    "load_dump",
    "merge_timelines",
    "write_merged",
    "validate_merged_dump",
]

logger = logging.getLogger(__name__)

#: worker telemetry snapshot schema tag — bump when the layout changes
SCHEMA = "dtf-fleetsnap-1"
#: merged cross-worker timeline schema tag
MERGED_SCHEMA = "dtf-fleetmerge-1"

#: metric names (docs/observability.md "Fleet observability")
FLEETSNAP_EXPORTS_TOTAL = "fleetsnap_exports_total"
FLEETSNAP_MERGES_TOTAL = "fleetsnap_merges_total"
FLEET_GOODPUT_FRACTION = "fleet_goodput_fraction"
FLEET_WORKER_STALENESS = "fleet_worker_staleness_seconds"

_KNOWN_KINDS = frozenset(EVENT_KINDS)


def fleetsnap_path(fleet_dir: str, worker: int) -> str:
    """The one snapshot file of worker ``worker`` under the fleet dir —
    the single definition of the layout, shared by exporter, aggregator,
    and tools/fleet_top.py (it sits next to ``heartbeat-<i>.json``)."""
    return os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)),
        f"fleetsnap-{worker}.json",
    )


# ---------------------------------------------------------------------------
# Worker side: snapshot export
# ---------------------------------------------------------------------------


class SnapshotExporter:
    """Worker-side telemetry snapshot writer.

    Each ``export()`` bumps a per-process ``seq``, emits a
    ``fleetsnap_export`` event (the clock anchor the merged timeline
    pairs with the fleet's ``fleetsnap_merge``), and atomically rewrites
    the snapshot file: registry dump, flight-recorder tail, and identity
    (worker, incarnation, pid, seq). tmp+fsync+replace — a worker killed
    mid-export leaves the previous snapshot readable, never a torn one.

    ``min_interval_s`` rate-limits exports on the injectable ``clock``
    (a per-step callback cadence can then stay 1 without a disk write
    per step); ``force=True`` bypasses it for end-of-run exports.
    """

    def __init__(self, path: str, worker: int, incarnation: int = 0,
                 registry: Registry | None = None,
                 flightrec: FlightRecorder | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 min_interval_s: float = 0.0, tail: int = 256):
        if min_interval_s < 0:
            raise ValueError("min_interval_s must be >= 0")
        if tail < 1:
            raise ValueError("tail must be >= 1")
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        self.path = path
        self.worker = int(worker)
        self.incarnation = int(incarnation)
        self.registry = registry if registry is not None else default_registry()
        self.flightrec = (flightrec if flightrec is not None
                          else default_recorder())
        self.clock = clock
        self.min_interval_s = float(min_interval_s)
        self.tail = int(tail)
        self._seq = 0
        self._t_last: float | None = None
        self._m_exports = self.registry.counter(
            FLEETSNAP_EXPORTS_TOTAL,
            "telemetry snapshots exported to the fleet dir",
            worker=str(self.worker))

    def export(self, step: int | None = None, phase: str | None = None,
               force: bool = False) -> str | None:
        """Write one snapshot; returns its path, or None when the
        rate limit swallowed the call. Raises OSError on write failure
        (callers on the step seam catch and log — see
        ``FleetSnapshotCallback``); the previous snapshot stays intact
        either way."""
        now = float(self.clock())
        if (not force and self._t_last is not None
                and now - self._t_last < self.min_interval_s):
            return None
        self._t_last = now
        self._seq += 1
        self._m_exports.inc()
        # emit BEFORE the write: the export event is then part of the
        # worker's final dump no matter when the process dies, and the
        # fleet's fleetsnap_merge observation still strictly follows it
        self.flightrec.emit("fleetsnap_export", seq=self._seq,
                            worker=self.worker)
        payload = {
            "schema": SCHEMA,
            "worker": self.worker,
            "incarnation": self.incarnation,
            "seq": self._seq,
            "pid": os.getpid(),
            "t": now,
            "step": int(step) if step is not None else None,
            "phase": phase,
            "registry": self.registry.snapshot(),
            "flightrec_tail": self.flightrec.events()[-self.tail:],
            "flightrec_dropped": self.flightrec.dropped,
        }
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(payload, sort_keys=True, default=repr))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)  # a torn export must not look complete
        return self.path


def read_snapshot(path: str) -> dict | None:
    """Decode the snapshot at ``path``; None when absent or unreadable
    (an interrupted export never replaces the file, so unreadable means
    external corruption — logged, treated as absent)."""
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise ValueError("snapshot is not a JSON object")
        return data
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        logger.warning("unreadable fleet snapshot %s (%s); treating as "
                       "absent", path, e)
        return None


def validate_snapshot(snap: Mapping,
                      expect_worker: int | None = None) -> list[str]:
    """Schema-check a decoded snapshot; returns failures (empty ==
    pass). ``expect_worker`` additionally pins the identity: a snapshot
    claiming another worker's index under this worker's path is a label
    collision, not a merge input."""
    failures: list[str] = []
    if snap.get("schema") != SCHEMA:
        failures.append(
            f"snapshot schema {snap.get('schema')!r} != {SCHEMA!r}")
    for key in ("worker", "incarnation", "seq", "pid"):
        v = snap.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            failures.append(f"missing/non-int {key!r}: {v!r}")
    t = snap.get("t")
    if not isinstance(t, (int, float)) or isinstance(t, bool):
        failures.append(f"missing/non-numeric 't': {t!r}")
    if (expect_worker is not None and isinstance(snap.get("worker"), int)
            and snap["worker"] != expect_worker):
        failures.append(
            f"worker label collision: snapshot claims worker "
            f"{snap['worker']}, expected {expect_worker}")
    reg = snap.get("registry")
    if not isinstance(reg, Mapping):
        failures.append(f"missing/non-dict 'registry': {type(reg).__name__}")
    else:
        for key, entry in reg.items():
            if not isinstance(entry, Mapping) or "kind" not in entry:
                failures.append(f"registry entry {key!r} has no kind")
                continue
            kind = entry["kind"]
            if kind == "histogram":
                bounds, counts = entry.get("bounds"), entry.get("counts")
                if (not isinstance(bounds, list) or not isinstance(counts, list)
                        or len(counts) != len(bounds) + 1):
                    failures.append(
                        f"registry histogram {key!r} bounds/counts "
                        f"malformed")
            elif kind in ("counter", "gauge"):
                if not isinstance(entry.get("value"), (int, float)):
                    failures.append(
                        f"registry {kind} {key!r} has no numeric value")
            else:
                failures.append(
                    f"registry entry {key!r} has unknown kind {kind!r}")
    tail = snap.get("flightrec_tail")
    if not isinstance(tail, list):
        failures.append("missing/non-list 'flightrec_tail'")
    else:
        for i, e in enumerate(tail):
            if not isinstance(e, Mapping) \
                    or e.get("kind") not in _KNOWN_KINDS \
                    or not isinstance(e.get("t"), (int, float)):
                failures.append(
                    f"flightrec_tail[{i}] malformed: {e!r}")
                break
    return failures


# ---------------------------------------------------------------------------
# Fleet side: aggregation
# ---------------------------------------------------------------------------


class FleetAggregator:
    """Folds per-worker snapshots into one fleet-wide registry view.

    ``poll()`` reads every worker's snapshot file, rebuilds the merged
    view FROM SCRATCH (the scrape-aggregator pattern: re-merging a live
    counter into an accumulating registry would double-count it), and
    refreshes the derived gauges on the fleet's own registry. Snapshot
    freshness is judged by observing ``(pid, seq)`` changes on the
    aggregator's OWN clock — writer timestamps never cross processes,
    the same rule the heartbeat monitor follows. Each newly observed
    snapshot emits ``fleetsnap_merge`` into the fleet's flight recorder:
    the recurring clock anchor ``merge_timelines`` aligns on.
    """

    def __init__(self, fleet_dir: str, workers: Sequence[int],
                 registry: Registry | None = None,
                 flightrec: FlightRecorder | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.fleet_dir = fleet_dir
        self.workers = [int(w) for w in workers]
        self.registry = registry if registry is not None else default_registry()
        self.flightrec = (flightrec if flightrec is not None
                          else default_recorder())
        self.clock = clock
        self._seen: dict[int, tuple[int, int]] = {}   # worker -> (pid, seq)
        self._t_new: dict[int, float] = {}  # worker -> own-clock obs time
        #: latest (worker, incarnation, seq, step, phase) per worker
        self.status: dict[int, dict] = {}
        self._view = Registry()

    def poll(self) -> Registry:
        """One aggregation pass; returns the rebuilt merged view (also
        available as ``view()`` until the next poll)."""
        now = float(self.clock())
        view = Registry()
        union = Registry()
        for i in self.workers:
            snap = read_snapshot(fleetsnap_path(self.fleet_dir, i))
            if snap is None:
                continue
            bad = validate_snapshot(snap, expect_worker=i)
            if bad:
                logger.warning("fleet: snapshot for worker %d rejected: %s",
                               i, bad[0])
                continue
            key = (snap["pid"], snap["seq"])
            if self._seen.get(i) != key:
                self._seen[i] = key
                self._t_new[i] = now
                self.registry.counter(
                    FLEETSNAP_MERGES_TOTAL,
                    "new worker snapshots folded into the fleet view",
                    worker=str(i)).inc()
                self.flightrec.emit(
                    "fleetsnap_merge", worker=i, seq=snap["seq"],
                    pid=snap["pid"], incarnation=snap["incarnation"])
            self.status[i] = {
                "worker": i, "incarnation": snap["incarnation"],
                "seq": snap["seq"], "pid": snap["pid"],
                "step": snap.get("step"), "phase": snap.get("phase"),
            }
            try:
                view.merge(Registry.from_snapshot(
                    snap["registry"], labels={"worker": str(i)}))
                # fleet-wide union: counters/histograms sum exactly; a
                # "latest write" gauge has no cross-process union and
                # stays worker-labeled only (merge, not average — and
                # not pretend). Metrics ALREADY carrying a worker label
                # (the exporter's own fleetsnap_exports_total{worker=…})
                # are per-worker by definition and must stay out of the
                # union: their relabeled copy lands on the same key, so
                # folding both into the view would double-count them.
                union_entries = {
                    k: v for k, v in snap["registry"].items()
                    if "worker" not in (v.get("labels") or {})}
                union.merge(Registry.from_snapshot(
                    union_entries, kinds=("counter", "histogram")))
            except ValueError as e:
                logger.warning("fleet: snapshot for worker %d unmergeable: "
                               "%s", i, e)
                continue
        view.merge(union)
        for i, t0 in self._t_new.items():
            staleness = max(now - t0, 0.0)
            for reg in (self.registry, view):
                reg.gauge(
                    FLEET_WORKER_STALENESS,
                    "fleet-clock seconds since this worker's newest "
                    "snapshot was first observed",
                    worker=str(i)).set(staleness)
        productive = union.total(goodput.PRODUCTIVE_SECONDS)
        wasted = union.total(goodput.WASTED_SECONDS)
        if productive + wasted > 0:
            frac = productive / (productive + wasted)
            for reg in (self.registry, view):
                reg.gauge(
                    FLEET_GOODPUT_FRACTION,
                    "fleet-wide productive / tracked seconds, from "
                    "MERGED per-worker counters").set(frac)
        self._view = view
        return view

    def view(self) -> Registry:
        """The merged view from the last ``poll()`` — render it over the
        existing scrape path (``obs.render(agg.view())``)."""
        return self._view


# ---------------------------------------------------------------------------
# Merged cross-worker timelines
# ---------------------------------------------------------------------------


def load_dump(path: str) -> tuple[dict, list[dict]]:
    """Read a flight-recorder (or merged) JSONL dump: (header, events).
    Raises ValueError/OSError on an unreadable dump."""
    with open(path) as f:
        lines = f.read().splitlines()
    if not lines:
        raise ValueError(f"empty dump: {path}")
    header = json.loads(lines[0])
    events = [json.loads(line) for line in lines[1:]]
    return header, events


def _first(events: Iterable[Mapping], kind: str, **attrs: Any):
    for e in events:
        if e.get("kind") == kind \
                and all(e.get(k) == v for k, v in attrs.items()):
            return e
    return None


def _offset_bounds(src: str, header: Mapping, events: Sequence[Mapping],
                   fleet_events: Sequence[Mapping],
                   failures: list[str]) -> tuple[float, float]:
    """Clock-offset bounds (lo, hi) mapping this worker dump onto the
    fleet clock. Appends to ``failures`` when the required anchor is
    missing or the bounds are inconsistent.

    Hierarchical dumps (resilience/podfleet.py): the fleet dump carries
    coordinator events (no ``pod`` attr) interleaved with every pod
    supervisor's events (tagged ``pod=<p>``), and worker headers carry
    their pod. Anchors then pair WITHIN the pod — worker indices and
    per-pod incarnation counters repeat across pods, so a pod-blind
    match would align worker 0 of pod B against pod A's launch of its
    own worker 0. The coordinator's global ``fleet_done`` (no pod) is
    the one cross-pod anchor: it fires after every pod's exit, so it
    bounds every worker from above. Flat dumps have no ``pod`` anywhere
    and behave exactly as before (None == None)."""
    w, k = header["worker"], header["incarnation"]
    pod = header.get("pod")
    pid = header.get("pid")
    first_t, last_t = events[0]["t"], events[-1]["t"]
    lows: list[float] = []
    highs: list[float] = []

    # REQUIRED lower anchor: the fleet launched this process before any
    # of its events. Disambiguate multiple launches of the same slot
    # (elastic replacement relaunch) by pid.
    launches = [e for e in fleet_events if e.get("kind") == "fleet_launch"
                and e.get("worker") == w and e.get("incarnation") == k
                and e.get("pod") == pod]
    by_pid = [e for e in launches if pid is not None
              and e.get("pid") == pid]
    if by_pid:
        launches = by_pid
    if not launches:
        failures.append(
            f"{src}: clock anchor missing — no fleet_launch for worker "
            f"{w} incarnation {k} (pod {pod}, pid {pid}) in the fleet "
            f"dump")
        return 0.0, 0.0
    if len(launches) > 1:
        failures.append(
            f"{src}: clock anchor ambiguous — {len(launches)} "
            f"fleet_launch events for worker {w} incarnation {k} and no "
            f"pid match")
        return 0.0, 0.0
    lows.append(launches[0]["t"] - first_t)

    for fe in fleet_events:
        kind = fe.get("kind")
        if fe.get("pod") != pod and not (
                kind == "fleet_done" and fe.get("pod") is None):
            # another pod's (or, for a pod-scoped worker, the
            # coordinator's) events anchor nothing here — except the
            # global fleet_done, which fires after every pod exits
            continue
        if kind == "fleet_hold" and fe.get("version") is not None:
            we = _first(events, "elastic_hold", version=fe["version"])
            if we is not None:
                lows.append(fe["t"] - we["t"])
        elif kind in ("fleet_shrink", "fleet_rejoin") \
                and fe.get("version") is not None:
            we = _first(events, "elastic_release", version=fe["version"])
            if we is not None:
                lows.append(fe["t"] - we["t"])
            # the release was written only after the fleet OBSERVED the
            # holders' barrier acks: their hold precedes it
            wh = _first(events, "elastic_hold", version=fe["version"] - 1)
            if wh is not None:
                highs.append(fe["t"] - wh["t"])
        elif kind == "fleetsnap_merge" and fe.get("worker") == w \
                and pid is not None and fe.get("pid") == pid:
            we = _first(events, "fleetsnap_export", seq=fe.get("seq"))
            if we is not None:
                highs.append(fe["t"] - we["t"])
        elif kind == "ckpt_restore" and fe.get("relayed") \
                and fe.get("worker") == w and fe.get("incarnation") == k:
            we = _first(events, "ckpt_restore", step=fe.get("step"))
            if we is not None:
                highs.append(fe["t"] - we["t"])
        elif kind == "fleet_worker_dead" and fe.get("worker") == w \
                and fe.get("incarnation") == k \
                and pid is not None and fe.get("pid") == pid:
            highs.append(fe["t"] - last_t)
        elif kind == "serve_route" and fe.get("replica") == w \
                and fe.get("rid") is not None:
            # serve-fleet dispatch handshake: the router emitted the
            # dispatch before this replica ACKed it (same rid). A stale
            # pairing from an earlier dispatch of the rid to this slot
            # only loosens the bound — max(lows) keeps the tight one.
            we = _first(events, "serve_route", rid=fe["rid"])
            if we is not None:
                lows.append(fe["t"] - we["t"])
        elif kind == "serve_replica_dead" and fe.get("replica") == w \
                and fe.get("incarnation") == k \
                and pid is not None and fe.get("pid") == pid:
            highs.append(fe["t"] - last_t)
        elif kind == "fleet_done":
            # fires only after every worker's exit: all events precede
            highs.append(fe["t"] - last_t)

    lo = max(lows)
    hi = min(highs) if highs else float("inf")
    if lo > hi + 1e-9:
        failures.append(
            f"{src}: clock anchors inconsistent — offset lower bound "
            f"{lo:.6f}s exceeds upper bound {hi:.6f}s (the dumps do not "
            f"describe one causal history)")
    return lo, hi


def merge_timelines(
    fleet_path: str, worker_paths: Sequence[str], reason: str = "",
) -> tuple[dict, list[dict], list[str]]:
    """Merge one fleet dump and N worker dumps into a single
    fleet-clock timeline. Returns ``(header, events, failures)`` —
    a non-empty ``failures`` means the merge is unusable (missing
    worker identity, missing/inconsistent clock anchors, worker label
    collisions) and header/events are best-effort only.

    Every merged event carries ``src`` (``fleet``, ``w<i>i<k>``, or —
    for workers under a pod coordinator — ``p<p>w<i>i<k>``) and a
    timestamp shifted by that source's anchored offset; ties sort the
    fleet event first (anchors are happens-before edges FROM the fleet).
    Hierarchical runs hand in ONE fleet dump (coordinator + all pod
    supervisors share a process and a pod-tagging recorder), and worker
    identity becomes the triple ``(pod, worker, incarnation)``.
    """
    failures: list[str] = []
    try:
        fleet_header, fleet_events = load_dump(fleet_path)
    except (OSError, ValueError) as e:
        return {}, [], [f"unreadable fleet dump {fleet_path}: {e}"]
    sources: list[dict] = [{
        "src": "fleet", "offset": 0.0, "events": len(fleet_events),
        "pid": fleet_header.get("pid"),
    }]
    keyed: list[tuple[float, int, int, int, dict]] = []
    for j, e in enumerate(fleet_events):
        rec = dict(e)
        rec["src"] = "fleet"
        keyed.append((float(e["t"]), 0, 0, j, rec))

    seen: set[tuple[int | None, int, int]] = set()
    for si, path in enumerate(worker_paths, start=1):
        try:
            header, events = load_dump(path)
        except (OSError, ValueError) as e:
            failures.append(f"unreadable worker dump {path}: {e}")
            continue
        w, k = header.get("worker"), header.get("incarnation")
        p = header.get("pod")
        if not isinstance(w, int) or not isinstance(k, int):
            failures.append(
                f"{path}: dump header lacks worker/incarnation identity "
                f"(dump with extra={{'worker': i, 'incarnation': k}})")
            continue
        src = f"p{p}w{w}i{k}" if p is not None else f"w{w}i{k}"
        if (p, w, k) in seen:
            failures.append(
                f"worker label collision: two dumps claim "
                f"{'pod ' + str(p) + ' ' if p is not None else ''}worker "
                f"{w} incarnation {k}")
            continue
        seen.add((p, w, k))
        ident = {"pid": header.get("pid"), "worker": w, "incarnation": k}
        if p is not None:
            ident["pod"] = p
        if not events:
            sources.append({"src": src, "offset": 0.0, "events": 0,
                            **ident})
            continue
        lo, hi = _offset_bounds(src, header, events, fleet_events, failures)
        sources.append({
            "src": src, "offset": lo, "events": len(events), **ident,
            "offset_bounds": [lo, hi if hi != float("inf") else None],
        })
        for j, e in enumerate(events):
            rec = dict(e)
            rec["t"] = float(e["t"]) + lo
            rec["src"] = src
            keyed.append((rec["t"], 1, si, j, rec))

    keyed.sort(key=lambda x: x[:4])
    merged = [x[4] for x in keyed]
    header = {
        "schema": MERGED_SCHEMA,
        "reason": reason,
        "events": len(merged),
        "sources": sources,
    }
    return header, merged, failures


def write_merged(path: str, header: Mapping, events: Sequence[Mapping]) -> str:
    """Write a merged timeline as JSONL (header line + one event per
    line), with the same atomic idiom as every postmortem artifact."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(dict(header), sort_keys=True, default=repr) + "\n")
        for e in events:
            f.write(json.dumps(dict(e), sort_keys=True, default=repr) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def validate_merged_dump(path: str) -> list[str]:
    """Schema-check a merged timeline dump; returns failures (empty ==
    pass). Checks: header schema tag + event count + unique sources
    (a duplicate (worker, incarnation) is a label collision), and per
    event: numeric non-decreasing ``t``, known ``kind``, a ``src``
    declared in the header, int ``step`` when present."""
    failures: list[str] = []
    try:
        header, events = load_dump(path)
    except (OSError, ValueError) as e:
        return [f"unreadable merged dump: {e}"]
    if header.get("schema") != MERGED_SCHEMA:
        failures.append(
            f"header schema {header.get('schema')!r} != {MERGED_SCHEMA!r}")
    if header.get("events") != len(events):
        failures.append(
            f"header says {header.get('events')} events, dump has "
            f"{len(events)}")
    sources = header.get("sources")
    srcs: set[str] = set()
    if not isinstance(sources, list) or not sources:
        failures.append("header has no sources list")
    else:
        ids: set[tuple[int | None, int, int]] = set()
        for s in sources:
            if not isinstance(s, Mapping) or not isinstance(
                    s.get("src"), str):
                failures.append(f"malformed source entry: {s!r}")
                continue
            if s["src"] in srcs:
                failures.append(f"duplicate source {s['src']!r}")
            srcs.add(s["src"])
            wk = (s.get("pod"), s.get("worker"), s.get("incarnation"))
            if isinstance(wk[1], int) and isinstance(wk[2], int):
                if wk in ids:
                    failures.append(
                        f"worker label collision in sources: "
                        f"{'pod ' + str(wk[0]) + ' ' if wk[0] is not None else ''}"
                        f"worker {wk[1]} incarnation {wk[2]} appears twice")
                ids.add(wk)
            if not isinstance(s.get("offset"), (int, float)):
                failures.append(f"source {s['src']!r} has no numeric offset")
    prev_t = None
    for i, e in enumerate(events, 2):
        t = e.get("t")
        if not isinstance(t, (int, float)) or isinstance(t, bool):
            failures.append(f"line {i}: missing/non-numeric 't': {e!r}")
        elif prev_t is not None and t < prev_t:
            failures.append(
                f"line {i}: timestamp {t} decreases (prev {prev_t})")
        else:
            prev_t = t
        if e.get("kind") not in _KNOWN_KINDS:
            failures.append(f"line {i}: unknown event kind {e.get('kind')!r}")
        if not isinstance(e.get("src"), str) or (
                srcs and e.get("src") not in srcs):
            failures.append(
                f"line {i}: src {e.get('src')!r} not declared in header "
                f"sources")
        if "step" in e and not isinstance(e["step"], int):
            failures.append(f"line {i}: non-int step {e['step']!r}")
    return failures
