"""Flight recorder — a bounded ring buffer of structured run events.

The reference's MonitoredTrainingSession assumed an operator could
answer "what was the job doing when it died?" from scattered logs; the
metrics registry (obs/registry.py) answers "how much" but not "in what
order". This module is the causal record: every layer that already has
a seam — the train loop, the checkpoint manager, the retry executor,
the Supervisor, the fault harness, the serve scheduler — emits a small
structured event (monotonic timestamp, kind, step, attrs) into one
process-wide ring. The ring is bounded (old events are dropped, counted)
so a week-long run costs the same memory as a smoke test, and
lock-protected so the watchdog poll thread, async manifest stampers, and
the train loop can emit concurrently.

On any abnormal exit — emergency checkpoint, ``SupervisorExhausted``,
an unhandled ``fit`` exception — the owning layer dumps the ring as a
JSONL postmortem into the run directory; ``tools/postmortem.py`` renders
it as a human-readable causal timeline ("fault fired → emergency
checkpoint → restart → fallback restore"), and ``validate_dump`` is the
schema gate shared by ``tools/obs_check.py`` and CI.

The event vocabulary is CLOSED (``EVENT_KINDS``): ``emit`` rejects
unknown kinds, so a new emitter must extend the vocabulary here — which
is exactly what keeps the postmortem renderer, the dump validator, and
the docs event table in sync.

Nothing here imports jax — plain stdlib, usable from the scheduler's
pure-host tests and from tools that never touch a device.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, Mapping, Sequence

__all__ = [
    "EVENT_KINDS",
    "SCHEMA",
    "FlightRecorder",
    "default_recorder",
    "dump_postmortem",
    "validate_dump",
    "contains_in_order",
]

logger = logging.getLogger(__name__)

#: dump header schema tag — bump when the record layout changes
SCHEMA = "dtf-flightrec-1"

#: the closed event vocabulary (docs/observability.md has the table)
EVENT_KINDS = (
    # train loop (train/loop.py) + host callbacks (train/callbacks.py)
    "train_start",          # fit() entered                 {step}
    "step_start",           # step dispatch begins          {step}
    "step_end",             # step + callbacks done         {step}
    "train_stop",           # fit() returned                {step, reason}
    "train_exception",      # unhandled step exception      {step, error, etype}
    "emergency_checkpoint", # best-effort crash save        {step, saved}
    "watchdog_stall",       # no step within the budget     {overdue_s, budget_s}
    # distributed eval (train/evaluation.py)
    "eval_start",           # sharded eval pass begins      {step, shards}
    "eval_end",             # sharded eval pass done        {step, batches}
    # checkpoint lifecycle (train/checkpoint.py)
    "ckpt_save",            # checkpoint written            {step, trigger}
    "ckpt_async_begin",     # async snapshot enqueued       {step, trigger}
    "ckpt_async_commit",    # background commit published   {step, seconds}
    "ckpt_restore",         # state restored                {step, fallback}
    "ckpt_quarantine",      # corrupt step condemned        {step, note}
    # retry/backoff (resilience/retry.py)
    "retry_attempt",        # re-attempt after a failure    {site, failures}
    "retry_exhausted",      # budget ran out                {site, failures, reason}
    # supervision (resilience/supervisor.py)
    "sup_attempt",          # supervised attempt begins     {attempt}
    "sup_failure",          # attempt died, classified      {attempt, cause, error}
    "sup_restart",          # restart granted               {restart, cause, backoff_s}
    "sup_exhausted",        # restart budget ran out        {cause, restarts}
    # fault injection (resilience/faults.py)
    "fault_fired",          # a planned fault fired         {fault, step, ...}
    # numeric-anomaly defense (resilience/anomaly.py)
    "anomaly_skip",         # nonfinite step no-op'd in-graph, batch dropped
    #                                                       {step, index, cause}
    "anomaly_spike",        # loss spiked vs EWMA baseline  {step, index, loss, ewma}
    "anomaly_blame",        # batch index blamed+quarantined {step, index, cause}
    # fleet control plane (resilience/fleet.py)
    "fleet_start",          # fleet run begins              {workers, incarnation}
    "fleet_launch",         # worker subprocess launched    {worker, incarnation, pid}
    "fleet_worker_dead",    # liveness/exit failure         {worker, cause, detail}
    "fleet_gang_stop",      # gang torn down                {cause, survivors, killed}
    "fleet_restart",        # new gang live after restart   {restart, cause, incarnation}
    "fleet_hold",           # resize hold plan written      {version, hold, resize}
    "fleet_shrink",         # elastic shrink released       {worker, world, barrier, cause, version}
    "fleet_rejoin",         # replacement rejoined the gang {worker, world, barrier, version}
    "fleet_exhausted",      # fleet restart budget ran out  {cause, restarts}
    "fleet_done",           # every worker finished         {incarnation}
    # elastic worker client (resilience/fleet.ElasticWorker) — the
    # worker-side half of the resize handshake, the clock anchors the
    # merged cross-worker timeline aligns on (obs/fleetview.py)
    "elastic_hold",         # worker paused at a resize barrier {step, version}
    "elastic_release",      # worker applied a steady plan  {version, world, barrier, rank}
    # peer-to-peer joiner catch-up (resilience/fleet.py): a rejoining
    # worker asks a live survivor for its newest valid step over the
    # file control plane instead of replaying from its own older ckpt
    "catchup_offer",        # survivor exported a verified step {step, peer, worker}
    "catchup_restore",      # joiner imported a peer's step {step, peer, seconds}
    "catchup_fallback",     # no usable offer within budget {worker, budget_s}
    # hierarchical fault domains (resilience/podfleet.py): the global
    # coordinator + per-pod supervisors' pod-level record — every event
    # a pod supervisor emits (including the fleet_* kinds above) also
    # carries a ``pod`` attr, so one timeline spans coordinator → pod
    # supervisors → workers
    "pod_outage",           # a pod's gang failed as a unit {pod, cause}
    "pod_restart",          # pod relaunched at its own quorum ceiling
    #                                       {pod, restart, cause, ceiling}
    "pod_rejoin",           # restarted pod's gang confirmed live {pod, restart}
    "pod_fence",            # pod control plane stale but processes alive:
    #                         fenced, no restart, no stale-plan action {pod}
    "pod_unfence",          # fenced pod's control plane came back {pod, fenced_s}
    "pod_hold",             # cross-pod hold plan written   {version, hold}
    "pod_release",          # cross-pod barrier released    {version, world, barrier}
    # fleet telemetry snapshots (obs/fleetview.py)
    "fleetsnap_export",     # worker exported a snapshot    {seq, worker}
    "fleetsnap_merge",      # fleet folded a new snapshot   {worker, seq, pid, incarnation}
    # serving (serve/scheduler.py, serve/engine.py)
    "serve_admit",          # request placed into a slot    {uid, slot}
    "serve_evict",          # request left (any reason)     {uid, reason}
    "serve_drain",          # engine graceful shutdown      {finished}
    "serve_close",          # scheduler admission stopped   {cancelled}
    "serve_preempt",        # resident evicted to queue head on block
    #                         exhaustion (paged cache)      {uid, slot}
    "serve_prefill_chunk",  # one chunk of a chunked prefill
    #                                               {uid, slot, start, n}
    "serve_spec_step",      # one speculative verify step for one slot
    #                                   {uid, slot, proposed, accepted}
    # serve fleet (serve/router.py, serve/fleet.py, serve/replica.py) —
    # serve_route is BOTH halves of the dispatch handshake: the router
    # emits it when it places a request on a replica, and the replica
    # re-emits it (same rid) when it ingests the dispatch — the clock
    # anchor the merged timeline aligns serve replicas on (fleetview)
    "serve_route",          # request dispatched / ingested {rid, lane, replica, hit}
    "serve_requeue",        # in-flight requeued at lane head after its
    #                         replica died                  {rid, lane, replica, delivered}
    "serve_replica_dead",   # serve replica liveness/exit failure
    #                                       {replica, cause, incarnation, pid}
    # free-form operator note
    "note",
)

_KNOWN = frozenset(EVENT_KINDS)
#: record keys an attr may not shadow
_RESERVED = frozenset(("t", "kind", "step", "schema"))


class FlightRecorder:
    """Lock-protected ring of events, newest-``capacity`` retained.

    ``emit`` is the single write path: it stamps the monotonic clock
    *inside* the lock, so event order in the ring is timestamp order
    even under concurrent emitters — the property the postmortem
    validator checks as "monotonic timestamps".
    """

    def __init__(self, capacity: int = 2048,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.clock = clock
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dropped = 0

    # -- write -------------------------------------------------------------

    def emit(self, kind: str, step: int | None = None, **attrs: Any) -> None:
        """Record one event. ``kind`` must be in ``EVENT_KINDS``; attrs
        are free-form JSON-able fields (non-JSON values are repr'd at
        dump time, never at emit time — the hot path does no encoding)."""
        if kind not in _KNOWN:
            raise ValueError(
                f"unknown flight-recorder event kind {kind!r} "
                f"(extend EVENT_KINDS to add one)"
            )
        bad = _RESERVED.intersection(attrs)
        if bad:
            raise ValueError(f"attrs shadow reserved keys: {sorted(bad)}")
        rec: dict = {"kind": kind}
        if step is not None:
            rec["step"] = int(step)
        rec.update(attrs)
        with self._lock:
            # clock INSIDE the lock: ring order == time order
            rec["t"] = float(self.clock())
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(rec)

    # -- read --------------------------------------------------------------

    @property
    def dropped(self) -> int:
        """Events evicted by the ring bound since the last clear()."""
        with self._lock:
            return self._dropped

    def events(self) -> list[dict]:
        """Snapshot copy, oldest first."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    # -- dump --------------------------------------------------------------

    def dump(self, path: str, reason: str = "",
             extra: Mapping[str, Any] | None = None) -> str:
        """Write the ring as a JSONL postmortem: one header line
        (schema, reason, counts) then one line per event, oldest first.
        ``extra`` adds identity fields to the header (the fleet-merge
        path stamps ``worker``/``incarnation`` so obs/fleetview.py can
        pair a dump with its control-plane anchors); core header keys
        win on collision. Returns ``path``. Never raises on
        unserializable attrs — they are repr'd."""
        with self._lock:
            events = [dict(e) for e in self._ring]
            dropped = self._dropped
        header = dict(extra or {})
        header.update({
            "schema": SCHEMA,
            "reason": reason,
            "dumped_t": float(self.clock()),
            "events": len(events),
            "dropped": dropped,
            "capacity": self.capacity,
            "pid": os.getpid(),
        })
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(header, sort_keys=True, default=repr) + "\n")
            for e in events:
                f.write(json.dumps(e, sort_keys=True, default=repr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # a torn dump must not look complete
        return path

    def dump_unique(self, directory: str, reason: str = "",
                    basename: str = "postmortem") -> str:
        """Dump into ``directory`` as ``postmortem.jsonl``, suffixing
        ``-1``, ``-2``, … instead of overwriting an earlier postmortem
        (a supervised run can die more than once)."""
        d = os.path.abspath(os.path.expanduser(directory))
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{basename}.jsonl")
        n = 0
        while os.path.exists(path):
            n += 1
            path = os.path.join(d, f"{basename}-{n}.jsonl")
        return self.dump(path, reason=reason)


def dump_postmortem(recorder: FlightRecorder, directory: str | None,
                    reason: str = "") -> str | None:
    """Best-effort ``dump_unique`` for abnormal-exit paths (Supervisor
    exhaustion, FleetSupervisor exhaustion, …): the whole point of the
    recorder is this moment, so a dump failure is logged — it must
    never mask the exception the caller is about to raise. Returns the
    dump path, or None when there is no directory or the dump failed."""
    if not directory:
        return None
    try:
        path = recorder.dump_unique(directory, reason=reason)
    except Exception:
        logger.exception("flight-recorder postmortem dump failed")
        return None
    logger.warning("flight-recorder postmortem dumped to %s", path)
    return path


# ---------------------------------------------------------------------------
# Dump validation + ordering queries (shared by tools/postmortem.py,
# tools/obs_check.py, and the chaos tests)
# ---------------------------------------------------------------------------


def validate_dump(path: str) -> list[str]:
    """Schema-check a postmortem dump; returns failures (empty == pass).

    Checks: header schema tag, event count agreement, required keys
    (``t`` number, ``kind`` in the known vocabulary, ``step`` an int
    when present), and non-decreasing timestamps.
    """
    failures: list[str] = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"unreadable dump: {e}"]
    if not lines:
        return ["empty dump (no header line)"]
    try:
        header = json.loads(lines[0])
    except ValueError as e:
        return [f"header is not JSON: {e}"]
    if header.get("schema") != SCHEMA:
        failures.append(
            f"header schema {header.get('schema')!r} != {SCHEMA!r}")
    n_events = len(lines) - 1
    if header.get("events") != n_events:
        failures.append(
            f"header says {header.get('events')} events, dump has {n_events}")
    prev_t = None
    for i, line in enumerate(lines[1:], 2):
        try:
            rec = json.loads(line)
        except ValueError as e:
            failures.append(f"line {i}: not JSON ({e})")
            continue
        t = rec.get("t")
        if not isinstance(t, (int, float)) or isinstance(t, bool):
            failures.append(f"line {i}: missing/non-numeric 't': {rec!r}")
        elif prev_t is not None and t < prev_t:
            failures.append(
                f"line {i}: timestamp {t} decreases (prev {prev_t})")
        else:
            prev_t = t
        kind = rec.get("kind")
        if kind not in _KNOWN:
            failures.append(f"line {i}: unknown event kind {kind!r}")
        if "step" in rec and not isinstance(rec["step"], int):
            failures.append(f"line {i}: non-int step {rec['step']!r}")
    return failures


def contains_in_order(
    events: Iterable[Mapping],
    specs: Sequence[tuple[str, Mapping[str, Any]] | str],
) -> bool:
    """True when ``events`` (time-ordered) contains a subsequence
    matching ``specs``: each spec is a kind, or ``(kind, {attr: value})``
    where every given attr must equal the event's (compared as str, so
    CLI-supplied expectations work). The causal-order oracle for
    postmortem timelines."""
    want = list(specs)
    it = iter(events)
    for spec in want:
        kind, attrs = (spec, {}) if isinstance(spec, str) else spec
        for e in it:
            if e.get("kind") != kind:
                continue
            if all(str(e.get(k)) == str(v) for k, v in attrs.items()):
                break
        else:
            return False
    return True


_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    """The process-wide flight recorder every emitter defaults to —
    one ring per process, so a postmortem interleaves train, checkpoint,
    retry, supervisor, fault, and serve events in true causal order."""
    return _default
