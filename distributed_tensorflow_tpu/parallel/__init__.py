"""Parallelism core: mesh topology, cluster bootstrap, collectives, sharding.

Replaces the reference's L1/L2 layers (SURVEY.md §1): ClusterSpec/Server/
gRPC runtime and replica_device_setter placement.
"""

from .mesh import (  # noqa: F401
    AXIS_NAMES,
    BATCH_AXES,
    DATA,
    EXPERT,
    FSDP,
    MODEL,
    PIPE,
    SEQ,
    MeshSpec,
    PodTopology,
    build_mesh,
    describe,
    factor_mesh_axis,
    mesh_axis_size,
    rescale_for_world,
    single_device_mesh,
)
from .cluster import (  # noqa: F401
    ClusterConfig,
    initialize,
    is_chief,
    process_count,
    process_index,
    sync_hosts,
)
from . import collectives  # noqa: F401
from . import sharding  # noqa: F401
from .pipeline import (  # noqa: F401
    microbatch,
    pipeline_apply,
    stack_stages,
    stage_param_specs,
    unmicrobatch,
)
