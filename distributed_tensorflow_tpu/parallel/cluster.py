"""Multi-host cluster bootstrap — replaces ClusterSpec/Server/TF_CONFIG.

Reference mechanism (SURVEY.md §3.1, substrate $TF/python/training/
server_lib.py:96,243): every process parses ``--job_name/--task_index``,
builds a ClusterSpec naming every peer, and starts an in-process gRPC server;
PS processes then block in ``server.join()`` forever.

TPU-native shape: every host runs the *same* program. ``jax.distributed
.initialize`` stands up the coordination service (the control plane the
reference got from gRPC + TF_CONFIG), after which ``jax.devices()`` is global
and XLA owns the data plane (ICI within a slice, DCN between slices). There
are no roles — no PS, no "chief session" — only process 0 conventionally
doing singleton host work (logging, checkpoint metadata), mirroring how the
reference's chief ran init and the sync token queue (SURVEY.md §3.1).
"""

from __future__ import annotations

import dataclasses
import logging
import os

import jax

logger = logging.getLogger(__name__)

_initialized = False


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Topology flags. All default to single-process (the common TPU-VM case,
    where the TPU runtime discovers peers itself and none of these are
    needed — the analog of how TPUClusterResolver replaced hand-written
    ClusterSpecs, $TF/python/distribute/cluster_resolver/tpu/
    tpu_cluster_resolver.py:95).
    """

    coordinator_address: str | None = None  # "host:port" of process 0
    num_processes: int | None = None
    process_id: int | None = None
    local_device_ids: tuple[int, ...] | None = None
    # "auto" (default): argless jax.distributed.initialize() when TPU-pod
    # environment markers are present (the TPUClusterResolver analog,
    # $TF tpu_cluster_resolver.py:95 — metadata autodetection); "always":
    # force argless init; "never": only explicit/env-configured init.
    auto_detect: str = "auto"
    # Non-empty = persistent XLA compilation cache directory (first TPU
    # compile is tens of seconds; restarts/resumes then load it in
    # milliseconds — the checkpoint-restart elasticity story of SURVEY.md
    # §5.3 leans on fast re-entry). Also honors JAX_COMPILATION_CACHE_DIR.
    compilation_cache_dir: str = ""


def initialize(config: ClusterConfig | None = None) -> None:
    """Idempotent multi-host init. Safe to call in single-process runs.

    Replaces the per-role bootstrap of SURVEY.md §3.1 (ClusterSpec → Server →
    ps? join : build graph). Call once at program start, before any
    device-touching JAX call.
    """
    global _initialized
    if _initialized:
        return
    # Honor JAX_PLATFORMS explicitly: plugin registration hooks (e.g. a
    # tunneled-TPU site module) may have overridden the config default at
    # import time, which would silently ignore the user's env var.
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)
    config = config or ClusterConfig()
    cache_dir = config.compilation_cache_dir or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", ""
    )
    if cache_dir:
        if jax.config.jax_compilation_cache_dir != cache_dir:
            # the persistent-cache backend binds lazily on FIRST use —
            # to the dir configured then, or to "disabled" if none was.
            # If some earlier code (a test rig, a notebook, any jit
            # before initialize()) already bound it, reset so the
            # configured dir actually takes effect for this process.
            # Private API — best-effort only: if a jax upgrade moves
            # it, the stale binding costs cache hits, never correctness.
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except (ImportError, AttributeError) as e:
                logger.warning(
                    "could not reset the compilation cache binding "
                    "(private jax API moved?): %s — the configured "
                    "cache dir may not take effect this process", e)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even quick-compiling programs: resume-after-preemption
        # replays the whole startup, so every skipped compile counts.
        # An explicit env threshold wins (same env-honoring contract as
        # JAX_PLATFORMS above).
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
    explicit = config.coordinator_address is not None
    env = "COORDINATOR_ADDRESS" in os.environ
    if explicit or env:
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
            local_device_ids=config.local_device_ids,
        )
        _log_topology()
    elif config.auto_detect == "always" or (
        config.auto_detect == "auto" and _on_multihost_tpu_pod()
    ):
        # Pod-idiomatic path: argless initialize lets jax's cluster
        # autodetection (GCE/TPU metadata) discover coordinator + peers —
        # the TPUClusterResolver analog. Never triggered on single-host
        # TPU-VMs or CPU test rigs.
        jax.distributed.initialize()
        _log_topology()
    _initialized = True


def _on_multihost_tpu_pod() -> bool:
    """True when env markers say this process is one worker of a multi-host
    Cloud-TPU pod slice. `TPU_WORKER_HOSTNAMES` lists every peer host of
    the slice (set by the TPU runtime); more than one entry means
    multi-host, where argless jax.distributed.initialize is both safe and
    required for a global jax.devices() view."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if "," in hostnames:
        return True
    # Multislice (MEGASCALE) deployments always need the coordination
    # service, even with one host per slice.
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    return False


def _log_topology() -> None:
    logger.info(
        "jax.distributed initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_chief() -> bool:
    """Process 0 — the singleton-host-work role. Unlike the reference's chief
    (ChiefSessionCreator, $TF monitored_session.py:623) it holds no special
    graph state: any process could take over after a restart."""
    return jax.process_index() == 0


def sync_hosts(name: str = "sync") -> None:
    """Host-level barrier across processes (the reference's analog was the
    token queue + wait_for_session, SURVEY.md §3.1). No-op single-process."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
