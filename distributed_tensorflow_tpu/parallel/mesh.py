"""Device mesh & named-axis abstraction — the topology core of the framework.

This replaces the reference harness's cluster-topology layer
(``tf.train.ClusterSpec`` / ``tf.train.Server`` / ``replica_device_setter`` —
see SURVEY.md §2b rows 1-3; substrate: $TF/python/training/server_lib.py:243,
device_setter.py:129). Where the reference mapped *ops* onto *processes*
(variables round-robin onto PS tasks, compute onto the local worker, gRPC
inserted at every job boundary), a TPU-native design maps one SPMD program onto
a named device mesh and lets XLA compile collectives onto ICI/DCN
(SURVEY.md §2d, §5.8).

Axis vocabulary (all six are always present; unused axes have size 1 so that
every PartitionSpec in the codebase is valid on every mesh):

- ``pipe``   — pipeline-parallel stages (1F1B schedule, parallel/pipeline.py)
- ``data``   — pure data parallelism (gradient psum rides this axis)
- ``fsdp``   — data parallelism with parameter/optimizer-state sharding
               (ZeRO-style weight-update sharding, arXiv:2004.13336)
- ``seq``    — sequence/context parallelism (ring attention / Ulysses,
               parallel/ring_attention.py, SURVEY.md §5.7)
- ``expert`` — expert parallelism for MoE token dispatch (mesh-axis stub per
               SURVEY.md §2c; full MoE is out of baseline scope)
- ``model``  — tensor parallelism (megatron-style column/row sharding)

Axis order puts ``model`` innermost: ``mesh_utils.create_device_mesh`` assigns
innermost axes to physically adjacent chips, so the highest-traffic collectives
(TP all-gather / reduce-scatter every layer) ride the shortest ICI hops.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis order, outermost → innermost.
AXIS_NAMES: tuple[str, ...] = ("pipe", "data", "fsdp", "seq", "expert", "model")

PIPE, DATA, FSDP, SEQ, EXPERT, MODEL = AXIS_NAMES

#: Axes over which a batch is split. Gradients are summed over these axes
#: (explicitly under shard_map; implicitly by GSPMD under jit).
BATCH_AXES: tuple[str, ...] = (DATA, FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. ``-1`` on at most one axis means "absorb the rest".

    The TPU-native analog of the reference's ``--ps_hosts/--worker_hosts``
    flags (SURVEY.md §5.6): instead of listing host:port endpoints, the user
    names how many ways each *meaning* of parallelism is applied, and the
    device mesh is derived from the physical topology.
    """

    pipe: int = 1
    data: int = -1  # default: all remaining devices do data parallelism
    fsdp: int = 1
    seq: int = 1
    expert: int = 1
    model: int = 1
    # DCN (inter-slice) factors for multislice pods (BASELINE.json:10
    # "pod-scale"): the TOTAL size of an axis is its ICI part × its DCN
    # part. E.g. data=8, dcn_data=2 → each of 2 slices holds 4-way ICI
    # data parallelism, and the gradient psum's final hop rides DCN.
    # Only axes whose collectives tolerate DCN latency (data/pipe grad
    # reduction, not per-layer TP) get dcn_* knobs — the
    # mesh_utils.create_hybrid_device_mesh recipe.
    dcn_data: int = 1
    dcn_pipe: int = 1

    def sizes(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_NAMES}

    def dcn_sizes(self) -> dict[str, int]:
        return {PIPE: self.dcn_pipe, DATA: self.dcn_data, FSDP: 1,
                SEQ: 1, EXPERT: 1, MODEL: 1}

    @property
    def num_slices(self) -> int:
        return self.dcn_data * self.dcn_pipe

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill in the single -1 axis so the product equals ``n_devices``.
        Axis fields are TOTALS (ICI × DCN); each must divide by its dcn_*
        factor."""
        sizes = self.sizes()
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one axis may be -1, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product "
                    f"{fixed} ({sizes})"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"Mesh {sizes} needs {fixed} devices but {n_devices} are "
                f"available"
            )
        out = MeshSpec(**sizes, dcn_data=self.dcn_data, dcn_pipe=self.dcn_pipe)
        for name, dcn in out.dcn_sizes().items():
            if dcn > 1 and out.sizes()[name] % dcn != 0:
                raise ValueError(
                    f"axis {name}={out.sizes()[name]} not divisible by its "
                    f"DCN factor dcn_{name}={dcn}"
                )
        return out

    @classmethod
    def from_dict(cls, d: Mapping[str, int]) -> "MeshSpec":
        valid = set(AXIS_NAMES) | {"dcn_data", "dcn_pipe"}
        unknown = set(d) - valid
        if unknown:
            raise ValueError(f"Unknown mesh axes {unknown}; valid: {sorted(valid)}")
        return cls(**dict(d))


@dataclasses.dataclass(frozen=True)
class PodTopology:
    """Two-level fault-domain topology: ``num_pods`` identical pods.

    ``pod_spec`` is the ICI mesh of ONE pod — its ``dcn_*`` factors
    must be 1, because the only inter-pod dimension is the one this
    descriptor adds. The flat mesh the trainer builds is
    ``to_mesh_spec()``: the data axis grows ``num_pods``-fold and its
    new outer hop is declared DCN (``dcn_data = num_pods``), so the
    gradient psum reduces intra-pod first and crosses pod boundaries
    exactly once — the same hybrid-mesh recipe as multislice, with the
    slice boundary reinterpreted as the FAULT boundary
    (resilience/podfleet.py supervises one fault domain per pod; a
    pod's outage shrinks or holds this axis, never the intra-pod ones).

    Only ``data`` may span pods: ``model`` / ``pipe`` / ``seq`` /
    ``expert`` collectives are latency-critical per layer and a pod
    restart must never re-partition parameter state — the same rule
    ``rescale_for_world`` enforces one level down.
    """

    num_pods: int
    pod_spec: MeshSpec = MeshSpec()

    def __post_init__(self):
        if self.num_pods < 1:
            raise ValueError(f"num_pods must be >= 1, got {self.num_pods}")
        if self.pod_spec.num_slices != 1:
            raise ValueError(
                "pod_spec describes ONE pod's ICI mesh: its dcn_* factors "
                f"must be 1 (got dcn_data={self.pod_spec.dcn_data}, "
                f"dcn_pipe={self.pod_spec.dcn_pipe}); cross-pod DCN comes "
                "from num_pods")

    def to_mesh_spec(self) -> MeshSpec:
        """The flat (total-extent) MeshSpec for the whole fleet: pod
        data extent × num_pods on the data axis, pod boundary = DCN."""
        data = self.pod_spec.data
        total = data if data == -1 else data * self.num_pods
        return dataclasses.replace(
            self.pod_spec, data=total, dcn_data=self.num_pods)

    def resolve(self, n_devices: int) -> "PodTopology":
        """Fill the pod_spec wildcard from the PER-POD device count."""
        if n_devices % self.num_pods != 0:
            raise ValueError(
                f"{n_devices} devices not divisible into {self.num_pods} "
                "pods")
        return dataclasses.replace(
            self, pod_spec=self.pod_spec.resolve(n_devices // self.num_pods))

    @property
    def devices_per_pod(self) -> int:
        """Device count of one pod (pod_spec must be resolved)."""
        sizes = self.pod_spec.sizes()
        if -1 in sizes.values():
            raise ValueError("pod_spec has an unresolved -1 axis; call "
                             "resolve(n_devices) first")
        return math.prod(sizes.values())

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodTopology":
        """``{"num_pods": n, "pod": {<MeshSpec axes>}}``."""
        unknown = set(d) - {"num_pods", "pod"}
        if unknown:
            raise ValueError(
                f"Unknown PodTopology keys {unknown}; valid: num_pods, pod")
        return cls(num_pods=int(d.get("num_pods", 1)),
                   pod_spec=MeshSpec.from_dict(d.get("pod", {})))

    def describe(self) -> str:
        sizes = " ".join(f"{a}={v}" for a, v in self.pod_spec.sizes().items())
        return f"{self.num_pods} pod(s) × [{sizes}]"


def build_mesh(
    spec: MeshSpec | Mapping[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with the canonical six named axes.

    Replaces ``ClusterSpec`` + ``Server`` bootstrap (SURVEY.md §3.1 frames
    1-3): there is no per-process server to start — the runtime owns
    transport, and this mesh is the only topology object the user touches.
    """
    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec()
    if not isinstance(spec, MeshSpec):
        spec = MeshSpec.from_dict(spec)
    spec = spec.resolve(len(devices))
    shape = tuple(spec.sizes()[name] for name in AXIS_NAMES)
    if spec.num_slices > 1:
        dev_array = _hybrid_device_array(spec, devices)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=np.asarray(devices, dtype=object)
            )
        except (ValueError, AssertionError, NotImplementedError):
            # Fallback for topologies mesh_utils cannot optimize (e.g. CPU
            # fake devices or single-chip): plain row-major reshape.
            # Collective placement is still correct, just not hop-optimal.
            dev_array = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(dev_array, AXIS_NAMES)


def rescale_for_world(spec: MeshSpec, old_world: int,
                      new_world: int) -> MeshSpec:
    """Respec a mesh for an elastic fleet resize (docs/resilience.md
    "Elastic fleet"): the worker count changed ``old_world →
    new_world``, so the device pool scales by the same ratio.

    Only the BATCH axes may absorb a world change — ``model`` / ``pipe``
    / ``seq`` / ``expert`` extents are baked into parameter and
    activation layouts, and resizing them would re-partition state, not
    just re-partition the batch. Concretely:

    - ``data == -1`` passes through: the wildcard already absorbs
      whatever devices the surviving workers contribute.
    - otherwise the first of ``data``, ``fsdp`` (both are BATCH_AXES)
      whose explicit extent scales integrally by
      ``new_world / old_world`` absorbs the change; the DCN factor
      constraint is re-validated by ``resolve`` at build time.

    Anything else raises with the fix spelled out. The returned spec is
    what a (re)launched worker passes to ``build_mesh`` for the resized
    gang; the data-stream half of the resize is
    ``data/pipeline.ElasticStream``."""
    if old_world < 1 or new_world < 1:
        raise ValueError("old_world and new_world must be >= 1")
    if new_world == old_world or spec.data == -1:
        return spec
    for axis in (DATA, FSDP):
        extent = getattr(spec, axis)
        scaled = extent * new_world
        if scaled % old_world == 0 and scaled >= old_world:
            return dataclasses.replace(spec, **{axis: scaled // old_world})
    raise ValueError(
        f"neither batch axis scales by {new_world}/{old_world} "
        f"(data={spec.data}, fsdp={spec.fsdp}): the resized extent would "
        f"not be integral — use data=-1 so the batch axis absorbs the "
        f"surviving devices, or pick a fleet size dividing a batch-axis "
        f"extent")


def _hybrid_device_array(spec: MeshSpec, devices: Sequence[jax.Device]) -> np.ndarray:
    """Device array for a multislice ICI×DCN mesh (SURVEY.md §2d: ICI
    within a slice, DCN between slices; the DeviceAssignment/Topology
    analog, $TF device_assignment.py:70).

    Per axis, the DCN factor is the OUTER sub-dimension: neighboring
    indices along an axis stay on the same slice (ICI), and only the
    outermost hop crosses DCN — so e.g. a gradient psum over `data`
    reduces intra-slice first. Uses mesh_utils.create_hybrid_device_mesh
    (slice-topology-aware) when device slice metadata exists; falls back
    to a slice-major block construction for test rigs without it."""
    totals = spec.sizes()
    dcn = spec.dcn_sizes()
    ici_shape = tuple(totals[a] // dcn[a] for a in AXIS_NAMES)
    dcn_shape = tuple(dcn[a] for a in AXIS_NAMES)
    np_devices = np.asarray(devices, dtype=object)
    try:
        return mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=np_devices
        )
    except (ValueError, AssertionError, NotImplementedError, KeyError):
        # Fake-device fallback: jax.devices() is process-/slice-major, so
        # reshape (dcn..., ici...) then interleave to put each axis's DCN
        # part just outside its ICI part.
        arr = np_devices.reshape(*dcn_shape, *ici_shape)
        n = len(AXIS_NAMES)
        perm = [k for pair in zip(range(n), range(n, 2 * n)) for k in pair]
        arr = arr.transpose(perm)
        return arr.reshape(tuple(totals[a] for a in AXIS_NAMES))


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """A 1×1×1×1×1×1 mesh: lets every sharded code path run on one chip."""
    if device is None:
        device = jax.devices()[0]
    return build_mesh(MeshSpec(data=1), [device])


def factor_mesh_axis(
    mesh: Mesh, axis: str, factors: Mapping[str, int]
) -> Mesh:
    """Split one named mesh axis into ordered sub-axes (outer → inner).

    This is the API form of "structural subgroups get their own mesh axis"
    (SURVEY.md §5.8; the TPU-native descendant of NCCL communicator
    subgroups / CrossReplicaSum ``group_assignment``, $TF tpu_ops.py:32-40):
    a collective over ONE sub-axis compiles to a true subgroup collective —
    XLA emits an all-reduce over just those replica groups, no full-axis
    gather — unlike the mask-emulated ``groups=`` path in
    parallel/collectives.py, whose wire cost is the whole axis.

    >>> sub = factor_mesh_axis(mesh, "data", {"replica": 2, "shard": 4})
    >>> # inside shard_map over `sub`: lax.psum(x, "shard") reduces within
    >>> # each group of 4; lax.psum(x, ("replica", "shard")) == old axis.

    Device placement is unchanged — only the naming is refined, so
    sub-axis groups are exactly the contiguous index blocks the emulated
    path expresses as ``groups=[[0..k-1], [k..2k-1], ...]``.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
    for name in factors:
        if name in mesh.axis_names:
            raise ValueError(f"sub-axis name {name!r} already in mesh")
    size = mesh.shape[axis]
    if math.prod(factors.values()) != size:
        raise ValueError(
            f"factors {dict(factors)} do not multiply to {axis}={size}"
        )
    idx = mesh.axis_names.index(axis)
    new_shape = (
        mesh.devices.shape[:idx]
        + tuple(factors.values())
        + mesh.devices.shape[idx + 1:]
    )
    new_names = (
        mesh.axis_names[:idx] + tuple(factors) + mesh.axis_names[idx + 1:]
    )
    return Mesh(mesh.devices.reshape(new_shape), new_names)


def mesh_axis_size(mesh: Mesh, axes: str | Sequence[str]) -> int:
    """Product of the named axis sizes (e.g. total batch shards)."""
    if isinstance(axes, str):
        axes = (axes,)
    return math.prod(mesh.shape[a] for a in axes)


def describe(mesh: Mesh) -> str:
    """Human-readable one-liner, e.g. 'pipe=1 data=4 fsdp=1 seq=1 expert=1 model=2 (8 devices, cpu)'."""
    parts = " ".join(f"{a}={mesh.shape[a]}" for a in AXIS_NAMES)
    plat = mesh.devices.flat[0].platform
    return f"{parts} ({mesh.size} devices, {plat})"
