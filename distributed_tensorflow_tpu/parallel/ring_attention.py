"""Sequence/context parallelism: ring attention, Ulysses, all-gather KV.

SURVEY.md §5.7 — first-class new-framework capability (the reference
predates transformers; nothing to port). Three interchangeable schedules
for attention over a sequence sharded on the ``seq`` mesh axis, all
expressed as ``shard_map`` islands whose collectives XLA lowers onto ICI
(the torus makes the ring's neighbor-exchange native — SURVEY.md §2d):

- **ring** (`ring attention`): K/V shards rotate around the ring via
  ``jax.lax.ppermute`` while each device folds every visiting shard into
  its queries' online-softmax state (the same recurrence as
  ops/attention.blockwise_attention, carried across devices instead of
  blocks). Activation memory O(S_local²) per step under remat; K/V
  residency O(S_global/N). Backward differentiates through the scan —
  ppermute's AD transpose is the reverse-direction ppermute, so the
  gradient ring falls out of autodiff.
- **ulysses** (attention-head all-to-all): ``all_to_all`` re-shards
  seq→heads, runs the dense per-head attention locally (the Pallas flash
  kernel on TPU), then re-shards heads→seq. Cheaper than the ring when
  heads ≥ seq-shards; requires H % seq_shards == 0.
- **allgather**: all-gather K/V over the seq axis, compute the local query
  chunk against the full K/V. Simplest; K/V residency O(S_global) —
  the right choice when S_global·D fits HBM comfortably.

Selection is by config string (SURVEY.md §5.7 "offer both, selected by
config"); `sequence_parallel_attention` is the dispatcher the transformer
models call.

Global-position bookkeeping: each device owns the contiguous query chunk
``[idx·S_local, (idx+1)·S_local)``; causal masks and padding masks are
evaluated in global coordinates on every device, so the sharded result
matches the unsharded oracle exactly (tests/test_ring_attention.py).
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import NEG_INF, attention_reference
from ..utils.compat import shard_map
from ..ops.flash_attention import flash_attention
from . import mesh as mesh_lib

Impl = Literal["ring", "ulysses", "allgather"]


def _inner_attention(q, k, v, *, causal, kv_mask, q_offset, kv_offset):
    """Dense attention on local tiles with GLOBAL-coordinate masking.

    q [B,H,Sq,D] starting at global position q_offset; k/v [B,H,Sk,D]
    starting at kv_offset; kv_mask [B,Sk] or None. Returns (out_unnorm,
    m, l): the un-normalized accumulator and row stats, so callers can
    merge partial results across ring steps / shards."""
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * (q.shape[-1] ** -0.5)
    mask = jnp.ones(logits.shape, bool)
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, None, :]
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = kv_offset + jnp.arange(k.shape[2])[None, :]
        mask = mask & (kpos <= qpos)[None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    m = logits.max(-1)  # [B,H,Sq]
    p = jnp.where(mask, jnp.exp(logits - m[..., None]), 0.0)
    l = p.sum(-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out, m, l


def _ring_body(q, k, v, kv_mask, *, axis, causal, n_shards, s_local):
    """Per-device ring schedule (runs inside shard_map)."""
    idx = jax.lax.axis_index(axis)
    q_offset = idx * s_local
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    if kv_mask is None:
        kv_mask = jnp.ones((q.shape[0], k.shape[2]), bool)

    @jax.checkpoint
    def fold(carry_acc, carry_m, carry_l, k_t, v_t, mask_t, src_idx):
        out, m, l = _inner_attention(
            q, k_t, v_t, causal=causal, kv_mask=mask_t,
            q_offset=q_offset, kv_offset=src_idx * s_local,
        )
        m_new = jnp.maximum(carry_m, m)
        c_old = jnp.exp(carry_m - m_new)
        c_cur = jnp.exp(m - m_new)
        acc = carry_acc * c_old[..., None] + out * c_cur[..., None]
        l_new = carry_l * c_old + l * c_cur
        return acc, m_new, l_new

    def maybe_fold(acc, m, l, k_t, v_t, mask_t, src_idx):
        if not causal:
            return fold(acc, m, l, k_t, v_t, mask_t, src_idx)
        # A strictly-future shard (src_idx > idx) is fully masked by the
        # global causal mask — skip its O(S_local²) attention entirely
        # (≈halves causal ring FLOPs; the ppermute still runs, keeping the
        # ring schedule uniform across devices).
        return jax.lax.cond(
            src_idx > idx,
            lambda a, mm, ll, *_: (a, mm, ll),
            fold,
            acc, m, l, k_t, v_t, mask_t, src_idx,
        )

    def step(carry, t):
        acc, m, l, k_t, v_t, mask_t = carry
        src_idx = (idx - t) % n_shards  # whose shard is visiting now
        acc, m, l = maybe_fold(acc, m, l, k_t, v_t, mask_t, src_idx)
        k_t = jax.lax.ppermute(k_t, axis, perm)
        v_t = jax.lax.ppermute(v_t, axis, perm)
        mask_t = jax.lax.ppermute(mask_t, axis, perm)
        return (acc, m, l, k_t, v_t, mask_t), None

    B, H, Sq, D = q.shape
    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    # scan the first n-1 (fold + rotate) steps; fold the last visiting
    # shard outside the loop — a rotation after the final fold would still
    # go out on the wire (scan bodies are identical every iteration, XLA
    # cannot dead-code it), costing 1/N of total ring traffic
    (acc, m, l, k_last, v_last, mask_last), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v, kv_mask), jnp.arange(n_shards - 1)
    )
    acc, m, l = maybe_fold(
        acc, m, l, k_last, v_last, mask_last, (idx + 1) % n_shards
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _ulysses_body(q, k, v, kv_mask, *, axis, causal, n_shards, s_local,
                  use_flash):
    """seq→heads all_to_all, dense local attention, heads→seq back."""

    def seq_to_heads(x):  # [B, H, S_loc, D] -> [B, H/N, S_glob, D]
        return jax.lax.all_to_all(
            x, axis, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(x):  # [B, H/N, S_glob, D] -> [B, H, S_loc, D]
        return jax.lax.all_to_all(
            x, axis, split_axis=2, concat_axis=1, tiled=True
        )

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if kv_mask is not None:
        # mask is sharded [B, S_loc] like kv; gather the full row
        maskg = jax.lax.all_gather(kv_mask, axis, axis=1, tiled=True)
    else:
        maskg = None
    if use_flash:
        og = flash_attention(qg, kg, vg, causal=causal, kv_mask=maskg)
    else:
        og = attention_reference(qg, kg, vg, causal=causal, kv_mask=maskg)
    return heads_to_seq(og)


def _allgather_body(q, k, v, kv_mask, *, axis, causal, n_shards, s_local,
                    use_flash):
    """All-gather K/V; local queries attend to the full sequence."""
    idx = jax.lax.axis_index(axis)
    kg = jax.lax.all_gather(k, axis, axis=2, tiled=True)
    vg = jax.lax.all_gather(v, axis, axis=2, tiled=True)
    maskg = (
        jax.lax.all_gather(kv_mask, axis, axis=1, tiled=True)
        if kv_mask is not None else None
    )
    if use_flash and not causal:
        out = flash_attention(q, kg, vg, kv_mask=maskg)
    else:
        # causal path stays dense even under use_flash: the flash kernel's
        # causal alignment is the static offset Sk - Sq, but here each
        # device's q chunk sits at a *traced* mid-sequence offset
        # (axis_index), which a Mosaic-compiled kernel cannot take.
        out, m, l = _inner_attention(
            q, kg, vg, causal=causal, kv_mask=maskg,
            q_offset=idx * s_local, kv_offset=0,
        )
        out = (out / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
    return out


def sequence_parallel_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    impl: Impl = "ring",
    causal: bool = False,
    kv_mask: jax.Array | None = None,
    use_flash: bool | None = None,
) -> jax.Array:
    """Attention over a sequence sharded on the ``seq`` mesh axis.

    Takes GLOBAL arrays (q/k/v [B, H, S, D], kv_mask [B, S]) inside or
    outside jit; shard_map shards them: batch over (data, fsdp), heads
    over model, seq over seq. Returns the global [B, H, S, D] result,
    numerically equal to the unsharded oracle.

    With seq axis size 1 this degenerates to one dense local attention
    (the shard_map is a no-op ring of length 1), so models can call it
    unconditionally."""
    n_shards = mesh.shape[mesh_lib.SEQ]
    B, H, S, D = q.shape
    if S % n_shards:
        raise ValueError(f"seq len {S} not divisible by seq axis {n_shards}")
    model_shards = mesh.shape[mesh_lib.MODEL]
    if H % model_shards:
        raise ValueError(
            f"heads ({H}) not divisible by model axis ({model_shards})"
        )
    if impl == "ulysses" and (H // model_shards) % n_shards:
        # heads are already sharded over the model axis by qkv_spec; the
        # all_to_all further splits the LOCAL head count by seq shards
        raise ValueError(
            f"ulysses needs local heads ({H}//{model_shards}) divisible by "
            f"seq shards ({n_shards})"
        )
    s_local = S // n_shards
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"

    qkv_spec = P((mesh_lib.DATA, mesh_lib.FSDP), mesh_lib.MODEL,
                 mesh_lib.SEQ, None)
    mask_spec = P((mesh_lib.DATA, mesh_lib.FSDP), mesh_lib.SEQ)

    body = {
        "ring": functools.partial(
            _ring_body, axis=mesh_lib.SEQ, causal=causal,
            n_shards=n_shards, s_local=s_local,
        ),
        "ulysses": functools.partial(
            _ulysses_body, axis=mesh_lib.SEQ, causal=causal,
            n_shards=n_shards, s_local=s_local, use_flash=use_flash,
        ),
        "allgather": functools.partial(
            _allgather_body, axis=mesh_lib.SEQ, causal=causal,
            n_shards=n_shards, s_local=s_local, use_flash=use_flash,
        ),
    }[impl]

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec,
                  mask_spec if kv_mask is not None else None),
        out_specs=qkv_spec,
        check_vma=False,  # masks/iota are device-invariant; skip the check
    )
    return sharded(q, k, v, kv_mask)
