"""Pipeline parallelism over the ``pipe`` mesh axis.

SURVEY.md §2c row 'Pipeline parallel (PP)': absent from the reference
(its model-parallelism was variables round-robined over PS processes,
device_setter.py:147-149); this is the TPU-native mechanism — a
collective-permute microbatch schedule expressed as one ``shard_map``
island, activations hopping stage→stage over ICI via ``ppermute``.

Design (the standard GPipe-on-SPMD formulation, cf. the scaling-book's
pipelining chapter and praxis' LayerwiseShardablePipelined):

- Every stage runs the SAME program (SPMD); stage identity comes from
  ``lax.axis_index('pipe')``. Stage s holds the parameters for its layer
  slice — every parameter leaf carries a leading ``[n_stages, ...]`` dim
  sharded ``P('pipe')``, so each device materializes only its own slice.
- The schedule is a ``lax.scan`` over ``M + S - 1`` ticks (M microbatches,
  S stages). At tick t, stage 0 injects microbatch t (while t < M), every
  stage applies its layers to its current buffer, and the buffer rotates
  one hop around the ring. Stage S-1's outputs are collected into the
  result; trailing-edge devices compute on garbage that is masked out —
  the classic (S-1)/(M+S-1) bubble.
- Backward is autodiff through the scan: ``ppermute``'s transpose is the
  reverse-direction ``ppermute``, so the backward pipeline (activations'
  cotangents flowing stage S-1 → 0) falls out of ``jax.grad`` — no
  hand-written 1F1B needed for correctness. ``jax.checkpoint`` on the
  stage fn keeps activation memory at O(layers_per_stage) per tick.
- Output collection: only stage S-1 holds real outputs; they are
  broadcast to all pipe ranks with a masked ``psum`` so downstream global
  code (loss over the full batch) sees a pipe-replicated array. Traffic
  analysis (why this is kept): the psum moves ~2(S-1)/S of the output
  bytes once per step, and its *transpose is communication-free* (the
  cotangent arrives already pipe-replicated from the replicated loss and
  is masked locally). The alternatives measure the same or worse:
  all_gather+index is (S-1)/S forward but its transpose is a
  psum_scatter of the same order, and riding outputs around the existing
  ppermute ring for S-1 extra drain ticks moves exactly the same bytes
  as the psum while adding S-1 ticks of garbage compute.
- Memory schedule: ``jax.checkpoint`` on the stage fn bounds live
  activations at one stage-IO buffer per in-flight microbatch — O(M)
  per device (GPipe), not 1F1B's O(S). True 1F1B needs hand-interleaved
  forward/backward ticks (a custom VJP over the whole schedule) because
  autodiff-through-scan replays the forward schedule before starting the
  backward one; documented as the known delta vs Megatron-style
  schedulers rather than half-built.

Constraints (documented, standard): stage_fn must be shape-preserving
([mb, ...] -> [mb, ...]); heterogeneous ends (embedding lookup, output
head) run OUTSIDE the pipeline, pipe-replicated — see the pipelined
path in models/transformer.py (to_pipeline_params/pipelined_apply). Composes with data/fsdp (batch dim sharded inside
the same shard_map) AND with tensor parallelism inside a stage: pass
``param_specs`` that shard kernel dims over `model` and a ``stage_fn``
that does the matching manual collectives — the transformer family wires
this via ``Block(tp_shards=...)`` (megatron column/row slices + psum),
see models/transformer.pipelined_apply.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import mesh as mesh_lib
from . import sharding
from ..utils.compat import shard_map


def stage_param_specs(stage_params: Any) -> Any:
    """P('pipe', None, ...) for every leaf (leading dim = stage) —
    constructed at the sharding seam (sharding.stacked_stage_specs)."""
    return sharding.stacked_stage_specs(stage_params)


def stack_stages(per_stage: list) -> Any:
    """[tree_0, ..., tree_{S-1}] (same structure) -> one tree with a
    leading stage dim on every leaf."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage)


def pipeline_apply(
    stage_fn: Callable[..., jax.Array],
    stage_params: Any,
    x_mb: jax.Array,
    mesh: Mesh,
    aux_mb: Any = None,
    n_virtual: int = 1,
    param_specs: Any = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Run ``x_mb`` through the S-stage (optionally interleaved) pipeline.

    stage_fn: (params_slice, x [mb, ...]) -> y [mb, ...] — shape-preserving.
        With ``aux_mb``, (params_slice, x, aux) -> y.
    stage_params: every leaf [S, ...] (``n_virtual == 1``) or
        [S, V, ...] (interleaved: device d holds chunks v·S+d for
        v in [0, V)), to be sharded P('pipe') on the leading dim.
    x_mb: [M, mb, ...] microbatches; mb dim is sharded over (data, fsdp),
        the microbatch dim M is replicated. Returns [M, mb, ...] outputs,
        pipe-replicated.
    aux_mb: optional pytree of [M, mb, ...] per-microbatch side inputs
        (e.g. attention masks) that do NOT hop the ring: every rank holds
        all microbatches' aux (they are small), and the schedule indexes
        the slice for the microbatch currently at this stage.
    n_virtual: V > 1 runs the Megatron-style interleaved (circular)
        schedule — the network is cut into S·V chunks of L/(S·V) layers,
        each device owns V non-contiguous chunks, and the bubble shrinks
        V-fold to (S-1)/(M·V+S-1) at the cost of retaining ~V× more
        per-tick activations for the backward (the scan is V× longer).
        Requires M % S == 0.
    param_specs: override the default P('pipe', None, ...) per-leaf specs
        — for PP×TP, pass specs that ALSO shard kernel dims over `model`
        (models/transformer.pipeline_param_specs(tp=True)); stage_fn is
        then responsible for the matching manual collectives (Block's
        tp_shards psums). Specs must keep 'pipe' on the leading dim.
    rng: optional PRNG key enabling STOCHASTIC stage fns (dropout in
        pipelined training — VERDICT r2 item 7). When given, stage_fn is
        called with two extra trailing args ``(mb_key, chunk_idx)``:
        ``mb_key = fold_in(rng, m)`` is unique per microbatch and
        ``chunk_idx = v·S + stage`` identifies the chunk, so the stage fn
        can derive a key per (microbatch, layer) that is INDEPENDENT of
        the schedule — fold the global layer index ``chunk_idx ·
        layers_per_chunk + local_idx`` into ``mb_key`` and the same key
        tree falls out for any (S, V) decomposition (asserted by
        tests/test_pipeline.py dropout-parity). Keys are replayed
        identically in the backward (jax.checkpoint re-runs the forward
        with the same folded values), so dropout masks are consistent
        across fwd/bwd by construction.
    """
    n_stages = mesh.shape[mesh_lib.PIPE]
    M = x_mb.shape[0]
    V = n_virtual
    for leaf in jax.tree.leaves(aux_mb):
        if jnp.ndim(leaf) < 2 or leaf.shape[0] != M:
            raise ValueError(
                f"aux_mb leaves must be [M={M}, mb, ...] microbatched "
                f"(use microbatch()); got shape {jnp.shape(leaf)}"
            )
    if V == 1:
        # canonical internal layout has the virtual-chunk dim: [S, 1, ...]
        stage_params = jax.tree.map(lambda p: p[:, None], stage_params)
        if param_specs is not None:
            # caller's specs describe the pre-insert layout; track the
            # new virtual dim (replicated) at position 1
            def _insert_vdim(s):
                e = tuple(s)
                return P(e[0], None, *e[1:])

            param_specs = jax.tree.map(
                _insert_vdim, param_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
    else:
        for leaf in jax.tree.leaves(stage_params):
            if jnp.ndim(leaf) < 2 or leaf.shape[1] != V:
                raise ValueError(
                    f"n_virtual={V} needs stage_params leaves laid out "
                    f"[S, V, ...]; got shape {jnp.shape(leaf)} (build with "
                    "to_pipeline_params(..., n_virtual=V) or stack chunks "
                    "v*S+d at [d, v])"
                )
    if n_stages == 1:
        if param_specs is not None:
            raise ValueError(
                "param_specs on a pipe=1 mesh: the degenerate path runs "
                "outside shard_map, so a TP stage_fn's collectives would "
                "hit unbound axis names — use the GSPMD path instead"
            )
        # degenerate: no pipe axis — scan this device's chunks in order
        # (S=1, so chunk index c = v, matching the pipelined c = v·S+d)
        sq = jax.tree.map(lambda p: p.reshape(-1, *p.shape[2:]), stage_params)
        n_chunks = jax.tree.leaves(sq)[0].shape[0]

        def through_chunks(x, aux=None, mb_key=None):
            def chunk(x, pc):
                p, c = pc
                args = [p, x] + ([] if aux is None else [aux])
                if mb_key is not None:
                    args += [mb_key, c]
                return stage_fn(*args), None

            y, _ = jax.lax.scan(chunk, x, (sq, jnp.arange(n_chunks)))
            return y

        mb_keys = (
            None if rng is None
            else jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                jnp.arange(M))
        )
        return jax.vmap(
            through_chunks,
            in_axes=(0,
                     0 if aux_mb is not None else None,
                     0 if mb_keys is not None else None),
        )(x_mb, aux_mb, mb_keys)
    if M < n_stages:
        raise ValueError(
            f"need at least as many microbatches ({M}) as stages "
            f"({n_stages}) — bubble would dominate and the schedule "
            "below assumes M >= S"
        )
    if V > 1 and M % n_stages:
        raise ValueError(
            f"interleaved schedule needs microbatches ({M}) divisible by "
            f"stages ({n_stages})"
        )

    batch_shards = mesh_lib.mesh_axis_size(mesh, mesh_lib.BATCH_AXES)
    if x_mb.shape[1] % batch_shards:
        raise ValueError(
            f"microbatch size {x_mb.shape[1]} not divisible by "
            f"data×fsdp={batch_shards}; use fewer microbatches or a larger "
            "global batch"
        )

    if param_specs is None:
        param_specs = stage_param_specs(stage_params)
    mb_spec = lambda leaf: P(
        None, mesh_lib.BATCH_AXES, *([None] * (jnp.ndim(leaf) - 2))
    )
    x_spec = mb_spec(x_mb)
    aux_specs = jax.tree.map(mb_spec, aux_mb)

    body = functools.partial(
        _pipeline_body, stage_fn, n_stages=n_stages, n_microbatches=M,
        n_virtual=V,
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, x_spec, aux_specs, P()),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x_mb, aux_mb, rng)


def _pipeline_body(stage_fn, stage_params, x_mb, aux_mb, rng, *, n_stages,
                   n_microbatches, n_virtual):
    """Per-device schedule; runs inside shard_map. stage_params leaves are
    [1, V, ...] local slices; x_mb is [M, mb_local, ...].

    One unified schedule covers GPipe (V=1) and interleaved (V>1): chunk
    c = v·S + d lives on device d; every tick runs ONE chunk per device
    and hops the ring once. Device d at tick t is at local time
    λ = t - d; with (g, r) = divmod(λ, S·V), (v, j) = divmod(r, S), it
    runs chunk v on microbatch m = g·S + j. Producer-consumer timing is
    exact by construction: chunk c's output for m (tick m + c) arrives at
    chunk c+1 exactly when that chunk processes m (tick m + c + 1) — the
    wraparound d = S-1 → d = 0 lands on v+1 with the same algebra."""
    stage = jax.lax.axis_index(mesh_lib.PIPE)
    params_local = jax.tree.map(lambda p: p[0], stage_params)  # [V, ...]
    M, S, V = n_microbatches, n_stages, n_virtual
    perm = [(i, (i + 1) % S) for i in range(S)]

    fn = jax.checkpoint(stage_fn)

    def tick(carry, t):
        buf, outputs = carry
        lam = t - stage
        active = (lam >= 0) & (lam < M * V)
        g, r = jnp.divmod(jnp.maximum(lam, 0), S * V)
        v, j = jnp.divmod(r, S)
        m = jnp.clip(g * S + j, 0, M - 1)
        params_v = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, v, 0, keepdims=False),
            params_local,
        )
        # device 0 injects a fresh microbatch whenever it starts chunk 0
        x_t = jax.lax.dynamic_index_in_dim(x_mb, m, 0, keepdims=False)
        inp = jnp.where((stage == 0) & (v == 0) & active, x_t, buf)
        args = [params_v, inp]
        if aux_mb is not None:
            args.append(jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, m, 0, keepdims=False
                ),
                aux_mb,
            ))
        if rng is not None:
            # (mb_key, chunk): schedule-independent RNG identity — see
            # the pipeline_apply docstring
            args += [jax.random.fold_in(rng, m), v * S + stage]
        y = fn(*args)
        # the last device finishing the last chunk holds microbatch m's
        # final output; collect it (only stage S-1's buffer survives the
        # masked psum below, so garbage writes on other ranks are inert)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, y.astype(outputs.dtype), m, 0
        )
        outputs = jnp.where(active & (v == V - 1), updated, outputs)
        buf = jax.lax.ppermute(y, mesh_lib.PIPE, perm)
        return (buf, outputs), None

    buf0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    (_, outputs), _ = jax.lax.scan(
        tick, (buf0, out0), jnp.arange(M * V + S - 1)
    )
    # broadcast stage S-1's outputs to every pipe rank (masked psum); the
    # other ranks' buffers hold zeros/garbage masked to zero above
    outputs = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, mesh_lib.PIPE)


def microbatch(x: jax.Array, n_microbatches: int) -> jax.Array:
    """[B, ...] -> [M, B//M, ...] with STRIDED assignment (microbatch m
    takes rows m, M+m, 2M+m, ...): a device owning a contiguous batch
    slice keeps exactly its own rows in every microbatch, so the
    (data, fsdp) sharding lands on dim 1 with no cross-device movement —
    a contiguous split would shard the M dim instead and force an
    all-to-all at pipeline_apply's shard_map boundary."""
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError(f"batch {B} not divisible by microbatches {n_microbatches}")
    return x.reshape(B // n_microbatches, n_microbatches, *x.shape[1:]).swapaxes(0, 1)


def unmicrobatch(y: jax.Array) -> jax.Array:
    """Inverse of :func:`microbatch` (restores original row order)."""
    return y.swapaxes(0, 1).reshape(y.shape[0] * y.shape[1], *y.shape[2:])
