"""Collective verbs over named mesh axes — the framework's data plane.

The reference had three transports (SURVEY.md §2d): gRPC rendezvous for every
PS↔worker variable read / gradient push, NCCL ring allreduce intra-host
($TF/python/ops/nccl_ops.py:208), and the RING/NCCL collective executor for
multi-worker ($TF/python/ops/collective_ops.py:19). On TPU there is no
user-space transport to write: XLA compiles these primitives directly onto
ICI (intra-slice torus) and bridges DCN between slices. What the framework
owns is the *vocabulary* — the same five verbs the reference got from
NCCL+gRPC (allreduce, allgather, reducescatter, broadcast, barrier), plus the
two that long-context/MoE parallelism needs (all_to_all, ring permute),
expressed over named mesh axes.

All functions here must run inside a collective context: ``shard_map`` over a
mesh (the explicit path — pipeline, ring attention, embedding exchange) or
``vmap``/``pmap`` with a named axis. Under plain ``jit`` + NamedSharding,
GSPMD inserts the equivalents automatically and user code never calls these.

``groups``: optional list of index-groups restricting the collective to
subgroups of the axis — the TPU-native descendant of the reference's NCCL
communicator subgroups and of ``group_assignment`` on CrossReplicaSum
($TF/python/tpu/ops/tpu_ops.py:32-40).
"""

from __future__ import annotations

import warnings
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size as _compat_axis_size

AxisNames = str | tuple[str, ...]
Groups = Sequence[Sequence[int]] | None

# The emulated ``groups=`` path below costs the FULL axis in wire traffic
# (all_gather then mask) regardless of group size. Fine for the small
# ad-hoc meshes it exists for; a silent O(axis) collective on a pod axis
# would be a production footgun (VERDICT r2 Weak #5), so past this axis
# size it is an error — structural subgroups belong on
# ``mesh.factor_mesh_axis`` (true subgroup collectives, HLO-asserted).
EMULATED_GROUP_AXIS_LIMIT = 8


def _check_emulated_groups(axis: str, groups, verb: str) -> None:
    n = axis_size(axis)
    if n > EMULATED_GROUP_AXIS_LIMIT:
        raise ValueError(
            f"{verb}(groups=...) over axis {axis!r} of size {n}: the "
            f"emulated grouped path gathers the FULL axis (O(axis) wire "
            f"for O(group) semantics) and is capped at axis size "
            f"{EMULATED_GROUP_AXIS_LIMIT}. For structural (contiguous) "
            f"subgroups, split the axis with mesh.factor_mesh_axis and "
            f"run the collective on one sub-axis — XLA then emits a true "
            f"subgroup collective."
        )
    warnings.warn(
        f"{verb}(groups=...) is emulated: O(axis={n}) wire traffic for "
        f"O(group={len(groups[0])}) semantics; prefer "
        f"mesh.factor_mesh_axis for structural subgroups",
        stacklevel=3,
    )


def _group_mask(axis: str, groups) -> jax.Array:
    """(N,) one-hot-per-group membership mask for this device's group.

    ``shard_map`` does not lower ``axis_index_groups`` (JAX 0.9), so grouped
    collectives are emulated: gather the full axis, then reduce the members
    of this device's group — O(axis) wire traffic for O(group) semantics.
    Use this ONLY for ad-hoc/irregular groups. When a subgroup pattern is
    *structural* (contiguous blocks — per-slice reductions, per-replica
    shards), use ``mesh.factor_mesh_axis`` to split the axis into named
    sub-axes and run the collective on one sub-axis: XLA then emits a true
    subgroup collective with no full-axis gather (asserted in
    tests/test_collectives.py::test_factored_axis_avoids_full_gather).
    That is the idiomatic TPU-native form of the reference's NCCL
    communicator subgroups / CrossReplicaSum ``group_assignment``
    ($TF tpu_ops.py:32-40)."""
    n = axis_size(axis)
    groups_arr = jnp.asarray(groups)  # (G, M), a partition of range(n)
    g = groups_arr.shape[0]
    membership = jnp.zeros((g, n), jnp.float32)  # membership[g, i] = i in group g
    membership = membership.at[
        jnp.arange(g)[:, None], groups_arr
    ].set(1.0)
    mine = membership[:, lax.axis_index(axis)]  # (G,) one-hot: my group
    return mine @ membership  # (N,) members of my group


def all_reduce(x, axis: AxisNames, groups: Groups = None):
    """Sum across the axis. Replaces: the whole SyncReplicasOptimizer
    accumulator+token protocol (494 LoC of Python over C++ queue kernels,
    SURVEY.md §3.1) and NCCL all_sum — one compiled op, inherently
    synchronous, no staleness by construction."""
    if groups is None:
        return lax.psum(x, axis)
    _check_emulated_groups(axis, groups, "all_reduce")
    return _emulated_group_reduce(x, axis, groups)


def _emulated_group_reduce(x, axis: AxisNames, groups):
    mask = _group_mask(axis, groups)
    gathered = lax.all_gather(x, axis, axis=0)  # (N, *x.shape)
    return jnp.tensordot(mask, gathered.astype(jnp.float32), axes=1).astype(x.dtype)


def all_reduce_mean(x, axis: AxisNames, groups: Groups = None):
    """Mean across the axis — gradient aggregation semantics
    (SyncReplicasOptimizer averaged; take_grad / N, SURVEY.md §3.1)."""
    if groups is None:
        return lax.pmean(x, axis)
    size = len(groups[0])
    return all_reduce(x, axis, groups=groups) / size


def all_gather(x, axis: AxisNames, *, tiled_axis: int = 0, groups: Groups = None):
    """Concatenate shards along ``tiled_axis``. NCCL all_gather analog."""
    if groups is None:
        return lax.all_gather(x, axis, axis=tiled_axis, tiled=True)
    _check_emulated_groups(axis, groups, "all_gather")
    # Emulated grouped gather: full gather, then select my group's members.
    gathered = lax.all_gather(x, axis, axis=0)  # (N, *x.shape)
    mask = _group_mask(axis, groups)  # (N,)
    m = len(groups[0])
    members = jnp.sort(jnp.argsort(-mask, stable=True)[:m])  # my group's ids, ascending
    mine = jnp.take(gathered, members, axis=0)  # (M, *x.shape)
    return _tile(mine, tiled_axis)


def _tile(stacked: jax.Array, tiled_axis: int) -> jax.Array:
    """(M, *shape) → concat along tiled_axis."""
    m = stacked.shape[0]
    moved = jnp.moveaxis(stacked, 0, tiled_axis)  # (..., M, dim, ...)
    shape = list(stacked.shape[1:])
    shape[tiled_axis] *= m
    return moved.reshape(shape)


def reduce_scatter(x, axis: AxisNames, *, scatter_axis: int = 0, groups: Groups = None):
    """Sum then keep this device's shard of ``scatter_axis``. The building
    block of ZeRO-style weight-update sharding (arXiv:2004.13336): grads are
    reduce-scattered over fsdp, each device updates its slice, params are
    all-gathered back."""
    if groups is None:
        return lax.psum_scatter(
            x, axis, scatter_dimension=scatter_axis, tiled=True
        )
    _check_emulated_groups(axis, groups, "reduce_scatter")
    reduced = _emulated_group_reduce(x, axis, groups)
    # my chunk = position within my group row along scatter_axis
    groups_arr = jnp.asarray(groups)
    idx = lax.axis_index(axis)
    pos = jnp.argmax(jnp.any(groups_arr == idx, axis=0))
    m = len(groups[0])
    chunk = x.shape[scatter_axis] // m
    return lax.dynamic_slice_in_dim(reduced, pos * chunk, chunk, scatter_axis)


def broadcast(x, axis: AxisNames, *, src: int = 0):
    """Every device gets ``src``'s value. The reference's analog was implicit:
    workers *read* variables from the PS shard over gRPC each step."""
    # Select src's contribution and sum: avoids materializing a full gather.
    idx = lax.axis_index(axis)
    contrib = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis)


def barrier(axis: AxisNames) -> jax.Array:
    """Device-level barrier: a trivial psum every participant must reach.
    Replaces the FIFOQueue token barrier ($TF data_flow_ops.py:774, used at
    sync_replicas_optimizer.py:303-322). Returns the axis size; consume it
    (e.g. via jax.block_until_ready) to enforce ordering."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


def all_to_all(
    x,
    axis: AxisNames,
    *,
    split_axis: int,
    concat_axis: int,
    groups: Groups = None,
):
    """Transpose sharding between two tensor dimensions across the axis —
    the primitive under Ulysses sequence parallelism and MoE token dispatch
    (SURVEY.md §2c; $TF analog tpu_ops.py:43)."""
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True, axis_index_groups=groups,
    )


def ring_permute(x, axis: str, *, shift: int = 1):
    """Rotate shards around the axis ring (device i → i+shift mod N): the
    K/V-block rotation of ring attention (SURVEY.md §5.7). ICI's torus makes
    each hop a single physical link."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm=perm)


def axis_index(axis: AxisNames):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return _compat_axis_size(axis)
