"""Sharding rules: logical axis names → mesh axes → NamedShardings.

This is the placement layer — the TPU-native replacement for
``replica_device_setter`` (SURVEY.md §2b; $TF/python/training/
device_setter.py:129, round-robin chooser :92-125). The reference decided
*which PS process owns each variable*; here we decide *how each array is laid
out over the mesh*, and XLA materializes the movement. Three pieces:

1. **Logical axis rules** — model code annotates each parameter dimension
   with a logical name ("embed", "mlp", "heads", "vocab", …); a rule table
   maps logical names to mesh axes. Swapping parallelism strategy = swapping
   the table, not the model (the flax `logical axis` idiom, generalized).
2. **Partition-rules tables** — the declarative engine
   (:func:`partition_rules` / :func:`match_partition_rules`): an ordered,
   named table of ``(path-regex, PartitionSpec)`` rows resolved over the
   param pytree with first-match precedence. Unlike the legacy soft form
   below, a table is a *contract*: an unmatched param or a dead rule is a
   hard error carrying the full per-param attribution listing, and each
   shipped table carries a static ``coverage`` fixture of param paths that
   the dtflint ``shard-rules-coverage`` rule re-checks on every CI run.
   Onboarding a model or a parallelism strategy = writing a table
   (docs/parallelism.md "Authoring partition-rules tables").
3. **Legacy path rules** — :func:`specs_from_path_rules`, the pre-engine
   soft form (unmatched params silently replicate). Kept for ad-hoc
   trees; shipped models route through tables.
4. **Tree utilities** — build NamedShardings for whole pytrees, shard/assert
   helpers, batch sharding over the (data, fsdp) axes. This module is the
   single sharding-assignment seam: constructing ``NamedSharding`` /
   ``PartitionSpec`` for persistent state anywhere else is a dtflint
   error (``sharding-seam-bypass``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Iterable, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

# ---------------------------------------------------------------------------
# Logical axis rules
# ---------------------------------------------------------------------------

#: logical dimension name → mesh axis (or tuple of mesh axes, or None).
LogicalRules = Mapping[str, str | tuple[str, ...] | None]

#: Default rule table: pure data parallelism; params fully replicated.
DP_RULES: LogicalRules = {
    "batch": (mesh_lib.DATA, mesh_lib.FSDP),
}

#: Megatron-style tensor parallelism + batch over data/fsdp.
TP_RULES: LogicalRules = {
    "batch": (mesh_lib.DATA, mesh_lib.FSDP),
    "vocab": mesh_lib.MODEL,
    "embed": None,           # residual-stream dim stays replicated
    "mlp": mesh_lib.MODEL,   # FFN hidden dim: column-parallel in, row-parallel out
    "heads": mesh_lib.MODEL,  # attention heads
    "kv": None,
    "seq": mesh_lib.SEQ,
    "expert": mesh_lib.EXPERT,
}

#: FSDP/ZeRO: additionally shard params' largest dim over fsdp axis.
FSDP_RULES: LogicalRules = {
    **TP_RULES,
    "embed": mesh_lib.FSDP,
}


def spec_from_logical(
    logical: Sequence[str | None], rules: LogicalRules
) -> P:
    """Map per-dimension logical names to a PartitionSpec under ``rules``."""
    return P(*(rules.get(name) if name is not None else None for name in logical))


# ---------------------------------------------------------------------------
# Path-regex rules (for un-annotated models)
# ---------------------------------------------------------------------------

PathRules = Sequence[tuple[str, P]]


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            # GetAttrKey — registered-dataclass pytrees (serve.KVCache):
            # field name without the "." prefix, so rules match "k"/"v"
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def specs_from_path_rules(tree: Any, rules: PathRules) -> Any:
    """First-match-wins regex rules over parameter paths → PartitionSpec tree.

    The descendant of the reference's round-robin variable chooser
    (device_setter.py:113-121) — except placement is by *meaning* (matched
    name), not by arrival order."""

    def assign(path, leaf):
        name = _path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        return P()  # replicated

    return jax.tree_util.tree_map_with_path(assign, tree)


# ---------------------------------------------------------------------------
# Partition-rules engine: named tables, hard coverage errors, attribution
# ---------------------------------------------------------------------------

#: The replicated spec. Seam consumers reference this instead of
#: constructing ``P()`` (the sharding-seam-bypass lint contract).
REPLICATED = P()

#: Conventional final row of a total table: everything the named rows did
#: not claim is replicated — DECLARED, not silently defaulted.
CATCH_ALL = r".*"


class PartitionCoverageError(ValueError):
    """A rules table failed its totality/liveness contract: some param
    matched no rule, or some rule matched no param. Carries the full
    attribution listing so the failure is debuggable at a glance."""


@dataclasses.dataclass(frozen=True)
class PartitionRow:
    """One table row. ``tag`` marks a variant-conditional row (e.g. the
    fused-QKV layout): :meth:`PartitionRules.select` keeps untagged rows
    plus the rows whose tag was selected, so the table handed to
    :func:`match_partition_rules` is exact for the tree it serves."""

    pattern: str
    spec: P
    tag: str | None = None


@dataclasses.dataclass(frozen=True)
class RuleMatch:
    """Attribution of one param path: which row won it (first match).
    ``rule_index`` is -1 (``pattern``/``spec`` None) for an unmatched
    path — the hard-error case of :func:`match_partition_rules`."""

    path: str
    rule_index: int
    pattern: str | None
    spec: P | None


@dataclasses.dataclass(frozen=True)
class PartitionRules:
    """A named, ordered partition-rules table (see module docstring §2).

    ``coverage`` is the table's static param-path fixture: the full path
    listing of the tree(s) the table serves (union over variants),
    frozen at authoring time. Construction re-runs the totality/liveness
    check against it, and the dtflint ``shard-rules-coverage`` rule
    re-checks the same contract statically on every lint run; a test
    pins each shipped coverage list to the live model's param tree."""

    name: str
    rows: tuple[PartitionRow, ...]
    coverage: tuple[str, ...] = ()

    def select(self, *tags: str) -> "PartitionRules":
        """Variant view: untagged rows plus rows tagged with any of
        ``tags``, original order preserved. The derived table drops the
        union coverage (it describes all variants at once); the strict
        per-tree check happens in :func:`match_partition_rules`."""
        keep = tuple(r for r in self.rows if r.tag is None or r.tag in tags)
        suffix = "+".join(sorted(tags))
        return PartitionRules(
            name=f"{self.name}[{suffix}]" if suffix else self.name,
            rows=keep,
        )

    def as_path_rules(self) -> PathRules:
        """The table's rows in the legacy ``specs_from_path_rules`` form
        (soft fallback semantics) — the back-compat bridge for callers
        that predate the engine."""
        return tuple((r.pattern, r.spec) for r in self.rows)


def partition_rules(
    name: str,
    rules: Sequence[tuple],
    *,
    coverage: Sequence[str] = (),
) -> PartitionRules:
    """Build (and validate) a :class:`PartitionRules` table.

    ``rules`` rows are ``(pattern, spec)`` or ``(pattern, spec, tag)``
    tuples, matched against ``_path_str`` param paths with
    ``re.search``, first match wins. Every pattern must compile; when
    ``coverage`` is given, the totality/liveness contract is enforced
    right here — a table that cannot cover its own fixture fails at
    import time, not at first training run."""
    built: list[PartitionRow] = []
    for i, row in enumerate(rules):
        if len(row) not in (2, 3):
            raise ValueError(
                f"partition_rules({name!r}): row {i} must be "
                f"(pattern, spec[, tag]), got {row!r}"
            )
        pattern, spec = row[0], row[1]
        tag = row[2] if len(row) == 3 else None
        try:
            re.compile(pattern)
        except re.error as e:
            raise ValueError(
                f"partition_rules({name!r}): row {i} pattern "
                f"{pattern!r} does not compile: {e}"
            ) from e
        if not isinstance(spec, P):
            raise ValueError(
                f"partition_rules({name!r}): row {i} spec must be a "
                f"PartitionSpec, got {type(spec).__name__}"
            )
        built.append(PartitionRow(pattern, spec, tag))
    table = PartitionRules(name, tuple(built), tuple(coverage))
    if table.coverage:
        _check_coverage(table, table.coverage)
    return table


def _attribute_paths(
    rows: Sequence[PartitionRow], paths: Iterable[str]
) -> list[RuleMatch]:
    compiled = [re.compile(r.pattern) for r in rows]
    out: list[RuleMatch] = []
    for path in paths:
        for i, rx in enumerate(compiled):
            if rx.search(path):
                out.append(RuleMatch(path, i, rows[i].pattern, rows[i].spec))
                break
        else:
            out.append(RuleMatch(path, -1, None, None))
    return out


def format_attribution(
    table: PartitionRules, matches: Sequence[RuleMatch]
) -> str:
    """The full per-param listing (one line per path: winning rule
    index, pattern, spec — or UNMATCHED), plus a dead-rule trailer.
    Shared by the hard-error message and ``show_sharding --rules``."""
    won = {m.rule_index for m in matches if m.rule_index >= 0}
    lines = [f"table {table.name!r}: {len(table.rows)} rule(s), "
             f"{len(matches)} param(s)"]
    for m in matches:
        if m.rule_index < 0:
            lines.append(f"  {m.path}  <-  UNMATCHED")
        else:
            lines.append(
                f"  {m.path}  <-  rule[{m.rule_index}] "
                f"{m.pattern!r} -> {m.spec}"
            )
    for i, row in enumerate(table.rows):
        if i not in won:
            lines.append(
                f"  rule[{i}] {row.pattern!r} -> {row.spec}  DEAD "
                f"(matched no param)"
            )
    return "\n".join(lines)


def _coverage_violations(
    table: PartitionRules, paths: Sequence[str]
) -> tuple[list[RuleMatch], list[str], list[int]]:
    """(matches, unmatched paths, dead rule indices) — the ONE place
    the totality/liveness contract is computed, shared by the
    construction-time check and match_partition_rules so the two can
    never drift."""
    matches = _attribute_paths(table.rows, paths)
    unmatched = [m.path for m in matches if m.rule_index < 0]
    won = {m.rule_index for m in matches if m.rule_index >= 0}
    dead = [i for i in range(len(table.rows)) if i not in won]
    return matches, unmatched, dead


def _check_coverage(table: PartitionRules, paths: Sequence[str]) -> None:
    matches, unmatched, dead = _coverage_violations(table, paths)
    if unmatched or dead:
        raise PartitionCoverageError(
            f"partition rules table {table.name!r} violates its "
            f"coverage contract: {len(unmatched)} unmatched param(s), "
            f"{len(dead)} dead rule(s).\n"
            + format_attribution(table, matches)
        )


def attribute_partition_rules(
    rules: "PartitionRules | PathRules", tree: Any
) -> list[RuleMatch]:
    """First-match attribution of every leaf path in ``tree`` — the
    debuggable view behind ``tools/show_sharding.py --rules``. Accepts
    a table or legacy path rules; never raises on coverage gaps."""
    rows = (rules.rows if isinstance(rules, PartitionRules)
            else tuple(PartitionRow(p, s) for p, s in rules))
    paths = [
        _path_str(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(tree)
    ]
    return _attribute_paths(rows, paths)


def match_partition_rules(table: PartitionRules, tree: Any) -> Any:
    """Resolve ``table`` over ``tree`` with the hard contract: every
    leaf must match a rule and every rule must match a leaf, else
    :class:`PartitionCoverageError` with the full attribution listing.
    This — not :func:`specs_from_path_rules` — is how shipped models
    get their specs (SNIPPETS.md [2] ``match_partition_rules``, with
    the dead-rule half added)."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    matches, unmatched, dead = _coverage_violations(
        table, [_path_str(p) for p, _ in leaves])
    if unmatched or dead:
        raise PartitionCoverageError(
            f"partition rules table {table.name!r} does not cover this "
            f"param tree: {len(unmatched)} unmatched param(s), "
            f"{len(dead)} dead rule(s). Add/repair rows (a final "
            f"(sharding.CATCH_ALL, sharding.REPLICATED) row declares "
            f"the replicated remainder) or fix the variant selection.\n"
            + format_attribution(table, matches)
        )
    return jax.tree_util.tree_unflatten(
        treedef, [m.spec for m in matches]
    )


def specs_from_rules(tree: Any, rules: "PartitionRules | PathRules") -> Any:
    """Dispatch seam used by ``train/step.init_train_state`` and the
    tools: a :class:`PartitionRules` table resolves strictly
    (:func:`match_partition_rules`); a legacy rule sequence keeps the
    soft replicate-on-miss semantics."""
    if isinstance(rules, PartitionRules):
        return match_partition_rules(rules, tree)
    return specs_from_path_rules(tree, rules)


def replicated_specs(tree: Any) -> Any:
    """A spec tree replicating every leaf of ``tree``."""
    return jax.tree.map(lambda _: REPLICATED, tree)


def merge_specs(explicit: Any, auto: Any) -> Any:
    """Per-leaf merge of two spec trees: the explicit spec wins unless
    it is replicated, where ``auto`` (e.g. :func:`auto_fsdp_specs`)
    fills in. The one merge used by ``init_train_state`` and
    ``show_sharding`` — factored here so the precedence cannot drift."""
    return jax.tree.map(
        lambda e, a: a if e == REPLICATED else e,
        explicit, auto, is_leaf=lambda x: isinstance(x, P),
    )


def opt_state_specs(opt_state: Any, params: Any, param_specs: Any) -> Any:
    """PartitionSpec tree for an optax state: sub-trees shaped like the
    param tree inherit the param specs (momentum/second-moment slots —
    the reference's PS-resident 'slot variables'), scalars replicated.

    This is the weight-update-sharding hook (arXiv:2004.13336): pass
    fsdp-sharded param_specs and the optimizer state shards with them."""
    import optax  # deferred: parallel/ stays importable without the train deps

    param_treedef = jax.tree.structure(params)
    masked_leaf = lambda x: isinstance(x, optax.MaskedNode)

    def rec(node):
        try:
            if jax.tree.structure(node) == param_treedef:
                return param_specs
        except (ValueError, TypeError):
            pass
        # optax.masked (the building block of multi_transform) replaces
        # out-of-group params with empty MaskedNode containers; such a
        # sub-tree still inherits the in-group param specs — mirror the
        # MaskedNodes into the spec tree so treedefs stay identical
        try:
            if jax.tree.structure(node, is_leaf=masked_leaf) == param_treedef:
                return jax.tree.map(
                    lambda n, s: n if masked_leaf(n) else s,
                    node, param_specs, is_leaf=masked_leaf,
                )
        except (ValueError, TypeError):
            pass
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(rec(c) for c in node))
        if isinstance(node, (tuple, list)):
            return type(node)(rec(c) for c in node)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return REPLICATED  # scalar leaf (counts, schedules)

    return rec(opt_state)


def stacked_stage_specs(stage_params: Any, *, col: str | None = None,
                        row: str | None = None) -> Any:
    """Specs for a pipeline-stacked param tree: every leaf leads with
    the ``pipe`` axis (leading [n_stages(, n_virtual), layers] stacking
    dims). ``col``/``row`` optionally add megatron tensor parallelism by
    path regex — column-parallel leaves shard their LAST dim over
    ``model``, row-parallel their second-to-last. The seam home of what
    ``parallel/pipeline.py`` and ``models/transformer.py`` previously
    each hand-built."""
    col_rx = re.compile(col) if col else None
    row_rx = re.compile(row) if row else None

    def assign(path, leaf):
        name = _path_str(path)
        spec = [mesh_lib.PIPE] + [None] * (jnp.ndim(leaf) - 1)
        if col_rx is not None and col_rx.search(name):
            spec[-1] = mesh_lib.MODEL
        elif row_rx is not None and row_rx.search(name):
            spec[-2] = mesh_lib.MODEL
        return P(*spec)

    return jax.tree_util.tree_map_with_path(assign, stage_params)


# ---------------------------------------------------------------------------
# NamedSharding / tree utilities
# ---------------------------------------------------------------------------


def named_sharding(mesh: Mesh, spec: P | None = None) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None else P())


def tree_shardings(mesh: Mesh, spec_tree: Any) -> Any:
    """PartitionSpec tree → NamedSharding tree."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_spec(ndim: int = 1) -> P:
    """Shard dim 0 over (data, fsdp); replicate the rest. How every input
    batch enters the mesh — replacing per-worker `tf.data.Dataset.shard`
    by task_index (SURVEY.md §2a 'Input pipeline' row)."""
    return P(mesh_lib.BATCH_AXES, *([None] * (ndim - 1)))


def batch_shardings(mesh: Mesh, batch: Any) -> Any:
    return jax.tree.map(
        lambda x: NamedSharding(mesh, batch_spec(jnp.ndim(x))), batch
    )


def put_host_batch(mesh: Mesh, batch: Any) -> Any:
    """Host batch (this process's shard of the global batch) → sharded
    global device array over (data, fsdp). The one feeding entry for both
    the Trainer and the standalone eval path — replaces per-worker
    `Dataset.shard`-by-task_index feeding (SURVEY.md §2a)."""
    shardings = batch_shardings(mesh, batch)
    return jax.tree.map(
        lambda x, s: jax.make_array_from_process_local_data(s, x),
        batch, shardings,
    )


def shard_tree(tree: Any, mesh: Mesh, spec_tree: Any) -> Any:
    """Device_put a pytree with the given PartitionSpec tree."""
    shardings = tree_shardings(mesh, spec_tree)
    return jax.device_put(tree, shardings)


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.device_put(
        tree, jax.tree.map(lambda _: NamedSharding(mesh, P()), tree)
    )


def shard_leading_dim(x: Any, mesh: Mesh, axis: str) -> Any:
    """Place ``x`` with dim 0 sharded over the named ``axis``, every
    other dim replicated — the seam form of the one-off
    ``device_put(x, NamedSharding(mesh, P(axis, None, ...)))`` pattern
    (ops/embedding.to_mod_sharded)."""
    spec = P(axis, *([None] * (jnp.ndim(x) - 1)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def auto_fsdp_specs(params: Any, mesh: Mesh, *, min_size: int = 2**14) -> Any:
    """ZeRO-style automatic weight sharding (arXiv:2004.13336, PAPERS.md):
    shard each parameter's largest divisible dimension over the fsdp axis;
    leave small params replicated. Used for optimizer state and (under pure
    FSDP) the params themselves."""
    n = mesh.shape[mesh_lib.FSDP]

    def assign(x):
        if n == 1 or x.size < min_size:
            return P()
        dims = list(x.shape)
        # largest dim divisible by the fsdp axis size
        best = max(
            (d for d in range(len(dims)) if dims[d] % n == 0),
            key=lambda d: dims[d],
            default=None,
        )
        if best is None:
            return P()
        spec = [None] * len(dims)
        spec[best] = mesh_lib.FSDP
        return P(*spec)

    return jax.tree.map(assign, params)
