"""Sharding rules: logical axis names → mesh axes → NamedShardings.

This is the placement layer — the TPU-native replacement for
``replica_device_setter`` (SURVEY.md §2b; $TF/python/training/
device_setter.py:129, round-robin chooser :92-125). The reference decided
*which PS process owns each variable*; here we decide *how each array is laid
out over the mesh*, and XLA materializes the movement. Three pieces:

1. **Logical axis rules** — model code annotates each parameter dimension
   with a logical name ("embed", "mlp", "heads", "vocab", …); a rule table
   maps logical names to mesh axes. Swapping parallelism strategy = swapping
   the table, not the model (the flax `logical axis` idiom, generalized).
2. **Path rules** — regex over the parameter path → PartitionSpec, for
   models that don't carry logical annotations.
3. **Tree utilities** — build NamedShardings for whole pytrees, shard/assert
   helpers, batch sharding over the (data, fsdp) axes.
"""

from __future__ import annotations

import re
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

# ---------------------------------------------------------------------------
# Logical axis rules
# ---------------------------------------------------------------------------

#: logical dimension name → mesh axis (or tuple of mesh axes, or None).
LogicalRules = Mapping[str, str | tuple[str, ...] | None]

#: Default rule table: pure data parallelism; params fully replicated.
DP_RULES: LogicalRules = {
    "batch": (mesh_lib.DATA, mesh_lib.FSDP),
}

#: Megatron-style tensor parallelism + batch over data/fsdp.
TP_RULES: LogicalRules = {
    "batch": (mesh_lib.DATA, mesh_lib.FSDP),
    "vocab": mesh_lib.MODEL,
    "embed": None,           # residual-stream dim stays replicated
    "mlp": mesh_lib.MODEL,   # FFN hidden dim: column-parallel in, row-parallel out
    "heads": mesh_lib.MODEL,  # attention heads
    "kv": None,
    "seq": mesh_lib.SEQ,
    "expert": mesh_lib.EXPERT,
}

#: FSDP/ZeRO: additionally shard params' largest dim over fsdp axis.
FSDP_RULES: LogicalRules = {
    **TP_RULES,
    "embed": mesh_lib.FSDP,
}


def spec_from_logical(
    logical: Sequence[str | None], rules: LogicalRules
) -> P:
    """Map per-dimension logical names to a PartitionSpec under ``rules``."""
    return P(*(rules.get(name) if name is not None else None for name in logical))


# ---------------------------------------------------------------------------
# Path-regex rules (for un-annotated models)
# ---------------------------------------------------------------------------

PathRules = Sequence[tuple[str, P]]


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def specs_from_path_rules(tree: Any, rules: PathRules) -> Any:
    """First-match-wins regex rules over parameter paths → PartitionSpec tree.

    The descendant of the reference's round-robin variable chooser
    (device_setter.py:113-121) — except placement is by *meaning* (matched
    name), not by arrival order."""

    def assign(path, leaf):
        name = _path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        return P()  # replicated

    return jax.tree_util.tree_map_with_path(assign, tree)


# ---------------------------------------------------------------------------
# NamedSharding / tree utilities
# ---------------------------------------------------------------------------


def named_sharding(mesh: Mesh, spec: P | None = None) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None else P())


def tree_shardings(mesh: Mesh, spec_tree: Any) -> Any:
    """PartitionSpec tree → NamedSharding tree."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_spec(ndim: int = 1) -> P:
    """Shard dim 0 over (data, fsdp); replicate the rest. How every input
    batch enters the mesh — replacing per-worker `tf.data.Dataset.shard`
    by task_index (SURVEY.md §2a 'Input pipeline' row)."""
    return P(mesh_lib.BATCH_AXES, *([None] * (ndim - 1)))


def batch_shardings(mesh: Mesh, batch: Any) -> Any:
    return jax.tree.map(
        lambda x: NamedSharding(mesh, batch_spec(jnp.ndim(x))), batch
    )


def put_host_batch(mesh: Mesh, batch: Any) -> Any:
    """Host batch (this process's shard of the global batch) → sharded
    global device array over (data, fsdp). The one feeding entry for both
    the Trainer and the standalone eval path — replaces per-worker
    `Dataset.shard`-by-task_index feeding (SURVEY.md §2a)."""
    shardings = batch_shardings(mesh, batch)
    return jax.tree.map(
        lambda x, s: jax.make_array_from_process_local_data(s, x),
        batch, shardings,
    )


def shard_tree(tree: Any, mesh: Mesh, spec_tree: Any) -> Any:
    """Device_put a pytree with the given PartitionSpec tree."""
    shardings = tree_shardings(mesh, spec_tree)
    return jax.device_put(tree, shardings)


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.device_put(
        tree, jax.tree.map(lambda _: NamedSharding(mesh, P()), tree)
    )


def auto_fsdp_specs(params: Any, mesh: Mesh, *, min_size: int = 2**14) -> Any:
    """ZeRO-style automatic weight sharding (arXiv:2004.13336, PAPERS.md):
    shard each parameter's largest divisible dimension over the fsdp axis;
    leave small params replicated. Used for optimizer state and (under pure
    FSDP) the params themselves."""
    n = mesh.shape[mesh_lib.FSDP]

    def assign(x):
        if n == 1 or x.size < min_size:
            return P()
        dims = list(x.shape)
        # largest dim divisible by the fsdp axis size
        best = max(
            (d for d in range(len(dims)) if dims[d] % n == 0),
            key=lambda d: dims[d],
            default=None,
        )
        if best is None:
            return P()
        spec = [None] * len(dims)
        spec[best] = mesh_lib.FSDP
        return P(*spec)

    return jax.tree.map(assign, params)
