"""Training engine: jit step, optimizers, host loop, callbacks, checkpoint.

Replaces the reference's L3 harness layer (SURVEY.md §1):
SyncReplicasOptimizer → step.py; MonitoredTrainingSession + hooks →
loop.py + callbacks.py; Saver/Scaffold → checkpoint.py; optimizer zoo →
optimizers.py.
"""

from .step import (  # noqa: F401
    StepOptions,
    TrainState,
    init_train_state,
    jit_train_step,
    make_eval_step,
    make_train_step,
    opt_state_specs,
    state_specs,
)
from .optimizers import (  # noqa: F401
    OptimizerConfig,
    ftrl,
    make_multi_optimizer,
    make_optimizer,
    make_schedule,
)
from .loop import Trainer  # noqa: F401
from . import callbacks  # noqa: F401
from .evaluation import (  # noqa: F401
    ShardedEvaluator,
    derive_metrics,
    make_sharded_eval_step,
)
from .checkpoint import (  # noqa: F401
    CheckpointConfig,
    Checkpointer,
    PreemptionSaved,
    PreemptionWatcher,
    init_or_restore,
)
