"""Distributed evaluation — eval sharded across the mesh, bit-exactly.

The MLPerf-0.6 TPU-pod paper (arXiv:1909.09756) lists distributed
evaluation among the structural changes that made pod-scale training
honest: serial evaluation either stalls the train loop or runs on a
separate underpowered evaluator, and both get worse with scale. Here
the eval set is sharded over the mesh's batch axes and every device
evaluates its shard with the full weights — the same
summed-sufficient-statistic contract the metrics registry and
``utils/metrics.py``'s AUC histograms already use.

**Bit-exactness contract.** A sharded eval must report the same loss a
serial evaluator would, to the BIT — otherwise quality gates drift with
the mesh shape and nobody can compare runs across topologies. Plain
GSPMD partitioning of a flat-batch ``eval_fn`` does NOT have this
property (measured on the 8-device CPU rig: partitioning retiles the
local matmuls, changing FMA order in the last ulp, and the cross-shard
``psum`` reorders the reduction again). The construction here pins the
reduction tree to the PROGRAM rather than the partitioning:

1. the batch is split over the mesh batch axes with ``shard_map``, so
   each device runs the eval body compiled at the LOCAL shard shape —
   the exact program a serial evaluator runs chunk by chunk;
2. per-shard partial sums come back stacked ``[shards, ...]`` (no
   device-side cross-shard reduction);
3. the cross-shard and cross-batch reduction happens on the HOST in
   float64, shard-major, fixed order.

A serial evaluator that walks the same chunks in the same order
computes the identical float sequence, so equality is structural —
``tests/test_distributed_eval.py`` proves it on the 8-device mesh.

The eval body receives params/model_state REPLICATED (``in_specs
P()``): distributed eval parallelizes the *batch*; when the stored
state is sharded (fsdp/tp), jit inserts the gather. The host fetch of
the stacked partials is the only synchronization — the train loop's
step cadence is untouched (no host syncs inside any step function;
dtflint's host-sync-in-step rule covers ``eval_step`` by name).
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..obs import flightrec as flightrec_lib
from ..obs.registry import Registry, default_registry
from ..parallel import mesh as mesh_lib
from ..parallel import sharding as sh
from ..utils import metrics as metrics_lib
from ..utils.compat import shard_map
from . import step as step_lib

__all__ = [
    "EVAL_STEPS",
    "make_sharded_eval_step",
    "ShardedEvaluator",
    "derive_metrics",
]

logger = logging.getLogger(__name__)

#: metric name (docs/observability.md "Scaling sweeps")
EVAL_STEPS = "eval_steps_total"


def batch_shards(mesh) -> int:
    """How many ways the batch dimension splits on this mesh."""
    return mesh_lib.mesh_axis_size(mesh, mesh_lib.BATCH_AXES)


def make_sharded_eval_step(eval_fn, mesh) -> Callable:
    """Jit an eval step that returns PER-SHARD partial sums, stacked
    ``[shards, ...]`` per metric, one row per batch shard.

    ``eval_fn(params, model_state, batch) -> dict`` of summed sufficient
    statistics (the workload contract). The body runs under shard_map
    over the batch axes at local shard shape — see the module docstring
    for why that, and not plain GSPMD, is what makes the result
    partition-invariant. Callers reduce the rows host-side
    (``ShardedEvaluator`` does, in float64, shard-major)."""

    def body(params, model_state, chunk):
        out = eval_fn(params, model_state, chunk)
        # one leading row per shard; out_specs stacks rows over the
        # batch axes instead of psum-ing them on device
        return {k: jnp.reshape(v, (1,) + jnp.shape(v))
                for k, v in out.items()}

    def eval_step(state, batch):
        in_specs = (P(), P(), jax.tree.map(
            lambda x: sh.batch_spec(jnp.ndim(x)), batch))
        fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                       out_specs=P(mesh_lib.BATCH_AXES), check_rep=False)
        return fn(state.params, state.model_state, batch)

    return jax.jit(eval_step)


class ShardedEvaluator:
    """The distributed-eval loop: sharded per-batch partials, host-side
    float64 accumulation, obs instrumentation.

    One instance per (eval_fn, mesh) — the jitted step is cached on it,
    so periodic mid-train evals never retrace. Each executed eval batch
    ticks ``eval_steps_total``; each pass emits ``eval_start`` /
    ``eval_end`` flight-recorder events. Two documented fallbacks to
    the flat (unsharded-reduction) step, each logged once, both correct
    but outside the bit-exactness contract: batches whose leading
    dimension does not divide by the mesh's batch-shard count, and eval
    bodies that themselves use mesh axes (sharding constraints /
    collectives — e.g. wide_deep's sharded embedding lookups), which
    cannot nest under shard_map's manual axes and are detected at the
    first trace."""

    def __init__(self, eval_fn, mesh, registry: Registry | None = None,
                 flightrec=None):
        self.mesh = mesh
        self.shards = batch_shards(mesh)
        self.registry = (registry if registry is not None
                         else default_registry())
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self._sharded = make_sharded_eval_step(eval_fn, mesh)
        self._flat = jax.jit(step_lib.make_eval_step(eval_fn))
        self._warned_indivisible = False
        #: None until the sharded step first traces; an eval body that
        #: itself uses mesh axes (sharding constraints / collectives —
        #: the sharded-embedding wide_deep path) cannot nest under
        #: shard_map's manual axes, and is detected at that first trace
        self._sharded_ok: bool | None = None
        self._m_steps = self.registry.counter(
            EVAL_STEPS, "evaluation batches executed")

    def _probe_sharded(self, state, global_batch) -> None:
        """Decide sharded-vs-flat by TRACING the sharded step (no
        execution): an eval body that uses mesh axes itself fails at
        trace time with shard_map's manual-axes error, which is the
        only thing that may demote this evaluator. Runtime failures of
        an already-traced step (a stall abort, an OOM) propagate to the
        caller like any other eval error — they say nothing about the
        construction."""
        try:
            self._sharded.lower(state, global_batch)
        except Exception as e:
            from .callbacks import StalledError

            if isinstance(e, StalledError):
                # a watchdog abort that happened to land mid-trace is a
                # classified control exception, never a demotion signal
                raise
            self._sharded_ok = False
            logger.warning(
                "sharded eval step failed to trace (the eval body "
                "itself uses mesh axes?); falling back to the flat "
                "GSPMD eval for this evaluator — correct, but outside "
                "the bit-exact reduction contract", exc_info=True)
        else:
            self._sharded_ok = True

    def run(self, state, batches: Iterable[Any],
            num_batches: int | None = None,
            step: int | None = None) -> dict[str, Any]:
        """Evaluate ``num_batches`` from ``batches``; returns float64
        totals of every summed statistic (scalars AND fixed-size arrays
        like the AUC histograms). Derive ratios with
        ``derive_metrics``."""
        self.flightrec.emit("eval_start", step=step, shards=self.shards)
        totals: dict[str, Any] = {}
        n = 0
        for batch in itertools.islice(batches, num_batches):
            lead = next(int(np.shape(x)[0]) for x in jax.tree.leaves(batch))
            if lead % self.shards == 0 and self._sharded_ok is not False:
                global_batch = sh.put_host_batch(self.mesh, batch)
                if self._sharded_ok is None:
                    self._probe_sharded(state, global_batch)
                if self._sharded_ok:
                    out = self._sharded(state, global_batch)
                    # shard-major fixed-order host reduction: the second
                    # half of the bit-exactness contract (module docstring)
                    vals = {k: np.asarray(v, np.float64).sum(axis=0)
                            for k, v in out.items()}
                else:
                    out = self._flat(state, global_batch)
                    vals = {k: np.asarray(v, np.float64)
                            for k, v in out.items()}
            else:
                if not self._warned_indivisible:
                    self._warned_indivisible = True
                    logger.warning(
                        "eval batch of %d does not divide by %d batch "
                        "shards; falling back to the flat eval step "
                        "(correct, but outside the bit-exact sharded "
                        "reduction contract)", lead, self.shards)
                # an indivisible batch can't shard over the batch axes:
                # evaluate it replicated through the flat step
                out = self._flat(state, sh.replicate(batch, self.mesh))
                vals = {k: np.asarray(v, np.float64)
                        for k, v in out.items()}
            for k, v in vals.items():
                totals[k] = totals.get(k, 0.0) + v
            n += 1
            self._m_steps.inc()
        self.flightrec.emit("eval_end", step=step, batches=n)
        return totals


def derive_metrics(totals: dict[str, Any], auc_prefix: str = "") -> dict:
    """Scalar metric dict from summed totals: keeps scalars, derives
    accuracy/top5/loss ratios, and folds AUC histograms into
    ``<auc_prefix>auc`` (omitted when undefined — a one-class stream
    makes AUC NaN, which is not valid JSON downstream). Shared by the
    runner's eval paths and the sweep harness so every consumer applies
    one arithmetic."""
    result = {k: float(v) for k, v in totals.items() if np.ndim(v) == 0}
    for summed, ratio in (("correct", "accuracy"),
                          ("top5_correct", "top5_accuracy"),
                          ("loss_sum", "loss")):
        if summed in result and result.get("count"):
            result[ratio] = result[summed] / result["count"]
    if "auc_pos_hist" in totals and "auc_neg_hist" in totals:
        auc = metrics_lib.auc_from_histograms(
            totals["auc_pos_hist"], totals["auc_neg_hist"]
        )
        if np.isfinite(auc):
            result[auc_prefix + "auc"] = auc
    return result
