"""Host run loop — replaces MonitoredTrainingSession (SURVEY.md §2b, §3.1).

The reference's loop was `while not mon_sess.should_stop():
mon_sess.run(train_op)` behind four session wrappers (_RecoverableSession /
_CoordinatedSession / _HookedSession, $TF monitored_session.py:1238-1447).
Here the loop is plain Python driving one jit-ed SPMD step: the
chief-vs-worker split, session recovery, and graph-side hook fetches have no
TPU equivalent — recovery is checkpoint-restart (train/checkpoint.py) and
hooks are host callbacks over the step's returned metrics.

The loop stays *async*: the host dispatches step N+1 while N executes on
device; only cadence'd callbacks (logging every N) synchronize.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterable, Sequence

import jax
from jax.sharding import Mesh

from ..obs import flightrec as flightrec_lib
from ..parallel import sharding as sh
from . import step as step_lib
from .callbacks import Callback, CheckpointCallback
from .checkpoint import PreemptionSaved

logger = logging.getLogger(__name__)


class Trainer:
    """Owns: the compiled step, the state, the data feed, the callbacks.

    Replaces the MonitoredTrainingSession factory (monitored_session.py:428)
    plus the Supervisor legacy path (supervisor.py:40): one class, no roles.
    """

    def __init__(
        self,
        train_step: Callable,
        state: step_lib.TrainState,
        mesh: Mesh,
        spec_tree: step_lib.TrainState,
        callbacks: Sequence[Callback] = (),
        donate: bool = True,
        emergency_checkpoint=None,
        flightrec=None,
        postmortem_dir: str | None = None,
        anomaly_policy=None,
    ):
        self.mesh = mesh
        self.spec_tree = spec_tree
        self.state = state
        self.callbacks = list(callbacks)
        self._stop_reason: str | None = None
        self.failed = False  # set when fit() aborts on an exception
        #: set when fit() exited via a coordinated preemption save — the
        #: signal resilience.Supervisor uses to distinguish "restart and
        #: resume" from a deliberate stop without string-matching reasons
        self.preempted = False
        #: Checkpointer used for the best-effort save on an unhandled
        #: step exception (docs/resilience.md). Defaults to the manager
        #: of the first CheckpointCallback in ``callbacks``, so wiring a
        #: CheckpointCallback is enough to get crash-safe exits.
        self.emergency_checkpoint = emergency_checkpoint
        if self.emergency_checkpoint is None:
            for cb in self.callbacks:
                if isinstance(cb, CheckpointCallback):
                    self.emergency_checkpoint = cb.manager
                    break
        #: flight recorder for the loop's causal events (obs/flightrec.py);
        #: defaults to the process ring so every layer shares one timeline
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        #: where an abnormal-exit postmortem dump lands; defaults to the
        #: emergency checkpointer's directory (the run dir)
        self.postmortem_dir = postmortem_dir
        if self.postmortem_dir is None:
            self.postmortem_dir = getattr(
                getattr(self.emergency_checkpoint, "cfg", None),
                "directory", None)
        #: resilience/anomaly.AnomalyPolicy (duck-typed: ``observe(step,
        #: metrics) -> bool``) — pairs with StepOptions(skip_nonfinite):
        #: a step the policy reports as skipped was a device-side no-op,
        #: so the loop does not count it and no callback sees it. Kept a
        #: plain attribute (no import) so train/ never depends on
        #: resilience/.
        self.anomaly_policy = anomaly_policy
        if donate:
            self.step_fn = step_lib.jit_train_step(train_step, mesh, spec_tree)
        else:
            self.step_fn = jax.jit(train_step)

    # -- control ----------------------------------------------------------
    def request_stop(self, reason: str = "") -> None:
        """Cooperative stop — the Coordinator.request_stop analog
        ($TF coordinator.py:28)."""
        if self._stop_reason is None:
            self._stop_reason = reason or "requested"

    @property
    def should_stop(self) -> bool:
        return self._stop_reason is not None

    @property
    def stop_reason(self) -> str | None:
        """Why the loop stopped (None while running / never stopped)."""
        return self._stop_reason

    # -- data -------------------------------------------------------------
    def put_batch(self, batch: Any) -> Any:
        """Host batch → sharded global device array (sharding.put_host_batch)."""
        return sh.put_host_batch(self.mesh, batch)

    # -- loop -------------------------------------------------------------
    def fit(
        self,
        data: Iterable[Any],
        num_steps: int | None = None,
    ) -> step_lib.TrainState:
        # Host-side step mirror: reading state.step would sync the device
        # every iteration and serialize dispatch with execution.
        step_now = int(self.state.step)
        rec = self.flightrec
        rec.emit("train_start", step=step_now)
        try:
            # inside the try: a raising on_train_start (or iter()) must
            # still reach the finally's on_train_end, or started
            # resources leak — e.g. Watchdog's poll thread would flag a
            # phantom stall in the registry forever
            for cb in self.callbacks:
                cb.on_train_start(self)
            data_iter = iter(data)
            while not self.should_stop:
                if num_steps is not None and step_now >= num_steps:
                    self.request_stop(f"num_steps={num_steps}")
                    break
                try:
                    batch = next(data_iter)
                except StopIteration:
                    self.request_stop("data exhausted")
                    break
                rec.emit("step_start", step=step_now + 1)
                batch = self.put_batch(batch)
                self.state, metrics = self.step_fn(self.state, batch)
                if self.anomaly_policy is not None:
                    if self.anomaly_policy.observe(step_now + 1, metrics):
                        # the compiled step kept the old state
                        # bit-identically (in-graph nonfinite guard): the
                        # batch vanishes from the trajectory — not a
                        # completed step, so neither the step mirror nor
                        # any callback may count it. The policy already
                        # blamed + quarantined the index and emitted
                        # anomaly_skip (which is what resolves this
                        # step's dangling step_start in a postmortem); a
                        # spent skip budget raises out of observe() into
                        # the classified-exit path below (poisoned),
                        # with the state still clean.
                        continue
                elif step_lib.step_nonfinite(metrics):
                    # guard on, no policy wired: fail fast HERE, before
                    # the step is counted. Counting it would desync the
                    # host mirror from the device step counter (the
                    # guard kept state.step unchanged) and mislabel
                    # every later checkpoint by one. The state is still
                    # the last healthy one, so the emergency save below
                    # lands under its true step number; the exception
                    # classifies poisoned — the pre-guard NaNGuard
                    # semantics, made exact and immediate.
                    raise FloatingPointError(
                        f"non-finite loss/gradients at step {step_now + 1}"
                        " (in-graph guard skipped the update; wire an "
                        "AnomalyPolicy to skip-and-continue instead)")
                step_now += 1
                for cb in self.callbacks:
                    cb.on_step_end(self, step_now, metrics)
                # after the callbacks: step_end marks the step COMPLETE
                # (checkpoint cadence included), so a missing step_end in
                # a postmortem points at the exact step that died
                rec.emit("step_end", step=step_now)
        except PreemptionSaved as e:
            # Clean preemption exit (SURVEY.md §5.3): state is safely on
            # disk; stop so the scheduler — or an in-process
            # resilience.Supervisor — can restart-and-resume.
            self.preempted = True
            self.request_stop(str(e))
        except BaseException as e:
            self.failed = True
            rec.emit("train_exception", step=step_now,
                     etype=type(e).__name__, error=repr(e)[:200])
            # Crash-safe exit: one best-effort emergency checkpoint of
            # the last completed step before re-raising. save() itself
            # applies validate_before_save, so a poisoned state (the
            # NaNGuard abort path) is refused and never becomes the
            # latest checkpoint; any error here must not mask the
            # original exception.
            self._emergency_save(step_now)
            # abnormal exit: dump the flight recorder as a postmortem
            # (best-effort, never masks the original exception)
            self._dump_postmortem(f"train_exception:{type(e).__name__}")
            raise
        finally:
            for cb in self.callbacks:
                cb.on_train_end(self)
        rec.emit("train_stop", step=step_now, reason=self._stop_reason or "")
        if self._stop_reason:
            logger.info("training stopped: %s", self._stop_reason)
        return self.state

    def _emergency_save(self, step: int) -> None:
        """Best-effort checkpoint on an unhandled exception: whatever
        survives validation is worth keeping so the restart resumes from
        step N instead of the last cadence save. Covers host-side
        failures — a dead data iterator, a raising callback — where the
        state really is the last completed step's. For a DEVICE-side
        step failure (deferred async XlaRuntimeError, donation already
        consumed) the state may be unreadable; fetching it then raises
        inside save(), is caught below, and the restart falls back to
        the last cadence save — best-effort means exactly that."""
        ckpt = self.emergency_checkpoint
        if ckpt is None or step <= 0:
            return
        try:
            if ckpt.save(step, self.state, force=True, trigger="emergency"):
                ckpt.wait()
                self.flightrec.emit("emergency_checkpoint", step=step,
                                    saved=True)
                logger.warning("emergency checkpoint saved at step %d", step)
            else:
                self.flightrec.emit("emergency_checkpoint", step=step,
                                    saved=False)
                logger.warning(
                    "emergency checkpoint at step %d not written "
                    "(refused by validation or already on disk)", step
                )
        except Exception:
            self.flightrec.emit("emergency_checkpoint", step=step,
                                saved=False, error="save raised")
            logger.exception("emergency checkpoint at step %d failed", step)

    def _dump_postmortem(self, reason: str) -> None:
        """Best-effort JSONL postmortem into the run dir
        (tools/postmortem.py renders it); on the abnormal exit path it
        must never raise past the original failure — the shared helper
        guarantees that."""
        flightrec_lib.dump_postmortem(self.flightrec, self.postmortem_dir,
                                      reason=reason)
