"""Optimizer zoo + LR schedules — the reference's trainer menu, via optax.

Covers every optimizer the reference's substrate shipped under
$TF/python/training/ (gradient_descent.py, momentum.py, adam.py, adagrad.py,
ftrl.py, rmsprop.py — SURVEY.md §2b 'Optimizer zoo' row) plus the modern
ones the workloads expect (adamw for BERT, lamb for large-batch pretraining).
``CrossShardOptimizer`` ($TF/python/tpu/tpu_optimizer.py) has no equivalent
here by design: gradient cross-replica aggregation is the step engine's job
(GSPMD psum), not an optimizer wrapper's.
"""

from __future__ import annotations

import dataclasses

import optax


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    name: str = "sgd"  # sgd|momentum|adam|adamw|adagrad|ftrl|rmsprop|lamb|adafactor
    learning_rate: float = 0.01
    # schedule: constant|cosine|warmup_cosine|exponential|linear
    schedule: str = "constant"
    warmup_steps: int = 0
    total_steps: int = 0  # required by cosine/linear decays
    end_lr_factor: float = 0.0  # final lr = learning_rate * factor
    decay_rate: float = 0.96  # exponential
    decay_steps: int = 1000  # exponential
    momentum: float = 0.9
    nesterov: bool = False
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    # ftrl
    lr_power: float = -0.5
    l1: float = 0.0
    l2: float = 0.0
    clip_grad_norm: float = 0.0  # 0 = off; applied as optax.clip_by_global_norm


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    lr = cfg.learning_rate
    if cfg.schedule == "constant":
        base = optax.constant_schedule(lr)
    elif cfg.schedule == "cosine":
        base = optax.cosine_decay_schedule(
            lr, max(cfg.total_steps - cfg.warmup_steps, 1), alpha=cfg.end_lr_factor
        )
    elif cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            0.0, lr, cfg.warmup_steps, max(cfg.total_steps, 1),
            end_value=lr * cfg.end_lr_factor,
        )
    elif cfg.schedule == "exponential":
        base = optax.exponential_decay(lr, cfg.decay_steps, cfg.decay_rate)
    elif cfg.schedule == "linear":
        base = optax.linear_schedule(
            lr, lr * cfg.end_lr_factor, max(cfg.total_steps - cfg.warmup_steps, 1)
        )
    else:
        raise ValueError(f"Unknown schedule '{cfg.schedule}'")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, lr, cfg.warmup_steps)
        return optax.join_schedules([warmup, base], [cfg.warmup_steps])
    return base


def ftrl(
    learning_rate,
    lr_power: float = -0.5,
    l1: float = 0.0,
    l2: float = 0.0,
    initial_accumulator_value: float = 0.1,
) -> optax.GradientTransformation:
    """Exact FTRL-Proximal (McMahan et al. 2013) — the same per-coordinate
    update as the reference's `tf.train.FtrlOptimizer`
    ($TF/python/training/ftrl.py → ApplyFtrl kernel), as an optax
    transformation. Per coordinate, with accumulators z (adjusted
    gradient) and n (sum of squared gradients):

        n+ = n + g²;  σ = (n+^{-p} − n^{-p}) / α;  z+ = z + g − σ·w
        w+ = 0                                   if |z+| ≤ λ1
           = −(z+ − sign(z+)·λ1) / (n+^{-p}/α + 2λ2)   otherwise

    Dense updates (every coordinate's n grows every step) — the TPU
    regime; the reference used FTRL's sparse form on PS embeddings.
    ``initial_accumulator_value`` matches the TF default (0.1)."""
    import jax
    import jax.numpy as jnp

    sched = (
        learning_rate if callable(learning_rate)
        else (lambda _: learning_rate)
    )

    def init(params):
        # accumulators always f32 (the update math is f32 regardless of
        # param dtype — state dtype must not change across steps)
        return {
            "z": jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            ),
            "n": jax.tree.map(
                lambda p: jnp.full(p.shape, initial_accumulator_value,
                                   jnp.float32),
                params,
            ),
            "count": jnp.zeros((), jnp.int32),
        }

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("ftrl requires params")
        lr = sched(state["count"])
        # lr == 0 (e.g. warmup step 0) must be a no-op step, not a NaN:
        # the update divides by lr, so compute with a stand-in and mask
        live = lr > 0.0
        lr_safe = jnp.where(live, lr, 1.0)
        p = lr_power

        def one(g, z, n, w):
            g = g.astype(jnp.float32)
            w32 = w.astype(jnp.float32)
            n_new = n + g * g
            sigma = (jnp.power(n_new, -p) - jnp.power(n, -p)) / lr_safe
            z_new = z + g - sigma * w32
            quad = jnp.power(n_new, -p) / lr_safe + 2.0 * l2
            w_new = jnp.where(
                jnp.abs(z_new) <= l1,
                0.0,
                -(z_new - jnp.sign(z_new) * l1) / quad,
            )
            delta = jnp.where(live, w_new - w32, 0.0).astype(w.dtype)
            return (delta, jnp.where(live, z_new, z),
                    jnp.where(live, n_new, n))

        # flatten/unflatten (not a tuple-leaved tree.map): params pytrees
        # may themselves contain tuples
        leaves_g, treedef = jax.tree.flatten(updates)
        out = [
            one(g, z, n, w)
            for g, z, n, w in zip(
                leaves_g, jax.tree.leaves(state["z"]),
                jax.tree.leaves(state["n"]), jax.tree.leaves(params),
            )
        ]
        unflat = lambda i: jax.tree.unflatten(treedef, [o[i] for o in out])
        return unflat(0), {
            "z": unflat(1), "n": unflat(2), "count": state["count"] + 1,
        }

    return optax.GradientTransformation(init, update)


def make_multi_optimizer(
    rules, default: OptimizerConfig
) -> optax.GradientTransformation:
    """Per-parameter-group optimizers by path regex, first-match-wins —
    the same path-rule idiom as parallel/sharding.py placement rules.

    rules: ((path_regex, OptimizerConfig), ...); parameters whose
    '/'-joined path matches no rule use ``default``. The canonical use is
    the reference's Wide&Deep split — FTRL on the wide/linear columns,
    AdaGrad on the deep net ($TF DNNLinearCombinedClassifier defaults,
    linear_optimizer='Ftrl'/dnn_optimizer='Adagrad') — see
    workloads/wide_deep.py.
    """
    import re

    import jax

    from ..parallel.sharding import _path_str

    # string labels only: optax state holds them as dict keys, and jax
    # pytrees cannot sort mixed-type keys
    compiled = [(re.compile(pat), f"rule{i}") for i, (pat, _) in enumerate(rules)]
    txs = {f"rule{i}": make_optimizer(c) for i, (_, c) in enumerate(rules)}
    txs["default"] = make_optimizer(default)

    def label_fn(params):
        def lab(path, _leaf):
            name = _path_str(path)
            for rx, key in compiled:
                if rx.search(name):
                    return key
            return "default"

        return jax.tree_util.tree_map_with_path(lab, params)

    return optax.multi_transform(txs, label_fn)


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    sched = make_schedule(cfg)
    name = cfg.name.lower()
    # Coupled L2 for every non-decoupled optimizer: grad += wd·w before the
    # update, kernels only (matching classification_loss_fn's L2 scope; the
    # reference put L2 in the loss for exactly these optimizers). Same math
    # as a loss L2 term, but the multiply fuses into the optimizer's
    # param-update pass instead of costing an extra full-parameter read in
    # the backward graph (~2% step time on the ResNet-50 bench). Note: the
    # decay term is applied inside the optimizer, after the step engine's
    # grads_finite guard — params are finite whenever training is healthy,
    # so the guard's coverage is unchanged in practice. adamw/lamb keep
    # their own decoupled decay.
    coupled_l2 = cfg.weight_decay > 0 and name not in ("adamw", "lamb", "ftrl")

    if name == "sgd":
        tx = optax.sgd(sched)
    elif name == "momentum":
        tx = optax.sgd(sched, momentum=cfg.momentum, nesterov=cfg.nesterov)
    elif name == "adam":
        tx = optax.adam(sched, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps)
    elif name == "adamw":
        tx = optax.adamw(
            sched, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps, weight_decay=cfg.weight_decay
        )
    elif name == "adagrad":
        tx = optax.adagrad(sched, eps=cfg.eps)
    elif name == "ftrl":
        # exact FTRL-Proximal (optax ships none); parity-tested against
        # tf.train.FtrlOptimizer
        # (tests/test_loop_checkpoint.py::test_ftrl_matches_tf_reference)
        tx = ftrl(sched, lr_power=cfg.lr_power, l1=cfg.l1, l2=cfg.l2)
    elif name == "rmsprop":
        tx = optax.rmsprop(sched, momentum=cfg.momentum, eps=cfg.eps)
    elif name == "lamb":
        tx = optax.lamb(
            sched, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps, weight_decay=cfg.weight_decay
        )
    elif name == "adafactor":
        tx = optax.adafactor(sched)
    else:
        raise ValueError(f"Unknown optimizer '{cfg.name}'")
    if cfg.clip_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.clip_grad_norm), tx)
    if coupled_l2:
        import jax

        kernels_only = lambda params: jax.tree.map(
            lambda p: p.ndim > 1, params
        )
        # outermost, so the decay term passes through clipping exactly like
        # a loss-side L2 gradient would
        tx = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay, mask=kernels_only), tx
        )
    return tx
