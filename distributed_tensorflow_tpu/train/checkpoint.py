"""Checkpoint/resume: async multi-host save, retention, preemption.

Replaces the reference's Saver stack (SURVEY.md §3.4, §5.4): `Saver.save`
($TF saver.py:642) driven by CheckpointSaverHook cadence, chief-only writes,
`CheckpointManager` retention/GC (checkpoint_management.py:519), restore via
ChiefSessionCreator/Scaffold, and the modern PreemptionCheckpointHandler
($TF failure_handling.py:337).

TPU-native differences: every host writes its own parameter shards in
parallel (orbax multi-host layout) instead of the chief serializing
everything through one process; saves are async (a host thread overlaps the
next training steps); restore is sharding-aware (each host reads only its
shards); preemption (SIGTERM / maintenance event) triggers one final
coordinated save and a clean exit, and recovery is restart-and-resume
rather than the reference's in-session _RecoverableSession retry loop
(monitored_session.py:1302) — TPU slices fail whole, so elasticity is
checkpoint-restart (SURVEY.md §5.3).

Async cadence saves use a native snapshot-then-commit path (ISSUE 18):
the step boundary takes a host snapshot (`jax.device_get` — donation-safe,
the live buffers may be consumed by the next step immediately), then one
background writer thread streams per-leaf shards through the checksummed
atomic IO (runtime/io.py: tmp+fsync+replace, CRC trailer) into a staging
dir under ``<dir>/.pending/<step>``, writes MANIFEST.dtf LAST, and
publishes the whole step with a single ``os.rename`` into the digit step
dir. Death at ANY instant therefore leaves either a fully valid step or
nothing: the staging dir is not a digit name, so torn background writes
are invisible to ``latest_step``, ``restore(fallback=True)``,
``resilience/fleet.valid_steps`` and the fleet's restore ceiling.
Emergency / preemption / final saves stay synchronous (orbax path). A
failed background save is never silently dropped: its exception is stored
and re-raised from the next ``save()`` / ``wait()`` / ``latest_step()`` /
``close()``, poisoning `latest` instead of skipping a step.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import shutil
import signal
import threading
import time
from typing import Any

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh

from ..obs import flightrec as flightrec_lib
from ..obs import goodput as goodput_lib
from ..parallel import cluster
from ..parallel import sharding as sharding_lib
# submodule import: resilience/retry.py has no train/ dependency, so this
# cannot cycle even though resilience/__init__ imports train.callbacks
from ..resilience.retry import RetryExhausted, RetryPolicy, retry_call
from ..utils import config as config_lib

logger = logging.getLogger(__name__)

#: staging subdir of the background writer — NOT a digit name, so every
#: step-listing consumer (latest_step, fallback restore, fleet
#: valid_steps/newest_common_valid_step) is blind to in-flight writes
PENDING_DIRNAME = ".pending"

#: histogram of background commit latency (enqueue → published step dir)
CKPT_ASYNC_COMMIT_SECONDS = "ckpt_async_commit_seconds"


def step_dir(directory: str, step: int) -> str:
    """The on-disk directory of one checkpoint step — the single
    definition of the layout, shared with the fault harness
    (resilience/faults.py) so disk faults always target the same paths
    the restore-time integrity checks read."""
    return os.path.join(
        os.path.abspath(os.path.expanduser(directory)), str(step)
    )


def _shard_name(index: int) -> str:
    """Native async-commit shard file name for one pytree leaf."""
    return f"shard-{index:05d}.dtf"


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str = ""
    save_interval_steps: int = 1000
    max_to_keep: int = 3
    async_save: bool = True
    save_on_preemption: bool = True
    # Refuse to write a checkpoint whose params contain NaN/Inf. One device
    # reduce over the param tree at save cadence (~free); closes the window
    # where gradients poison the params at step N but the loss — NaNGuard's
    # only signal when debug metrics are off — stays finite until N+1.
    validate_before_save: bool = True
    # Write a checksummed MANIFEST.dtf (native CRC IO, runtime/io.py) into
    # each completed step dir and verify it before restore — the reference
    # Saver's C++ IO-kernel integrity discipline ($TF saver.py:642).
    write_manifest: bool = True
    # Multi-host preemption agreement runs every N steps (a host-side
    # allgather; every step would serialize hosts). A preempted host waits
    # at most N steps before the coordinated save — keep N·step_time well
    # under the preemption grace period.
    preemption_check_every: int = 8


class PreemptionWatcher:
    """SIGTERM/SIGINT-aware flag — the TerminationConfig/
    PreemptionCheckpointHandler analog ($TF failure_handling.py:75,337).
    GCE maintenance events arrive as SIGTERM on TPU VMs."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._event = threading.Event()
        self._prev = {}
        if threading.current_thread() is threading.main_thread():
            for sig in signals:
                self._prev[sig] = signal.signal(sig, self._handler)

    def _handler(self, signum, frame):
        logger.warning("preemption signal %s received", signum)
        self._event.set()

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def close(self) -> None:
        """Reinstall the handlers captured at construction — without
        this, a second Checkpointer built later in the same process
        (tests, eval-side restore) would capture THIS watcher's handler
        as its ``_prev`` and chain stale flags. Only restores signals
        still pointing at this watcher (a newer watcher's handler is
        left in place); idempotent."""
        if threading.current_thread() is not threading.main_thread():
            # signal.signal is main-thread-only; keep _prev so a later
            # main-thread close() can still restore
            return
        for sig, prev in list(self._prev.items()):
            # bound-method identity is not stable across accesses;
            # == compares (__self__, __func__), which is what we need
            if signal.getsignal(sig) == self._handler:
                signal.signal(sig, prev)
                del self._prev[sig]
            # else: a newer watcher's handler is installed — keep our
            # captured prev so a LATER close() (after that watcher
            # restores ours) can still put the original back; dropping
            # it here would lose the original handler forever


class Checkpointer:
    """Save/restore + retention + preemption, over an orbax
    CheckpointManager (sync saves) plus a native snapshot-then-commit
    background writer (async cadence saves). One instance per run; also
    usable standalone for eval-side restore (SURVEY.md §3.5 pattern)."""

    def __init__(self, cfg: CheckpointConfig, mesh: Mesh, spec_tree: Any = None,
                 io_retry: RetryPolicy | None = None, registry=None,
                 flightrec=None, heartbeat=None):
        """``io_retry``: transient-IO retry budget applied to the save /
        restore / manifest-write seams (sites ``ckpt_save`` /
        ``ckpt_restore`` / ``ckpt_manifest_write``); defaults to a
        3-attempt exponential policy. ``registry``: obs.Registry for the
        retry counters (default: the process-wide one). ``flightrec``:
        obs.FlightRecorder for checkpoint lifecycle events (save /
        restore / quarantine; default: the process-wide ring).
        ``heartbeat``: optional fleet heartbeat writer
        (resilience/fleet.HeartbeatWriter, duck-typed ``beat``/``phase``)
        — saves beat phase ``save`` for their duration, so the fleet's
        elastic path can tell a death that landed mid-checkpoint (step
        dir possibly torn → gang-stop fallback) from a clean one. Kept
        out of CheckpointConfig so the config stays JSON-serializable."""
        if not cfg.directory:
            raise ValueError("CheckpointConfig.directory is required")
        self.cfg = cfg
        self.mesh = mesh
        self.spec_tree = spec_tree
        self.io_retry = io_retry if io_retry is not None else RetryPolicy()
        self.registry = registry
        self.heartbeat = heartbeat
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.watcher = PreemptionWatcher() if cfg.save_on_preemption else None
        options = ocp.CheckpointManagerOptions(
            # with the native async path in play, retention is ours
            # (_apply_retention over the committed digit dirs — orbax's
            # GC would race the background commits and has been observed
            # deleting steps it only learned about via reload())
            max_to_keep=None if cfg.async_save else cfg.max_to_keep,
            save_interval_steps=cfg.save_interval_steps,
            # async cadence saves use the native snapshot-then-commit
            # writer below; orbax handles only the synchronous triggers
            enable_async_checkpointing=False,
        )
        base = os.path.abspath(os.path.expanduser(cfg.directory))
        # crash leftovers of a previous incarnation's background writer:
        # .pending was never published, is not restorable by design, and
        # a new writer re-stages from scratch
        shutil.rmtree(os.path.join(base, PENDING_DIRNAME),
                      ignore_errors=True)
        self.manager = ocp.CheckpointManager(base, options=options)
        self._finite_check = None
        #: (step, thread) for in-flight async manifest stampers
        self._manifest_threads: list[tuple[int, threading.Thread]] = []
        #: save-sequence counter guarding the heartbeat save-phase
        #: window: a phase-restore thread only restores if NO newer save
        #: started meanwhile (back-to-back async saves must not clear
        #: the phase while the newer save's shard writes are in flight)
        self._hb_lock = threading.Lock()
        self._hb_save_seq = 0
        #: fault-injection seam: callables ``hook(stage, step)`` invoked
        #: by the background writer at ``async_begin`` (before shard
        #: writes) and ``shards_done`` (after shards, BEFORE the
        #: manifest publish) — resilience/faults.py plugs SlowWriter /
        #: AsyncCommitKill / fsync-error faults in here, through the
        #: exact code path production uses
        self.save_hooks: list = []
        self._async_q: queue.Queue = queue.Queue()
        self._async_thread: threading.Thread | None = None
        #: condition over the in-flight step set; the writer notifies on
        #: every completion (commit or failure) so wait() can drain
        self._async_cv = threading.Condition()
        self._async_steps: set[int] = set()
        #: first unreported background-save failure — re-raised from the
        #: next save()/wait()/latest_step()/close(), so a torn async
        #: save poisons `latest` instead of silently skipping a step
        self._async_error: BaseException | None = None
        self._retention_lock = threading.Lock()

    # -- save -------------------------------------------------------------
    def maybe_save(self, step: int, state: Any) -> bool:
        """Cadence save; also fires unconditionally on observed preemption
        (then asks the caller loop to stop via the returned flag +
        PreemptionError)."""
        if self.watcher is not None and self._any_host_preempted(step):
            saved = self.save(step, state, force=True, trigger="preemption")
            self.wait()
            latest = self.latest_step()
            if not saved and (latest is None or latest < step):
                # validate_before_save refused (non-finite params) and no
                # earlier save covers this step: the run must exit FAILED —
                # raising PreemptionSaved here would tell the scheduler a
                # step-`step` checkpoint exists when nothing was written.
                raise FloatingPointError(
                    f"preempted at step {step} with non-finite params; "
                    f"checkpoint refused (latest on disk: {latest})"
                )
            raise PreemptionSaved(step)
        return self.save(step, state)

    def _any_host_preempted(self, step: int) -> bool:
        """Cross-host OR of the local SIGTERM flag. Orbax saves are
        collective — if only the signaled host entered the save, the others
        would hang it — so every host must agree, the agreement protocol of
        TF's PreemptionCheckpointHandler ($TF failure_handling.py:337),
        throttled to every ``preemption_check_every`` steps."""
        local = bool(self.watcher.preempted)
        if jax.process_count() == 1:
            return local
        if step % max(self.cfg.preemption_check_every, 1) != 0:
            return False  # between agreement rounds even if locally flagged
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([1 if local else 0], np.int32)
        )
        return bool(np.max(flags) > 0)

    def _params_finite(self, state: Any) -> bool:
        """All-finite reduce over the float leaves of state.params AND
        state.opt_state (or of the whole tree for non-TrainState
        pytrees). Optimizer state is part of the check because poisoned
        Adam moments with still-finite params would otherwise pass,
        become the latest checkpoint, and poison the params one step
        after restore — a validated save that still bricks the run.
        Jitted once; identical on every host, so multi-host saves stay
        in agreement."""
        import jax.numpy as jnp

        checked = getattr(state, "params", state)
        opt_state = getattr(state, "opt_state", None)
        if opt_state is not None:
            checked = (checked, opt_state)
        if self._finite_check is None:
            def all_finite(tree):
                leaves = [
                    jnp.all(jnp.isfinite(x)) for x in jax.tree.leaves(tree)
                    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                ]
                return jnp.all(jnp.stack(leaves)) if leaves else jnp.asarray(True)

            self._finite_check = jax.jit(all_finite)
        return bool(jax.device_get(self._finite_check(checked)))

    def save(self, step: int, state: Any, force: bool = False,
             trigger: str = "cadence") -> bool:
        """``trigger`` labels the flight-recorder event (cadence /
        preemption / final / emergency) and selects the write path: with
        ``async_save``, cadence saves go through the native
        snapshot-then-commit background writer; every other trigger —
        the run is ending or the scheduler is about to kill us — stays
        synchronous."""
        native_async = self.cfg.async_save and trigger == "cadence"
        if native_async:
            # a failed background save must fail the RUN at the very
            # next save boundary, not silently leave a step hole
            self._raise_async_error()
        if self._step_exists(step):
            return False  # already saved (e.g. cadence save + final save)
        if native_async and not force:
            # mirror orbax's should_save cadence (first opportunity
            # always saves; then the save_interval_steps grid)
            last = self._newest_known_step()
            if last is not None and last >= step:
                return False
            if (last is not None
                    and step % max(self.cfg.save_interval_steps, 1) != 0):
                return False
        if self.cfg.validate_before_save and not self._params_finite(state):
            logger.error(
                "refusing to checkpoint at step %d: non-finite params", step
            )
            return False
        if native_async:
            return self._save_async(step, state, trigger)
        # Transient-IO retry around the (synchronous) orbax save call.
        prev_phase = None
        seq = 0
        if self.heartbeat is not None:
            # phase "save" for the WRITE's duration: a worker that dies
            # anywhere inside this window may leave a torn step dir, and
            # the fleet's elastic path reads the phase to fall back to a
            # gang stop instead of shrinking around unverified state.
            # ("save" never nests: a prior save's pending restore must
            # not be re-captured.)
            prev_phase = self.heartbeat.phase
            if prev_phase == "save":
                prev_phase = "train"
            with self._hb_lock:
                self._hb_save_seq += 1
                seq = self._hb_save_seq
            self.heartbeat.beat(step=step, phase="save")
        saved = False
        try:
            saved = retry_call(
                lambda: self.manager.save(
                    step, args=ocp.args.StandardSave(state), force=force
                ),
                policy=self.io_retry, site="ckpt_save", registry=self.registry,
                flightrec=self.flightrec,
            )
        finally:
            if self.heartbeat is not None:
                self._restore_phase(prev_phase, seq)
        if saved:
            self.flightrec.emit("ckpt_save", step=step, trigger=trigger)
        if saved and cluster.is_chief():
            logger.info("checkpoint saved at step %d", step)
        if saved and self.cfg.write_manifest and cluster.is_chief():
            self._manifest_threads = [
                (s, t) for s, t in self._manifest_threads if t.is_alive()
            ]
            self._write_manifest(step)
        if saved and self.cfg.async_save:
            # retention is native whenever async saves are on (the orbax
            # manager runs with max_to_keep=None then) — sync triggers
            # must GC too or final/preemption saves grow the dir forever
            self._apply_retention()
        return saved

    # -- native async snapshot-then-commit (ISSUE 18) ----------------------
    def _save_async(self, step: int, state: Any, trigger: str) -> bool:
        """Snapshot on the caller thread (the only part that stalls
        training — booked as ``async_checkpoint`` waste), then hand the
        host copy to the background writer. The heartbeat save-phase
        window opens HERE and is closed by the writer only after the
        commit publishes (or fails), so a death anywhere inside the
        background write shows phase ``save`` to the fleet."""
        t0 = time.perf_counter()
        # device→host copy; donation-safe: the live device buffers may
        # be consumed by the next train step the moment save() returns
        snapshot = jax.device_get(state)
        prev_phase = None
        seq = 0
        if self.heartbeat is not None:
            prev_phase = self.heartbeat.phase
            if prev_phase == "save":
                prev_phase = "train"
            with self._hb_lock:
                self._hb_save_seq += 1
                seq = self._hb_save_seq
            self.heartbeat.beat(step=step, phase="save")
        with self._async_cv:
            self._async_steps.add(step)
        self._ensure_writer()
        self.flightrec.emit("ckpt_async_begin", step=step, trigger=trigger)
        self._async_q.put((step, snapshot, trigger, prev_phase, seq,
                           time.perf_counter()))
        host_cost = time.perf_counter() - t0
        # the honest host-side bill of an async save: snapshot+enqueue
        # stall the step boundary; the shard/fsync work overlaps compute
        goodput_lib.note_wasted(goodput_lib.WASTE_ASYNC_CKPT, host_cost,
                                registry=self.registry)
        if cluster.is_chief():
            logger.info("async checkpoint snapshot at step %d (%.3fs host)",
                        step, host_cost)
        return True

    def _ensure_writer(self) -> None:
        if self._async_thread is None or not self._async_thread.is_alive():
            self._async_thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="ckpt-async-writer",
            )
            self._async_thread.start()

    def _writer_loop(self) -> None:
        """Single FIFO writer: commits land in save order, so retention
        (which runs after each commit) can never evict a step that a
        LATER-queued write still needs — and the staging dir keeps every
        in-flight write out of retention's sight entirely."""
        while True:
            item = self._async_q.get()
            if item is None:
                return
            step, snapshot, trigger, prev_phase, seq, t_enq = item
            try:
                retry_call(
                    lambda: self._commit_async(step, snapshot, trigger,
                                               t_enq),
                    policy=self.io_retry, site="ckpt_save",
                    registry=self.registry, flightrec=self.flightrec,
                )
            except BaseException as e:  # noqa: BLE001 — stored, re-raised
                #                         from the next save()/wait()
                with self._async_cv:
                    if self._async_error is None:
                        self._async_error = e
                logger.exception(
                    "background checkpoint commit for step %d failed; the "
                    "failure will surface at the next save()/wait()", step)
                shutil.rmtree(self._pending_dir(step), ignore_errors=True)
            finally:
                with self._async_cv:
                    self._async_steps.discard(step)
                    self._async_cv.notify_all()
                if self.heartbeat is not None:
                    self._restore_phase(prev_phase, seq)

    def _pending_dir(self, step: int) -> str:
        base = os.path.abspath(os.path.expanduser(self.cfg.directory))
        return os.path.join(base, PENDING_DIRNAME, str(step))

    def _commit_async(self, step: int, snapshot: Any, trigger: str,
                      t_enq: float) -> None:
        """One background commit: stage per-leaf shards under
        ``.pending/<step>`` through the checksummed atomic IO, write
        MANIFEST.dtf LAST, then publish the whole dir with a single
        rename to the digit step name. Interruptible at any instant:
        until the rename, no step-listing consumer can see the write."""
        from ..runtime import io as io_lib
        from io import BytesIO

        import numpy as np

        pending = self._pending_dir(step)
        final = self._step_dir(step)
        shutil.rmtree(pending, ignore_errors=True)  # clean retry slate
        os.makedirs(pending)
        self._run_save_hooks("async_begin", step)
        files = []
        for i, leaf in enumerate(jax.tree.leaves(snapshot)):
            buf = BytesIO()
            np.save(buf, np.asarray(leaf), allow_pickle=False)
            name = _shard_name(i)
            path = os.path.join(pending, name)
            io_lib.write_payload(path, buf.getvalue())
            files.append({"path": name, "bytes": os.path.getsize(path)})
        self._run_save_hooks("shards_done", step)
        if self.cfg.write_manifest:
            payload = json.dumps({"step": step, "files": files}).encode()
            io_lib.write_payload(os.path.join(pending, "MANIFEST.dtf"),
                                 payload)
        os.rename(pending, final)  # the commit point
        dt = time.perf_counter() - t_enq
        self.flightrec.emit("ckpt_save", step=step, trigger=trigger)
        self.flightrec.emit("ckpt_async_commit", step=step,
                            seconds=round(dt, 6))
        reg = (self.registry if self.registry is not None
               else goodput_lib.default_registry())
        reg.histogram(
            CKPT_ASYNC_COMMIT_SECONDS,
            "background async-save commit latency (enqueue → published "
            "step dir)",
        ).observe(dt)
        if cluster.is_chief():
            logger.info("async checkpoint committed at step %d (%.3fs)",
                        step, dt)
        self._apply_retention()

    def _run_save_hooks(self, stage: str, step: int) -> None:
        for hook in list(self.save_hooks):
            hook(stage, step)

    def _committed_steps(self) -> list[int]:
        """Published checkpoint steps, straight from the filesystem: the
        digit dirs are the commit points of BOTH write paths (orbax's
        tmp→rename and the native writer's .pending→rename), so this —
        not the orbax manager's cached view — is the restore truth."""
        base = os.path.abspath(os.path.expanduser(self.cfg.directory))
        try:
            names = os.listdir(base)
        except FileNotFoundError:
            return []
        return sorted(int(n) for n in names
                      if n.isdigit() and os.path.isdir(os.path.join(base, n)))

    def _step_exists(self, step: int) -> bool:
        if os.path.isdir(self._step_dir(step)):
            return True
        with self._async_cv:
            return step in self._async_steps

    def _newest_known_step(self) -> int | None:
        steps = self._committed_steps()
        with self._async_cv:
            if self._async_steps:
                steps = steps + [max(self._async_steps)]
        return max(steps) if steps else None

    def _apply_retention(self) -> None:
        """Keep the newest ``max_to_keep`` PUBLISHED steps. Only digit
        dirs are ever touched — the background writer stages under
        ``.pending/`` until its single commit rename, so retention can
        never pull a directory out from under an in-flight write."""
        if not self.cfg.max_to_keep or self.cfg.max_to_keep <= 0:
            return
        with self._retention_lock:
            steps = self._committed_steps()
            evict = (steps[:-self.cfg.max_to_keep]
                     if len(steps) > self.cfg.max_to_keep else [])
            for s in evict:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
            if evict:
                logger.info("retention evicted checkpoint steps %s "
                            "(max_to_keep=%d)", evict, self.cfg.max_to_keep)
                # the orbax manager caches its step list; refresh so a
                # later sync save/restore agrees with the filesystem
                if hasattr(self.manager, "reload"):
                    self.manager.reload()

    def _drain_async(self, join_s: float) -> None:
        """Bounded join of the in-flight background commits. Stragglers
        (a stuck/slow writer — an injectable fault) are logged BY STEP
        and left in flight for a later wait()/close() to retry."""
        if self._async_thread is None:
            return
        deadline = time.monotonic() + join_s
        with self._async_cv:
            while self._async_steps:
                left = deadline - time.monotonic()
                if left <= 0:
                    logger.error(
                        "async checkpoint writer still busy with steps %s "
                        "after %.1fs join; those checkpoints are not yet "
                        "durable", sorted(self._async_steps), join_s)
                    return
                self._async_cv.wait(left)

    def _raise_async_error(self) -> None:
        with self._async_cv:
            err, self._async_error = self._async_error, None
        if err is not None:
            raise err

    # -- native CRC manifest (runtime/io.py integration) -------------------
    def _step_dir(self, step: int) -> str:
        return step_dir(self.cfg.directory, step)

    def _restore_phase(self, prev_phase: str, seq: int) -> None:
        """Restore the pre-save heartbeat phase — unless a NEWER save
        already started (its own 'save' window must not be cleared by a
        stale restore), or something else owns the phase now (a resize
        barrier hold, a terminal phase): this thread only ever CLEARS
        the 'save' it set."""
        with self._hb_lock:
            if self._hb_save_seq != seq:
                return  # a newer save owns the phase now
            if self.heartbeat.phase != "save":
                return  # barrier/terminal phase owns it — never clobber
            self.heartbeat.beat(phase=prev_phase)

    def _write_manifest(self, step: int) -> None:
        """List every committed file of the step dir into MANIFEST.dtf,
        written through the checksummed atomic native IO (runtime/io.py:
        payload + [magic|len|CRC32] trailer, tmp+fsync+rename). Chief-only;
        on multi-host it records the files visible on the chief's
        filesystem at commit time."""
        from ..runtime import io as io_lib

        d = self._step_dir(step)
        if not os.path.isdir(d):
            return
        files = []
        for root, _, names in os.walk(d):
            for n in sorted(names):
                if n == "MANIFEST.dtf" or n.endswith(".tmp"):
                    continue
                p = os.path.join(root, n)
                files.append({
                    "path": os.path.relpath(p, d),
                    "bytes": os.path.getsize(p),
                })
        payload = json.dumps({"step": step, "files": files}).encode()
        retry_call(
            lambda: io_lib.write_payload(
                os.path.join(d, "MANIFEST.dtf"), payload),
            policy=self.io_retry, site="ckpt_manifest_write",
            registry=self.registry, flightrec=self.flightrec,
        )

    def verify_manifest(self, step: int) -> bool | None:
        """CRC-verify MANIFEST.dtf and check every listed file exists with
        the recorded size. Returns None when no manifest exists (pre-manifest
        checkpoint — allowed), True when intact; raises OSError on a corrupt
        manifest or missing/resized shard."""
        from ..runtime import io as io_lib

        d = self._step_dir(step)
        path = os.path.join(d, "MANIFEST.dtf")
        if not os.path.exists(path):
            return None
        manifest = json.loads(io_lib.read_payload(path))  # raises on bad CRC
        for entry in manifest["files"]:
            p = os.path.join(d, entry["path"])
            if not os.path.exists(p):
                raise OSError(
                    f"checkpoint step {step}: missing shard {entry['path']} "
                    f"(manifest expects {entry['bytes']} bytes at {p})"
                )
            size = os.path.getsize(p)
            if size != entry["bytes"]:
                # name the offending shard and expected-vs-actual sizes:
                # "a step was rejected" is undebuggable, "THIS shard lost
                # 512 bytes" points straight at the torn write
                raise OSError(
                    f"checkpoint step {step}: shard {entry['path']} is "
                    f"{size} bytes, manifest says {entry['bytes']} "
                    f"({entry['bytes'] - size:+d} byte delta at {p})"
                )
        return True

    def save_config(self, cfg_obj: Any) -> None:
        """Serialize the run config next to checkpoints (SURVEY.md §5.6
        reproducibility rule). Chief-only host file, written
        tmp+fsync+rename like every other durable artifact: the config
        is what makes a checkpoint tree reproducible, and a crash
        mid-write must not leave a truncated config.json that parses
        as far as it goes."""
        if cluster.is_chief():
            path = os.path.join(
                os.path.abspath(os.path.expanduser(self.cfg.directory)),
                "config.json",
            )
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                f.write(config_lib.to_json(cfg_obj))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def wait(self, manifest_join_s: float = 60.0) -> None:
        """Drain pending async commits AND their manifest stampers, then
        surface any stored background-save failure.

        Every in-flight stamper thread is joined here with a bounded
        ``manifest_join_s`` timeout — saves only PRUNE dead entries from
        ``_manifest_threads``, so without this join the LAST save's
        stamper would be orphaned at exit and its checkpoint would
        silently lack MANIFEST.dtf. Stragglers that outlive the bound
        are logged BY STEP (so the operator knows exactly which
        checkpoint may be missing its integrity manifest) and kept for a
        later wait()/close() to retry the join. The background writer
        gets the same bounded-join treatment, and a commit that FAILED
        while nobody was looking re-raises here — never lost with its
        thread."""
        self._drain_async(manifest_join_s)
        self.manager.wait_until_finished()
        still_alive: list[tuple[int, threading.Thread]] = []
        for step, t in self._manifest_threads:
            t.join(timeout=manifest_join_s)
            if t.is_alive():
                # never silently drop a stamper: the step's restore-time
                # integrity check depends on MANIFEST.dtf existing
                logger.error(
                    "manifest thread for step %d still running after "
                    "%.1fs join; MANIFEST.dtf for that checkpoint may be "
                    "missing", step, manifest_join_s,
                )
                still_alive.append((step, t))
        self._manifest_threads = still_alive
        self._raise_async_error()

    # -- restore ----------------------------------------------------------
    def latest_step(self) -> int | None:
        """latest_checkpoint analog ($TF checkpoint_management.py:329).
        Reads the published digit dirs (the commit points of both write
        paths); a stored background-save failure re-raises here first —
        `latest` is poisoned, not quietly one step older than believed."""
        self._raise_async_error()
        steps = self._committed_steps()
        return steps[-1] if steps else None

    def restore(self, abstract_state: Any, step: int | None = None,
                fallback: bool = False) -> Any:
        """Sharding-aware restore: each host reads only its shards.

        ``abstract_state``: pytree of jax.ShapeDtypeStruct (e.g. from
        jax.eval_shape over the init fn) — combined with spec_tree it tells
        orbax the target sharding. Returns None if no checkpoint exists
        (caller falls back to fresh init — the Scaffold init-or-restore
        decision, $TF monitored_session.py:52, without a chief).

        ``fallback=True``: walk checkpoints newest→oldest (starting at
        ``step`` when given), QUARANTINING any step whose manifest check
        fails (moved to ``<dir>/.corrupt/<step>``, never silently reused)
        and restoring the newest step that verifies — a truncated newest
        shard degrades the run by a few steps instead of bricking it.
        With ``fallback=False`` an integrity failure raises OSError
        naming the offending shard and its expected-vs-actual size."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        if not fallback:
            if self.cfg.write_manifest:
                self.verify_manifest(step)  # raises before a corrupt restore
            state = self._restore_step(step, abstract_state)
            self.flightrec.emit("ckpt_restore", step=step, fallback=False)
            return state
        for s in sorted(self._committed_steps(), reverse=True):
            if s > step:
                continue  # explicit ceiling: never restore past `step`
            if self.cfg.write_manifest:
                try:
                    # retried: quarantine is destructive, so a transient
                    # FS blip during the check must not condemn a good
                    # step — only a failure that survives the retry
                    # budget counts as corruption
                    retry_call(
                        lambda: self.verify_manifest(s),
                        policy=self.io_retry, site="ckpt_verify",
                        registry=self.registry, flightrec=self.flightrec,
                    )
                except RetryExhausted as e:
                    self._quarantine_or_skip(s, "integrity check",
                                             e.__cause__ or e)
                    continue
            try:
                state = self._restore_step(s, abstract_state)
                self.flightrec.emit("ckpt_restore", step=s, fallback=True)
                return state
            except (OSError, RetryExhausted) as e:
                # a step that verifies (or predates manifests) but fails
                # at read time — e.g. committed shards whose manifest
                # stamp never landed — must also fall back, not brick
                self._quarantine_or_skip(s, "restore", e)
                continue
        return None

    def _quarantine_or_skip(self, step: int, what: str,
                            exc: BaseException) -> None:
        """Condemn a step during the fallback walk. Chief-only rename:
        every host rejects the same step (shared fs, deterministic
        checks) but only one may move it — and a lost race (dir already
        gone) must fall back, not crash."""
        logger.error(
            "checkpoint step %d failed %s (%s); quarantining and falling "
            "back to an older step", step, what, exc,
        )
        if cluster.is_chief():
            try:
                self.quarantine_step(step, reason=str(exc))
            except OSError:
                logger.exception(
                    "quarantining step %d failed; skipping it without "
                    "quarantine", step)
        elif hasattr(self.manager, "reload"):
            self.manager.reload()  # pick up the chief's rename

    def _target_tree(self, abstract_state: Any) -> Any:
        if self.spec_tree is not None:
            shardings = sharding_lib.tree_shardings(self.mesh, self.spec_tree)
            return jax.tree.map(
                lambda s, shd: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=shd
                ),
                abstract_state,
                shardings,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
            )
        return abstract_state

    def _restore_step(self, step: int, abstract_state: Any) -> Any:
        # native async-commit layout (per-leaf shard files) vs orbax —
        # detected per step, so a dir can hold a mix of both
        if os.path.exists(os.path.join(self._step_dir(step), _shard_name(0))):
            state = retry_call(
                lambda: self._restore_native(step, abstract_state),
                policy=self.io_retry, site="ckpt_restore",
                registry=self.registry, flightrec=self.flightrec,
            )
        else:
            if (step not in self.manager.all_steps()
                    and hasattr(self.manager, "reload")):
                self.manager.reload()  # saved before this manager existed
            target = self._target_tree(abstract_state)
            state = retry_call(
                lambda: self.manager.restore(
                    step, args=ocp.args.StandardRestore(target)),
                policy=self.io_retry, site="ckpt_restore",
                registry=self.registry, flightrec=self.flightrec,
            )
        if cluster.is_chief():
            logger.info("restored checkpoint at step %d", step)
        return state

    def _restore_native(self, step: int, abstract_state: Any) -> Any:
        """Load a native async-committed step: one CRC-checked shard per
        pytree leaf, flatten order = save order. Shape/dtype are checked
        against the abstract target — a mismatched shard raises OSError
        so the fallback walk quarantines the step instead of restoring
        garbage."""
        from ..runtime import io as io_lib
        from io import BytesIO

        import numpy as np

        d = self._step_dir(step)
        target = self._target_tree(abstract_state)
        leaves, treedef = jax.tree.flatten(target)
        out = []
        for i, aval in enumerate(leaves):
            data = io_lib.read_payload(os.path.join(d, _shard_name(i)))
            arr = np.load(BytesIO(data), allow_pickle=False)
            if (tuple(arr.shape) != tuple(aval.shape)
                    or arr.dtype != aval.dtype):
                raise OSError(
                    f"checkpoint step {step}: shard {_shard_name(i)} is "
                    f"{arr.dtype}{list(arr.shape)}, restore target wants "
                    f"{aval.dtype}{list(aval.shape)}"
                )
            sharding = getattr(aval, "sharding", None)
            out.append(jax.device_put(arr, sharding) if sharding is not None
                       else jax.device_put(arr))
        return jax.tree.unflatten(treedef, out)

    def quarantine_step(self, step: int, reason: str = "") -> str:
        """Move a failed step dir to ``<dir>/.corrupt/<step>`` (suffixing
        on collision) so fallback never reconsiders it and a later
        ``save()`` at the same step number starts clean. A QUARANTINE
        file records why. Multi-host: call on the chief — the move is a
        single rename on the shared filesystem. Returns the new path."""
        self.flightrec.emit("ckpt_quarantine", step=step,
                            note=str(reason)[:160])
        src = self._step_dir(step)
        base = os.path.join(os.path.dirname(src), ".corrupt")
        os.makedirs(base, exist_ok=True)
        dst = os.path.join(base, str(step))
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(base, f"{step}-{n}")
        os.rename(src, dst)
        try:
            # reviewed: the RENAME above is the quarantine; this note is
            # best-effort human-readable context, and a torn/missing note
            # changes no recovery decision
            with open(os.path.join(dst, "QUARANTINE"), "w") as f:  # dtflint: disable=atomic-durable-write
                f.write(reason + "\n")
        except OSError:  # the reason note is best-effort
            logger.exception("writing QUARANTINE note under %s failed", dst)
        # the orbax manager caches its step list; refresh so latest_step()
        # and a re-save at this step number see the removal
        if hasattr(self.manager, "reload"):
            self.manager.reload()
        logger.warning("quarantined checkpoint step %d -> %s", step, dst)
        return dst

    def close(self) -> None:
        # Drain pending async commits AND their manifest stampers first —
        # otherwise the daemon writer/stamper threads die with the process
        # and the final checkpoint silently lacks shards or its manifest.
        # wait() re-raises a stored background failure; the shutdown below
        # still runs (try/finally), then the failure propagates to the
        # caller — a lost async save surfaces even on the close path.
        try:
            self.wait()
        finally:
            if self._async_thread is not None and self._async_thread.is_alive():
                self._async_q.put(None)
                self._async_thread.join(timeout=5.0)
            if self.watcher is not None:
                self.watcher.close()  # reinstall pre-watcher signal handlers
            self.manager.close()


class PreemptionSaved(RuntimeError):
    """Raised after a successful preemption-triggered save; the run loop
    should exit cleanly so the scheduler can restart-and-resume."""

    def __init__(self, step: int):
        super().__init__(f"preempted; checkpoint saved at step {step}")
        self.step = step


def init_or_restore(
    checkpointer: Checkpointer,
    init_fn,
    tx,
    mesh: Mesh,
    rng: jax.Array,
    fallback: bool = False,
    step: int | None = None,
    **init_kwargs,
):
    """The one-call init-or-restore every train script uses. Builds the
    sharded fresh state (train/step.init_train_state), then overwrites from
    the latest checkpoint if one exists. Returns (state, spec_tree,
    restored_bool). ``fallback=True`` = multi-checkpoint fallback restore
    (corrupt steps quarantined, newest valid step wins) — what supervised
    restarts use. ``step`` caps the restore at that step (the fleet's
    common-checkpoint ceiling, resilience/fleet.py: every gang member
    resumes from the same step); ``step=0`` forces a fresh init."""
    from . import step as step_lib

    state, specs = step_lib.init_train_state(
        init_fn, tx, mesh, rng, **init_kwargs
    )
    checkpointer.spec_tree = specs
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )
    restored = (None if step == 0 else
                checkpointer.restore(abstract, step=step, fallback=fallback))
    if restored is not None:
        return restored, specs, True
    return state, specs, False
