"""Checkpoint/resume: async multi-host save, retention, preemption.

Replaces the reference's Saver stack (SURVEY.md §3.4, §5.4): `Saver.save`
($TF saver.py:642) driven by CheckpointSaverHook cadence, chief-only writes,
`CheckpointManager` retention/GC (checkpoint_management.py:519), restore via
ChiefSessionCreator/Scaffold, and the modern PreemptionCheckpointHandler
($TF failure_handling.py:337).

TPU-native differences: every host writes its own parameter shards in
parallel (orbax multi-host layout) instead of the chief serializing
everything through one process; saves are async (a host thread overlaps the
next training steps); restore is sharding-aware (each host reads only its
shards); preemption (SIGTERM / maintenance event) triggers one final
coordinated save and a clean exit, and recovery is restart-and-resume
rather than the reference's in-session _RecoverableSession retry loop
(monitored_session.py:1302) — TPU slices fail whole, so elasticity is
checkpoint-restart (SURVEY.md §5.3).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import threading
from typing import Any

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh

from ..obs import flightrec as flightrec_lib
from ..parallel import cluster
from ..parallel import sharding as sharding_lib
# submodule import: resilience/retry.py has no train/ dependency, so this
# cannot cycle even though resilience/__init__ imports train.callbacks
from ..resilience.retry import RetryExhausted, RetryPolicy, retry_call
from ..utils import config as config_lib

logger = logging.getLogger(__name__)


def step_dir(directory: str, step: int) -> str:
    """The on-disk directory of one checkpoint step — the single
    definition of the layout, shared with the fault harness
    (resilience/faults.py) so disk faults always target the same paths
    the restore-time integrity checks read."""
    return os.path.join(
        os.path.abspath(os.path.expanduser(directory)), str(step)
    )


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str = ""
    save_interval_steps: int = 1000
    max_to_keep: int = 3
    async_save: bool = True
    save_on_preemption: bool = True
    # Refuse to write a checkpoint whose params contain NaN/Inf. One device
    # reduce over the param tree at save cadence (~free); closes the window
    # where gradients poison the params at step N but the loss — NaNGuard's
    # only signal when debug metrics are off — stays finite until N+1.
    validate_before_save: bool = True
    # Write a checksummed MANIFEST.dtf (native CRC IO, runtime/io.py) into
    # each completed step dir and verify it before restore — the reference
    # Saver's C++ IO-kernel integrity discipline ($TF saver.py:642).
    write_manifest: bool = True
    # Multi-host preemption agreement runs every N steps (a host-side
    # allgather; every step would serialize hosts). A preempted host waits
    # at most N steps before the coordinated save — keep N·step_time well
    # under the preemption grace period.
    preemption_check_every: int = 8


class PreemptionWatcher:
    """SIGTERM/SIGINT-aware flag — the TerminationConfig/
    PreemptionCheckpointHandler analog ($TF failure_handling.py:75,337).
    GCE maintenance events arrive as SIGTERM on TPU VMs."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._event = threading.Event()
        self._prev = {}
        if threading.current_thread() is threading.main_thread():
            for sig in signals:
                self._prev[sig] = signal.signal(sig, self._handler)

    def _handler(self, signum, frame):
        logger.warning("preemption signal %s received", signum)
        self._event.set()

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def close(self) -> None:
        """Reinstall the handlers captured at construction — without
        this, a second Checkpointer built later in the same process
        (tests, eval-side restore) would capture THIS watcher's handler
        as its ``_prev`` and chain stale flags. Only restores signals
        still pointing at this watcher (a newer watcher's handler is
        left in place); idempotent."""
        if threading.current_thread() is not threading.main_thread():
            # signal.signal is main-thread-only; keep _prev so a later
            # main-thread close() can still restore
            return
        for sig, prev in list(self._prev.items()):
            # bound-method identity is not stable across accesses;
            # == compares (__self__, __func__), which is what we need
            if signal.getsignal(sig) == self._handler:
                signal.signal(sig, prev)
                del self._prev[sig]
            # else: a newer watcher's handler is installed — keep our
            # captured prev so a LATER close() (after that watcher
            # restores ours) can still put the original back; dropping
            # it here would lose the original handler forever


class Checkpointer:
    """Save/restore + retention + preemption, over an orbax
    CheckpointManager. One instance per run; also usable standalone for
    eval-side restore (SURVEY.md §3.5 pattern)."""

    def __init__(self, cfg: CheckpointConfig, mesh: Mesh, spec_tree: Any = None,
                 io_retry: RetryPolicy | None = None, registry=None,
                 flightrec=None, heartbeat=None):
        """``io_retry``: transient-IO retry budget applied to the save /
        restore / manifest-write seams (sites ``ckpt_save`` /
        ``ckpt_restore`` / ``ckpt_manifest_write``); defaults to a
        3-attempt exponential policy. ``registry``: obs.Registry for the
        retry counters (default: the process-wide one). ``flightrec``:
        obs.FlightRecorder for checkpoint lifecycle events (save /
        restore / quarantine; default: the process-wide ring).
        ``heartbeat``: optional fleet heartbeat writer
        (resilience/fleet.HeartbeatWriter, duck-typed ``beat``/``phase``)
        — saves beat phase ``save`` for their duration, so the fleet's
        elastic path can tell a death that landed mid-checkpoint (step
        dir possibly torn → gang-stop fallback) from a clean one. Kept
        out of CheckpointConfig so the config stays JSON-serializable."""
        if not cfg.directory:
            raise ValueError("CheckpointConfig.directory is required")
        self.cfg = cfg
        self.mesh = mesh
        self.spec_tree = spec_tree
        self.io_retry = io_retry if io_retry is not None else RetryPolicy()
        self.registry = registry
        self.heartbeat = heartbeat
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.watcher = PreemptionWatcher() if cfg.save_on_preemption else None
        options = ocp.CheckpointManagerOptions(
            max_to_keep=cfg.max_to_keep,
            save_interval_steps=cfg.save_interval_steps,
            enable_async_checkpointing=cfg.async_save,
        )
        self.manager = ocp.CheckpointManager(
            os.path.abspath(os.path.expanduser(cfg.directory)), options=options
        )
        self._finite_check = None
        #: (step, thread) for in-flight async manifest stampers
        self._manifest_threads: list[tuple[int, threading.Thread]] = []
        #: save-sequence counter guarding the heartbeat save-phase
        #: window: a phase-restore thread only restores if NO newer save
        #: started meanwhile (back-to-back async saves must not clear
        #: the phase while the newer save's shard writes are in flight)
        self._hb_lock = threading.Lock()
        self._hb_save_seq = 0

    # -- save -------------------------------------------------------------
    def maybe_save(self, step: int, state: Any) -> bool:
        """Cadence save; also fires unconditionally on observed preemption
        (then asks the caller loop to stop via the returned flag +
        PreemptionError)."""
        if self.watcher is not None and self._any_host_preempted(step):
            saved = self.save(step, state, force=True, trigger="preemption")
            self.wait()
            latest = self.latest_step()
            if not saved and (latest is None or latest < step):
                # validate_before_save refused (non-finite params) and no
                # earlier save covers this step: the run must exit FAILED —
                # raising PreemptionSaved here would tell the scheduler a
                # step-`step` checkpoint exists when nothing was written.
                raise FloatingPointError(
                    f"preempted at step {step} with non-finite params; "
                    f"checkpoint refused (latest on disk: {latest})"
                )
            raise PreemptionSaved(step)
        return self.save(step, state)

    def _any_host_preempted(self, step: int) -> bool:
        """Cross-host OR of the local SIGTERM flag. Orbax saves are
        collective — if only the signaled host entered the save, the others
        would hang it — so every host must agree, the agreement protocol of
        TF's PreemptionCheckpointHandler ($TF failure_handling.py:337),
        throttled to every ``preemption_check_every`` steps."""
        local = bool(self.watcher.preempted)
        if jax.process_count() == 1:
            return local
        if step % max(self.cfg.preemption_check_every, 1) != 0:
            return False  # between agreement rounds even if locally flagged
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([1 if local else 0], np.int32)
        )
        return bool(np.max(flags) > 0)

    def _params_finite(self, state: Any) -> bool:
        """All-finite reduce over the float leaves of state.params AND
        state.opt_state (or of the whole tree for non-TrainState
        pytrees). Optimizer state is part of the check because poisoned
        Adam moments with still-finite params would otherwise pass,
        become the latest checkpoint, and poison the params one step
        after restore — a validated save that still bricks the run.
        Jitted once; identical on every host, so multi-host saves stay
        in agreement."""
        import jax.numpy as jnp

        checked = getattr(state, "params", state)
        opt_state = getattr(state, "opt_state", None)
        if opt_state is not None:
            checked = (checked, opt_state)
        if self._finite_check is None:
            def all_finite(tree):
                leaves = [
                    jnp.all(jnp.isfinite(x)) for x in jax.tree.leaves(tree)
                    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                ]
                return jnp.all(jnp.stack(leaves)) if leaves else jnp.asarray(True)

            self._finite_check = jax.jit(all_finite)
        return bool(jax.device_get(self._finite_check(checked)))

    def save(self, step: int, state: Any, force: bool = False,
             trigger: str = "cadence") -> bool:
        """``trigger`` labels the flight-recorder event only (cadence /
        preemption / final / emergency) — save semantics are identical."""
        if step in self.manager.all_steps():
            return False  # already saved (e.g. cadence save + final save)
        if self.cfg.validate_before_save and not self._params_finite(state):
            logger.error(
                "refusing to checkpoint at step %d: non-finite params", step
            )
            return False
        # Transient-IO retry around the orbax save call. With async_save
        # the heavy shard writes happen later on orbax's own threads (their
        # failures surface at wait_until_finished); the sync path — and the
        # metadata/dispatch work of the async one — gets the retry budget.
        prev_phase = None
        seq = 0
        if self.heartbeat is not None:
            # phase "save" for the WRITE's duration — including the
            # async shard writes on orbax's background threads, not just
            # the dispatch: a worker that dies anywhere inside this
            # window may leave a torn step dir, and the fleet's elastic
            # path reads the phase to fall back to a gang stop instead
            # of shrinking around unverified state. ("save" never nests:
            # a prior save's pending restore must not be re-captured.)
            prev_phase = self.heartbeat.phase
            if prev_phase == "save":
                prev_phase = "train"
            with self._hb_lock:
                self._hb_save_seq += 1
                seq = self._hb_save_seq
            self.heartbeat.beat(step=step, phase="save")
        saved = False
        try:
            saved = retry_call(
                lambda: self.manager.save(
                    step, args=ocp.args.StandardSave(state), force=force
                ),
                policy=self.io_retry, site="ckpt_save", registry=self.registry,
                flightrec=self.flightrec,
            )
        finally:
            if self.heartbeat is not None:
                if saved and self.cfg.async_save:
                    # the heavy shard writes are still in flight on
                    # orbax's threads: restore the phase only once the
                    # commit lands
                    threading.Thread(
                        target=self._restore_phase_after_commit,
                        args=(prev_phase, seq), daemon=True,
                        name=f"ckpt-hb-phase-{step}",
                    ).start()
                else:
                    self._restore_phase(prev_phase, seq)
        if saved:
            self.flightrec.emit("ckpt_save", step=step, trigger=trigger)
        if saved and cluster.is_chief():
            logger.info("checkpoint saved at step %d", step)
        if saved and self.cfg.write_manifest and cluster.is_chief():
            self._manifest_threads = [
                (s, t) for s, t in self._manifest_threads if t.is_alive()
            ]
            if self.cfg.async_save:
                # manifest can only cover files that exist: wait for the
                # async commit on a side thread, then stamp the step dir
                t = threading.Thread(
                    target=self._manifest_after_commit, args=(step,),
                    daemon=True, name=f"ckpt-manifest-{step}",
                )
                t.start()
                self._manifest_threads.append((step, t))
            else:
                self._write_manifest(step)
        return saved

    # -- native CRC manifest (runtime/io.py integration) -------------------
    def _step_dir(self, step: int) -> str:
        return step_dir(self.cfg.directory, step)

    def _restore_phase(self, prev_phase: str, seq: int) -> None:
        """Restore the pre-save heartbeat phase — unless a NEWER save
        already started (its own 'save' window must not be cleared by a
        stale restore), or something else owns the phase now (a resize
        barrier hold, a terminal phase): this thread only ever CLEARS
        the 'save' it set."""
        with self._hb_lock:
            if self._hb_save_seq != seq:
                return  # a newer save owns the phase now
            if self.heartbeat.phase != "save":
                return  # barrier/terminal phase owns it — never clobber
            self.heartbeat.beat(phase=prev_phase)

    def _restore_phase_after_commit(self, prev_phase: str, seq: int) -> None:
        try:
            self.manager.wait_until_finished()
        except Exception:
            # the failure surfaces to the caller at the next wait(); the
            # phase must still be restored or "save" sticks forever
            logger.exception("async commit failed while heartbeat phase "
                             "'save' was held")
        self._restore_phase(prev_phase, seq)

    def _manifest_after_commit(self, step: int) -> None:
        try:
            self.manager.wait_until_finished()
            self._write_manifest(step)
        except Exception:  # never kill the train loop from this thread
            logger.exception("manifest write for step %d failed", step)

    def _write_manifest(self, step: int) -> None:
        """List every committed file of the step dir into MANIFEST.dtf,
        written through the checksummed atomic native IO (runtime/io.py:
        payload + [magic|len|CRC32] trailer, tmp+fsync+rename). Chief-only;
        on multi-host it records the files visible on the chief's
        filesystem at commit time."""
        from ..runtime import io as io_lib

        d = self._step_dir(step)
        if not os.path.isdir(d):
            return
        files = []
        for root, _, names in os.walk(d):
            for n in sorted(names):
                if n == "MANIFEST.dtf" or n.endswith(".tmp"):
                    continue
                p = os.path.join(root, n)
                files.append({
                    "path": os.path.relpath(p, d),
                    "bytes": os.path.getsize(p),
                })
        payload = json.dumps({"step": step, "files": files}).encode()
        retry_call(
            lambda: io_lib.write_payload(
                os.path.join(d, "MANIFEST.dtf"), payload),
            policy=self.io_retry, site="ckpt_manifest_write",
            registry=self.registry, flightrec=self.flightrec,
        )

    def verify_manifest(self, step: int) -> bool | None:
        """CRC-verify MANIFEST.dtf and check every listed file exists with
        the recorded size. Returns None when no manifest exists (pre-manifest
        checkpoint — allowed), True when intact; raises OSError on a corrupt
        manifest or missing/resized shard."""
        from ..runtime import io as io_lib

        d = self._step_dir(step)
        path = os.path.join(d, "MANIFEST.dtf")
        if not os.path.exists(path):
            return None
        manifest = json.loads(io_lib.read_payload(path))  # raises on bad CRC
        for entry in manifest["files"]:
            p = os.path.join(d, entry["path"])
            if not os.path.exists(p):
                raise OSError(
                    f"checkpoint step {step}: missing shard {entry['path']} "
                    f"(manifest expects {entry['bytes']} bytes at {p})"
                )
            size = os.path.getsize(p)
            if size != entry["bytes"]:
                # name the offending shard and expected-vs-actual sizes:
                # "a step was rejected" is undebuggable, "THIS shard lost
                # 512 bytes" points straight at the torn write
                raise OSError(
                    f"checkpoint step {step}: shard {entry['path']} is "
                    f"{size} bytes, manifest says {entry['bytes']} "
                    f"({entry['bytes'] - size:+d} byte delta at {p})"
                )
        return True

    def save_config(self, cfg_obj: Any) -> None:
        """Serialize the run config next to checkpoints (SURVEY.md §5.6
        reproducibility rule). Chief-only host file, written
        tmp+fsync+rename like every other durable artifact: the config
        is what makes a checkpoint tree reproducible, and a crash
        mid-write must not leave a truncated config.json that parses
        as far as it goes."""
        if cluster.is_chief():
            path = os.path.join(
                os.path.abspath(os.path.expanduser(self.cfg.directory)),
                "config.json",
            )
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                f.write(config_lib.to_json(cfg_obj))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def wait(self, manifest_join_s: float = 60.0) -> None:
        """Drain pending async commits AND their manifest stampers.

        Every in-flight stamper thread is joined here with a bounded
        ``manifest_join_s`` timeout — saves only PRUNE dead entries from
        ``_manifest_threads``, so without this join the LAST save's
        stamper would be orphaned at exit and its checkpoint would
        silently lack MANIFEST.dtf. Stragglers that outlive the bound
        are logged BY STEP (so the operator knows exactly which
        checkpoint may be missing its integrity manifest) and kept for a
        later wait()/close() to retry the join."""
        self.manager.wait_until_finished()
        still_alive: list[tuple[int, threading.Thread]] = []
        for step, t in self._manifest_threads:
            t.join(timeout=manifest_join_s)
            if t.is_alive():
                # never silently drop a stamper: the step's restore-time
                # integrity check depends on MANIFEST.dtf existing
                logger.error(
                    "manifest thread for step %d still running after "
                    "%.1fs join; MANIFEST.dtf for that checkpoint may be "
                    "missing", step, manifest_join_s,
                )
                still_alive.append((step, t))
        self._manifest_threads = still_alive

    # -- restore ----------------------------------------------------------
    def latest_step(self) -> int | None:
        """latest_checkpoint analog ($TF checkpoint_management.py:329)."""
        return self.manager.latest_step()

    def restore(self, abstract_state: Any, step: int | None = None,
                fallback: bool = False) -> Any:
        """Sharding-aware restore: each host reads only its shards.

        ``abstract_state``: pytree of jax.ShapeDtypeStruct (e.g. from
        jax.eval_shape over the init fn) — combined with spec_tree it tells
        orbax the target sharding. Returns None if no checkpoint exists
        (caller falls back to fresh init — the Scaffold init-or-restore
        decision, $TF monitored_session.py:52, without a chief).

        ``fallback=True``: walk checkpoints newest→oldest (starting at
        ``step`` when given), QUARANTINING any step whose manifest check
        fails (moved to ``<dir>/.corrupt/<step>``, never silently reused)
        and restoring the newest step that verifies — a truncated newest
        shard degrades the run by a few steps instead of bricking it.
        With ``fallback=False`` an integrity failure raises OSError
        naming the offending shard and its expected-vs-actual size."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        if not fallback:
            if self.cfg.write_manifest:
                self.verify_manifest(step)  # raises before a corrupt restore
            state = self._restore_step(step, abstract_state)
            self.flightrec.emit("ckpt_restore", step=step, fallback=False)
            return state
        for s in sorted(self.manager.all_steps(), reverse=True):
            if s > step:
                continue  # explicit ceiling: never restore past `step`
            if self.cfg.write_manifest:
                try:
                    # retried: quarantine is destructive, so a transient
                    # FS blip during the check must not condemn a good
                    # step — only a failure that survives the retry
                    # budget counts as corruption
                    retry_call(
                        lambda: self.verify_manifest(s),
                        policy=self.io_retry, site="ckpt_verify",
                        registry=self.registry, flightrec=self.flightrec,
                    )
                except RetryExhausted as e:
                    self._quarantine_or_skip(s, "integrity check",
                                             e.__cause__ or e)
                    continue
            try:
                state = self._restore_step(s, abstract_state)
                self.flightrec.emit("ckpt_restore", step=s, fallback=True)
                return state
            except (OSError, RetryExhausted) as e:
                # a step that verifies (or predates manifests) but fails
                # at read time — e.g. committed shards whose manifest
                # stamp never landed — must also fall back, not brick
                self._quarantine_or_skip(s, "restore", e)
                continue
        return None

    def _quarantine_or_skip(self, step: int, what: str,
                            exc: BaseException) -> None:
        """Condemn a step during the fallback walk. Chief-only rename:
        every host rejects the same step (shared fs, deterministic
        checks) but only one may move it — and a lost race (dir already
        gone) must fall back, not crash."""
        logger.error(
            "checkpoint step %d failed %s (%s); quarantining and falling "
            "back to an older step", step, what, exc,
        )
        if cluster.is_chief():
            try:
                self.quarantine_step(step, reason=str(exc))
            except OSError:
                logger.exception(
                    "quarantining step %d failed; skipping it without "
                    "quarantine", step)
        elif hasattr(self.manager, "reload"):
            self.manager.reload()  # pick up the chief's rename

    def _restore_step(self, step: int, abstract_state: Any) -> Any:
        if self.spec_tree is not None:
            shardings = sharding_lib.tree_shardings(self.mesh, self.spec_tree)
            target = jax.tree.map(
                lambda s, shd: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=shd
                ),
                abstract_state,
                shardings,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
            )
        else:
            target = abstract_state
        state = retry_call(
            lambda: self.manager.restore(
                step, args=ocp.args.StandardRestore(target)),
            policy=self.io_retry, site="ckpt_restore", registry=self.registry,
            flightrec=self.flightrec,
        )
        if cluster.is_chief():
            logger.info("restored checkpoint at step %d", step)
        return state

    def quarantine_step(self, step: int, reason: str = "") -> str:
        """Move a failed step dir to ``<dir>/.corrupt/<step>`` (suffixing
        on collision) so fallback never reconsiders it and a later
        ``save()`` at the same step number starts clean. A QUARANTINE
        file records why. Multi-host: call on the chief — the move is a
        single rename on the shared filesystem. Returns the new path."""
        self.flightrec.emit("ckpt_quarantine", step=step,
                            note=str(reason)[:160])
        src = self._step_dir(step)
        base = os.path.join(os.path.dirname(src), ".corrupt")
        os.makedirs(base, exist_ok=True)
        dst = os.path.join(base, str(step))
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(base, f"{step}-{n}")
        os.rename(src, dst)
        try:
            # reviewed: the RENAME above is the quarantine; this note is
            # best-effort human-readable context, and a torn/missing note
            # changes no recovery decision
            with open(os.path.join(dst, "QUARANTINE"), "w") as f:  # dtflint: disable=atomic-durable-write
                f.write(reason + "\n")
        except OSError:  # the reason note is best-effort
            logger.exception("writing QUARANTINE note under %s failed", dst)
        # the orbax manager caches its step list; refresh so latest_step()
        # and a re-save at this step number see the removal
        if hasattr(self.manager, "reload"):
            self.manager.reload()
        logger.warning("quarantined checkpoint step %d -> %s", step, dst)
        return dst

    def close(self) -> None:
        # Drain pending async commits AND their manifest stampers first —
        # otherwise the daemon manifest thread dies with the process and the
        # final checkpoint silently lacks its integrity manifest.
        self.wait()
        if self.watcher is not None:
            self.watcher.close()  # reinstall pre-watcher signal handlers
        self.manager.close()


class PreemptionSaved(RuntimeError):
    """Raised after a successful preemption-triggered save; the run loop
    should exit cleanly so the scheduler can restart-and-resume."""

    def __init__(self, step: int):
        super().__init__(f"preempted; checkpoint saved at step {step}")
        self.step = step


def init_or_restore(
    checkpointer: Checkpointer,
    init_fn,
    tx,
    mesh: Mesh,
    rng: jax.Array,
    fallback: bool = False,
    step: int | None = None,
    **init_kwargs,
):
    """The one-call init-or-restore every train script uses. Builds the
    sharded fresh state (train/step.init_train_state), then overwrites from
    the latest checkpoint if one exists. Returns (state, spec_tree,
    restored_bool). ``fallback=True`` = multi-checkpoint fallback restore
    (corrupt steps quarantined, newest valid step wins) — what supervised
    restarts use. ``step`` caps the restore at that step (the fleet's
    common-checkpoint ceiling, resilience/fleet.py: every gang member
    resumes from the same step); ``step=0`` forces a fresh init."""
    from . import step as step_lib

    state, specs = step_lib.init_train_state(
        init_fn, tx, mesh, rng, **init_kwargs
    )
    checkpointer.spec_tree = specs
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )
    restored = (None if step == 0 else
                checkpointer.restore(abstract, step=step, fallback=fallback))
    if restored is not None:
        return restored, specs, True
    return state, specs, False
