"""Host-loop callbacks — one per reference session hook (SURVEY.md §2b
'Session hooks' row; $TF/python/training/basic_session_run_hooks.py).

Hooks decorated Session.run with extra fetches; callbacks observe the
*already-computed* per-step metrics dict the jit step returns. Metrics are
device arrays and fetching blocks on the step — so callbacks that read
values do it on a cadence (every_n), keeping the steady-state loop fully
async (host dispatches step N+1 while N executes).
"""

from __future__ import annotations

import logging
import os
import signal as signal_lib
import threading
import time
from typing import Any

import jax
import numpy as np

from ..obs import flightrec as flightrec_lib
from ..obs import goodput
from ..obs.registry import Registry, default_registry
from ..parallel import cluster

logger = logging.getLogger(__name__)


class Callback:
    def on_train_start(self, trainer) -> None: ...
    def on_step_end(self, trainer, step: int, metrics: dict[str, Any]) -> None: ...
    def on_train_end(self, trainer) -> None: ...


class StalledError(RuntimeError):
    """A train step exceeded the Watchdog wall budget with
    ``abort_on_stall`` set. Raised *asynchronously* in the training
    thread, so the hung attempt dies as a CLASSIFIED failure —
    ``resilience.classify_failure`` maps it to ``stalled`` (restartable)
    instead of the silent ``train_watchdog_stalled`` gauge being the
    only record. Must be constructible with no arguments: the async
    raise instantiates the class bare."""

    def __init__(self, message: str = "train step exceeded the watchdog "
                                      "wall budget"):
        super().__init__(message)


class HeartbeatCallback(Callback):
    """Fleet-liveness beats from the step seam (resilience/fleet.py):
    every completed step rewrites this worker's heartbeat file with the
    new global step. Pure host file IO — the async dispatch-ahead loop
    is unchanged — and because beats come from the loop itself, a hung
    step STOPS the beats: that silence is exactly the signal the
    FleetSupervisor's missed-heartbeat detection consumes. Beats on
    ``on_train_start`` too, so the (possibly long) first-step compile
    window starts with proof of life."""

    def __init__(self, writer, every_n: int = 1, pace=None):
        """``pace``: optional ``pace(step)`` hook run before each
        step-seam beat — the control-plane IO-delay seam
        (``resilience.faults.FaultPlan.beat_pace``): a bounded sleep
        here models slow heartbeat IO, so gray-failure rounds exercise
        the monitor's LIVE-vs-DEAD judgment under late-but-regular
        beats."""
        if every_n < 1:
            raise ValueError("every_n must be >= 1")
        self.writer = writer
        self.every_n = every_n
        self.pace = pace

    def on_train_start(self, trainer):
        self.writer.beat(phase="train")

    def note_pause(self, seconds: float) -> None:
        """A sanctioned off-the-train-path pause (mid-train distributed
        eval) just ended: beat NOW so the silent window the monitor saw
        stops at the pause boundary instead of stretching into the next
        step. A pause longer than the fleet's stall budget still needs
        that budget sized for it — same rule as compile/restore silent
        windows (docs/resilience.md)."""
        self.writer.beat()

    def on_step_end(self, trainer, step, metrics):
        if step % self.every_n == 0:
            if self.pace is not None:
                self.pace(step)
            self.writer.beat(step=step)


class ElasticCallback(Callback):
    """Step-seam adapter for the elastic fleet client
    (resilience/fleet.ElasticWorker): after every completed step the
    client polls the fleet's SHARD_PLAN, applies any new sharding to the
    worker's data stream (``ElasticStream.reshard`` through
    ``on_reshard``), and — when the fleet orders a resize hold — PAUSES
    the loop here, at a step boundary, until the release names the
    barrier. Pairs with a ``HeartbeatCallback`` on the same writer so
    liveness continues through the pause (the client beats while
    holding). Place it BEFORE the CheckpointCallback: a hold must land
    between steps, not between a step and its cadence save.

    A barrier hold is a sanctioned off-the-train-path pause, so its
    wall time is broadcast to every ``note_pause``-aware peer callback
    (the PR 11 protocol the mid-train eval uses): the cadence meters
    keep measuring the train loop — the fleet books the same window as
    ``elastic_resize`` waste, and double-booking it as productive would
    lie twice — and an armed ``Watchdog`` re-arms at the pause boundary
    instead of aborting the holder mid-resize."""

    def __init__(self, client, clock=time.perf_counter):
        self.client = client
        self.clock = clock

    def _poll(self, trainer, step):
        t0 = self.clock()
        self.client.poll(step)
        pause = self.clock() - t0
        if pause > 0:
            for other in trainer.callbacks:
                if other is self:
                    continue
                note = getattr(other, "note_pause", None)
                if note is not None:
                    note(pause)

    def on_train_start(self, trainer):
        # apply whatever plan is already on disk before the first step
        # (a worker launched mid-resize must not train a stale shard)
        self._poll(trainer, int(trainer.state.step))

    def on_step_end(self, trainer, step, metrics):
        self._poll(trainer, step)


class FleetSnapshotCallback(Callback):
    """Step-seam driver for the fleet-observatory snapshot exporter
    (obs/fleetview.SnapshotExporter): after every ``every_n``-th step
    the worker's telemetry snapshot — registry dump + flight-recorder
    tail — is atomically rewritten next to its heartbeat, where the
    ``FleetSupervisor``'s aggregator (and ``tools/fleet_top.py``) folds
    it into the fleet-wide view. Pure host file IO on the exporter's
    injectable clock; best-effort by design — a full disk must degrade
    the fleet view, never kill the step that was about to be trained.
    The final export on ``on_train_end`` bypasses the exporter's rate
    limit so the run's last state always lands."""

    def __init__(self, exporter, every_n: int = 1):
        if every_n < 1:
            raise ValueError("every_n must be >= 1")
        self.exporter = exporter
        self.every_n = every_n

    def _export(self, step: int | None, force: bool = False) -> None:
        try:
            self.exporter.export(step=step, phase="train", force=force)
        except OSError:
            logger.warning("fleet telemetry snapshot export failed",
                           exc_info=True)

    def on_train_start(self, trainer):
        self._export(int(trainer.state.step))

    def on_step_end(self, trainer, step, metrics):
        if step % self.every_n == 0:
            self._export(step)

    def on_train_end(self, trainer):
        self._export(int(trainer.state.step), force=True)


class StopAtStep(Callback):
    """$TF basic_session_run_hooks.py:393 StopAtStepHook."""

    def __init__(self, last_step: int):
        self.last_step = last_step

    def on_step_end(self, trainer, step, metrics):
        if step >= self.last_step:
            trainer.request_stop(f"reached last_step={self.last_step}")


class MetricsLogger(Callback):
    """StepCounterHook + LoggingTensorHook (:674, :169): steps/sec,
    examples/sec, MFU, and the metric dict, every N steps. Only the chief
    logs (matching the reference's chief-only summaries), but every process
    *fetches* — keeping hosts in lockstep."""

    def __init__(self, every_n: int = 100, batch_size: int | None = None,
                 model_flops_per_step: float | None = None,
                 history: bool = False, clock=time.perf_counter):
        """``model_flops_per_step``: FORWARD FLOPs per step (the framework
        contract — every model's flops_per_example is fwd-only). The ×3
        training multiplier is applied by the shared MFU helper
        (obs/goodput.train_mfu), the one consumer site for all of
        MetricsLogger, bench.py, and the ``mfu`` gauge."""
        self.every_n = every_n
        self.batch_size = batch_size
        self.model_flops = model_flops_per_step
        self.clock = clock
        self._t0: float | None = None
        self._step0 = 0
        self.history: list[dict] = [] if history else None
        self.last: dict[str, float] = {}
        #: step `last` was fetched at — consumers reusing `last` (e.g.
        #: SummaryWriter) MUST check this, or a cadence mismatch writes
        #: stale scalars under a newer global_step.
        self.last_step: int | None = None

    def on_train_start(self, trainer):
        self._t0 = None
        self.last, self.last_step = {}, None

    def note_pause(self, seconds: float) -> None:
        """Wall time spent OFF the train path between two steps (a
        mid-train distributed eval) — shift the rate baseline forward so
        steps/sec, examples/sec, and the derived MFU don't absorb it."""
        if self._t0 is not None:
            self._t0 += max(float(seconds), 0.0)

    def on_step_end(self, trainer, step, metrics):
        if step % self.every_n != 0:
            return
        fetched = {k: float(np.asarray(v)) for k, v in metrics.items()}
        now = self.clock()
        if self._t0 is not None:
            dt = now - self._t0
            steps_per_sec = (step - self._step0) / max(dt, 1e-9)
            fetched["steps_per_sec"] = steps_per_sec
            if self.batch_size:
                fetched["examples_per_sec"] = steps_per_sec * self.batch_size
            if self.model_flops:
                # one MFU definition for log line, bench JSON, and gauge:
                # obs/goodput.py applies the fwd+bwd multiplier
                fetched["mfu"] = goodput.train_mfu(
                    self.model_flops, steps_per_sec)
        self._t0, self._step0 = now, step
        self.last, self.last_step = fetched, step
        if self.history is not None:
            self.history.append({"step": step, **fetched})
        if cluster.is_chief():
            msg = " ".join(
                f"{k}={v:.6g}" for k, v in sorted(fetched.items())
            )
            logger.info("step %d: %s", step, msg)


def _fresh_scalars(metrics_logger: "MetricsLogger | None", step: int,
                   metrics: dict[str, Any]) -> dict[str, float]:
    """Scalars for ``step``: reuse the paired logger's fetched dict ONLY
    if its fetch happened at this very step (it ran earlier in the
    callback list with an aligned cadence) — `last` from an older step
    consumed under the current step would silently shift every curve
    (the SummaryWriter stale-scalar bug). Otherwise fetch directly,
    paying the same cadence'd device sync the logger would."""
    if metrics_logger is not None and metrics_logger.last_step == step:
        return dict(metrics_logger.last)
    return {k: float(np.asarray(v)) for k, v in metrics.items()}


class SummaryWriter(Callback):
    """SummarySaverHook analog ($TF basic_session_run_hooks.py:793,
    SURVEY.md §5.5): writes TensorBoard scalar event files via tensorboardX
    (same wire format as tf.summary). Chief-only — matching the reference's
    chief-only summaries — and cadence-gated like MetricsLogger so the
    steady-state loop stays async. Throughput/MFU scalars come from the
    paired MetricsLogger when one is given (avoids double-fetching)."""

    def __init__(self, logdir: str, every_n: int = 100,
                 metrics_logger: "MetricsLogger | None" = None):
        self.logdir = logdir
        self.every_n = every_n
        self.metrics_logger = metrics_logger
        self._writer = None

    def on_train_start(self, trainer):
        if cluster.is_chief():
            from tensorboardX import SummaryWriter as TBWriter

            self._writer = TBWriter(self.logdir)

    def on_step_end(self, trainer, step, metrics):
        if self._writer is None or step % self.every_n != 0:
            return
        for k, v in _fresh_scalars(self.metrics_logger, step,
                                   metrics).items():
            self._writer.add_scalar(f"train/{k}", v, global_step=step)

    def on_train_end(self, trainer):
        if self._writer is not None:
            self._writer.flush()
            self._writer.close()
            self._writer = None


class TelemetryCallback(Callback):
    """Canonical metrics sink: mirrors the train loop into an
    obs.Registry (scrape-able via obs.export, mergeable across hosts) —
    the registry-backed replacement for reading ``MetricsLogger.last``/
    ``history`` out of band.

    Two cadences, preserving the async steady state:

    - EVERY step: a host-clock step-latency observation into the
      ``train_step_seconds`` histogram plus a ``train_steps_total``
      tick. Pure host arithmetic — never touches the device metrics, so
      the loop's dispatch-ahead pipelining is unchanged.
    - Every ``every_n`` steps: scalar gauges (``train_<name>``). Reuses
      the paired MetricsLogger's already-fetched dict when its fetch
      happened at this step (same staleness rule as SummaryWriter);
      otherwise fetches directly — the same cadence'd device sync every
      other observer pays.

    With ``track_goodput`` (default on) the same host clock also feeds
    the goodput ledger (obs/goodput.py): the interval from
    ``on_train_start`` to the first completed step — compile + warmup —
    is booked as ``wasted_seconds_total{cause=compile_warmup}``, every
    later inter-step interval as productive seconds. Counters, so the
    accounting survives supervised restarts by the registry's
    merge-not-reset invariant.
    """

    def __init__(self, registry: Registry | None = None, every_n: int = 100,
                 metrics_logger: "MetricsLogger | None" = None,
                 clock=time.perf_counter, track_goodput: bool = True):
        self.registry = registry if registry is not None else default_registry()
        self.every_n = every_n
        self.metrics_logger = metrics_logger
        self.clock = clock
        self.track_goodput = track_goodput
        self._t_prev: float | None = None
        self._t_start: float | None = None
        self._step_prev = 0
        self._m_step = self.registry.histogram(
            "train_step_seconds", "host wall-clock between step dispatches")
        self._m_steps = self.registry.counter(
            "train_steps_total", "train steps completed")
        self._m_gstep = self.registry.gauge(
            "train_global_step", "latest completed global step")

    @staticmethod
    def _gauge_name(key: str) -> str:
        sane = "".join(c if c.isalnum() or c == "_" else "_" for c in key)
        return f"train_{sane}"

    def on_train_start(self, trainer):
        self._t_prev = None
        self._t_start = self.clock() if self.track_goodput else None

    def note_pause(self, seconds: float) -> None:
        """Wall time spent OFF the train path between two steps (a
        mid-train distributed eval): shift the inter-step baseline
        forward so the next ``train_step_seconds`` observation and its
        productive-seconds booking cover only step time. Eval wall time
        is deliberately neither productive nor wasted in the goodput
        ledger — it buys evaluation, not training progress, and booking
        it as either would skew ``goodput_fraction``."""
        pause = max(float(seconds), 0.0)
        if self._t_prev is not None:
            self._t_prev += pause
        elif self._t_start is not None:
            # pause landed inside the warmup window: keep it out of the
            # compile_warmup waste bucket too
            self._t_start += pause

    def on_step_end(self, trainer, step, metrics):
        now = self.clock()
        if self._t_prev is not None:
            # mean host latency per step since the last observation (the
            # loop calls us every step, so this is one step's wall time)
            n = max(step - self._step_prev, 1)
            self._m_step.observe((now - self._t_prev) / n)
            if self.track_goodput:
                goodput.note_productive(now - self._t_prev,
                                        registry=self.registry)
        elif self.track_goodput and self._t_start is not None:
            # attempt's first completed step: train_start → here is jit
            # compile + warmup, not productive throughput — the histogram
            # skips it (no baseline) and goodput books it as warmup waste
            goodput.note_wasted(goodput.WASTE_COMPILE_WARMUP,
                                now - self._t_start, registry=self.registry)
        self._t_prev, self._step_prev = now, step
        self._m_steps.inc()
        self._m_gstep.set(step)
        if step % self.every_n != 0:
            return
        scalars = _fresh_scalars(self.metrics_logger, step, metrics)
        for k, v in scalars.items():
            self.registry.gauge(
                self._gauge_name(k), "train metric (cadence-sampled)"
            ).set(v)
        if self.track_goodput and "mfu" in scalars:
            # mirror the paired logger's MFU into the canonical gauge
            self.registry.gauge(
                goodput.MFU, "model FLOPs utilization of the train step"
            ).set(scalars["mfu"])


class NaNGuard(Callback):
    """NanTensorHook (:761): stop (or raise) when the step reports non-finite
    loss/grads. Reads the on-device `grads_finite`/`loss` signals the step
    engine piggybacks on its output (SURVEY.md §5.5).

    When the step carries the per-step ``nonfinite`` flag
    (``StepOptions(skip_nonfinite=True)``, docs/resilience.md "Numeric
    anomalies"), the guard reads IT on every step instead of the
    cadence'd loss fetch: the old cadence left a non-finite step N
    unnoticed until the next multiple of ``every_n`` — after donation
    had already overwritten the state — so the abort was late and the
    blamed step wrong. With the flag the abort is immediate and exact
    (and the in-graph guard means the state it aborts with is still the
    last healthy one). The per-step scalar fetch trades the
    dispatch-ahead overlap for exactness — the same trade
    ``AnomalyPolicy`` makes, which supersedes this guard when wired
    (skipped steps never reach callbacks at all). Inside ``Trainer.fit``
    with the guard on and NO policy, the loop itself fails fast on the
    flag BEFORE callbacks run (a flagged no-op step must not be counted
    — see the loop), so this branch — ``fail_fast=False`` included — is
    reached only by custom/externally-driven loops; for
    skip-and-continue under Trainer, wire an AnomalyPolicy."""

    def __init__(self, every_n: int = 10, fail_fast: bool = True):
        self.every_n = every_n
        self.fail_fast = fail_fast

    def on_step_end(self, trainer, step, metrics):
        if "nonfinite" in metrics:
            from .step import step_nonfinite

            if step_nonfinite(metrics):
                self._bad(trainer, step)
            return
        if step % self.every_n != 0:
            return
        bad = False
        if "grads_finite" in metrics:
            bad |= float(np.asarray(metrics["grads_finite"])) == 0.0
        if "loss" in metrics:
            bad |= not np.isfinite(np.asarray(metrics["loss"]))
        if bad:
            self._bad(trainer, step)

    def _bad(self, trainer, step: int) -> None:
        msg = f"non-finite loss/gradients at step {step}"
        if self.fail_fast:
            raise FloatingPointError(msg)
        trainer.request_stop(msg)


def _async_raise(ident: int, exc_type: type[BaseException]) -> None:
    """Raise ``exc_type`` asynchronously in thread ``ident``
    (PyThreadState_SetAsyncExc) — the only host-side way to abort a
    train loop that is no longer reaching its own callbacks. Delivery
    happens at that thread's next bytecode; a thread blocked in a C
    call sees it when the call returns."""
    import ctypes

    n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(ident), ctypes.py_object(exc_type))
    if n != 1:
        logger.error(
            "async %s delivery to thread %d failed (SetAsyncExc hit %d "
            "threads)", exc_type.__name__, ident, n)


def _async_cancel(ident: int) -> None:
    """Revoke a not-yet-delivered async exception for thread ``ident``
    (SetAsyncExc with NULL; ctypes passes None as NULL). No-op when the
    exception already delivered."""
    import ctypes

    ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(ident), None)


#: ``abort_on_stall`` delivery for MAIN-THREAD loops: a process signal.
#: SetAsyncExc delivery can be lost on this CPython while the target
#: thread blocks inside C sleeps (observed: a hung-loop spin that never
#: received its StalledError); a signal instead wakes blocking C calls
#: via EINTR and its Python handler runs in the main thread at the next
#: bytecode, where it raises StalledError directly. The handler is
#: installed once, process-wide, on first arm and STAYS installed: with
#: no abort pending it ignores the signal, so a late delivery can never
#: hit SIGUSR1's default action (process termination) or kill a
#: recovered run. SetAsyncExc remains the best-effort fallback for
#: loops driven from non-main threads.
_STALL_SIGNAL = signal_lib.SIGUSR1
#: ids of watchdogs with an abort pending. Plain module-level set: the
#: mutations are GIL-atomic, and the signal handler must not take locks
#: (it preempts arbitrary main-thread code, possibly a lock holder).
_pending_aborts: set[int] = set()
_stall_handler_installed = False


def _stall_signal_handler(signum, frame):
    if _pending_aborts:
        _pending_aborts.clear()
        raise StalledError()
    logger.warning(
        "stall-abort signal received with no abort pending; ignored")


def _install_stall_handler() -> None:
    """Main-thread only (signal.signal requirement); idempotent."""
    global _stall_handler_installed
    if not _stall_handler_installed:
        signal_lib.signal(_STALL_SIGNAL, _stall_signal_handler)
        _stall_handler_installed = True


class Watchdog(Callback):
    """Host-side hung-step detector (docs/resilience.md): if no
    ``on_step_end`` arrives within ``budget_s`` wall seconds, flag the
    stall to the obs registry — ``train_watchdog_stalled`` gauge goes to
    1 and ``train_watchdog_stalls_total`` counts the event — and log an
    error. The next completed step clears the gauge (recovery), so a
    scrape sees `stalled==1` exactly while a step is overdue.

    Detection only by default: a stuck collective (one host dead in a
    psum) cannot be un-stuck host-side — the signal exists so the
    scrape surface / job scheduler can decide to kill-and-restart,
    which the checkpoint layer turns into resume-from-last-save. With
    ``abort_on_stall=True`` the watchdog goes one step further: on the
    stall edge it raises ``StalledError`` in the thread that entered
    ``on_train_start``, so a hung-but-interruptible step dies as a
    *classified, restartable* failure (``resilience.classify_failure``
    → ``stalled``) that the in-process Supervisor rolls back to the
    last valid checkpoint. Delivery: when the loop runs on the MAIN
    thread (the normal case) the abort arrives as a process signal
    whose handler raises ``StalledError`` — this interrupts blocking C
    sleeps via EINTR and, unlike PyThreadState_SetAsyncExc, cannot be
    silently lost; SetAsyncExc is the best-effort fallback for loops on
    other threads. Limitation: a thread wedged inside a C call that
    ignores EINTR (a device wait, a stuck collective) only aborts when
    the call returns; process-level supervision (resilience/fleet.py)
    is the layer that handles those, by killing the process. The
    monitor runs on a daemon poll thread; ``clock`` is injectable so
    tests (and the fault harness's ClockStall) can drive time
    deterministically.
    """

    def __init__(self, budget_s: float = 300.0, registry: Registry | None = None,
                 poll_s: float | None = None, clock=time.monotonic,
                 flightrec=None, abort_on_stall: bool = False):
        if budget_s <= 0:
            raise ValueError("budget_s must be positive")
        self.budget_s = budget_s
        self.flightrec = (flightrec if flightrec is not None
                          else flightrec_lib.default_recorder())
        self.registry = registry if registry is not None else default_registry()
        self.poll_s = poll_s if poll_s is not None else max(
            min(budget_s / 4, 1.0), 0.005)
        self.clock = clock
        self.abort_on_stall = abort_on_stall
        self._beat: float | None = None
        self._loop_ident: int | None = None  # thread to abort on stall
        self._abort_issued = False           # abort issued, not consumed
        self._signal_abort = False           # deliver via signal (main thread)
        self._lock = threading.Lock()  # orders beat writes vs stall flags
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._m_stalled = self.registry.gauge(
            "train_watchdog_stalled",
            "1 while no train step has completed within the watchdog budget")
        self._m_stalls = self.registry.counter(
            "train_watchdog_stalls_total",
            "times a train step exceeded the watchdog wall budget")

    def on_train_start(self, trainer):
        # delivery mode decided (and the handler installed) on the loop
        # thread, BEFORE the poll thread exists
        self._signal_abort = (
            self.abort_on_stall
            and threading.current_thread() is threading.main_thread())
        if self._signal_abort:
            _install_stall_handler()
        # same critical section as on_step_end/_watch: a supervised
        # restart re-enters here while a previous attempt's poll thread
        # may still be draining (dtflint: lock-discipline)
        with self._lock:
            self._beat = self.clock()
            self._loop_ident = threading.get_ident()
            self._m_stalled.set(0.0)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="train-watchdog")
        self._thread.start()

    def note_pause(self, seconds: float) -> None:
        """A sanctioned pause (mid-train distributed eval) just ended:
        re-arm the beat so the budget clock restarts at the pause
        boundary — without this, a stall abort could fire right after a
        long eval even though the loop is healthy. An eval LONGER than
        the budget still flags mid-pause (the poll thread cannot know a
        pause is sanctioned until it ends); size ``budget_s`` above the
        expected eval wall time, the same rule as compile windows."""
        with self._lock:
            if self._beat is not None:
                self._beat = self.clock()

    def on_step_end(self, trainer, step, metrics):
        with self._lock:
            if self._m_stalled.value:
                logger.warning("watchdog: step %d completed, stall cleared",
                               step)
                self._m_stalled.set(0.0)
            self._beat = self.clock()
            cancel = self._take_abort_unlocked()
        if cancel is not None:
            # the flagged step completed after all: progress wins — a
            # pending (undelivered) abort must not kill the healthy run.
            # Tiny race left: an abort delivered between the flag and
            # this revoke still aborts, which is within semantics (that
            # step really did exceed the budget).
            _pending_aborts.discard(id(self))
            if not self._signal_abort:
                _async_cancel(cancel)
            logger.warning("watchdog: step %d completed before the abort "
                           "delivered; revoked", step)

    def on_train_end(self, trainer):
        with self._lock:
            cancel = self._take_abort_unlocked()
        if cancel is not None:
            # loop exited with the abort still undelivered: revoke so it
            # cannot land in post-training code (final save, teardown)
            _pending_aborts.discard(id(self))
            if not self._signal_abort:
                _async_cancel(cancel)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _take_abort_unlocked(self) -> int | None:
        """Consume the abort-in-flight marker; caller holds the lock."""
        if not self._abort_issued:
            return None
        self._abort_issued = False
        return self._loop_ident

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            # beat read, staleness check, and flag set are one critical
            # section with on_step_end — otherwise a step landing
            # between check and set leaves a spurious stall flagged and
            # the edge-triggered counter inflated forever
            with self._lock:
                if self._beat is None:
                    continue
                overdue = self.clock() - self._beat
                if overdue <= self.budget_s or self._m_stalled.value:
                    continue
                # edge-triggered: one count per stall, gauge stays up
                # until a step completes
                self._m_stalled.set(1.0)
                self._m_stalls.inc()
                abort_ident = (self._loop_ident if self.abort_on_stall
                               else None)
                if abort_ident is not None:
                    # issue + marker in ONE critical section: a
                    # concurrent on_step_end revoke is then strictly
                    # before (sees no marker, nothing issued yet) or
                    # strictly after (sees marker, revokes a real issue)
                    self._abort_issued = True
                    if self._signal_abort:
                        _pending_aborts.add(id(self))
                        os.kill(os.getpid(), _STALL_SIGNAL)
                    else:
                        _async_raise(abort_ident, StalledError)
            # outside the lock: the recorder has its own
            self.flightrec.emit("watchdog_stall",
                                overdue_s=round(overdue, 3),
                                budget_s=self.budget_s,
                                abort=bool(abort_ident))
            logger.error(
                "watchdog: no step completed for %.1fs "
                "(budget %.1fs) — host loop or a collective is hung",
                overdue, self.budget_s,
            )


class Profiler(Callback):
    """ProfilerHook (:1013) → jax.profiler traces (same XPlane/TensorBoard
    wire format as TF's, SURVEY.md §5.1)."""

    def __init__(self, logdir: str, start_step: int = 10, num_steps: int = 5):
        self.logdir = logdir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False

    def on_step_end(self, trainer, step, metrics):
        if step == self.start_step and not self._active:
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop_step and self._active:
            jax.tree.map(
                lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x,
                metrics,
            )
            jax.profiler.stop_trace()
            self._active = False
            if cluster.is_chief():
                logger.info("profile written to %s", self.logdir)

    def on_train_end(self, trainer):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False


class CheckpointCallback(Callback):
    """CheckpointSaverHook (:524): delegates cadence + retention to the
    checkpoint manager (train/checkpoint.py); also saves on clean train end
    and on preemption (SURVEY.md §5.3/5.4). Named distinctly from the
    train.checkpoint.Checkpointer manager it wraps."""

    def __init__(self, manager):
        self.manager = manager

    def on_step_end(self, trainer, step, metrics):
        self.manager.maybe_save(step, trainer.state)

    def on_train_end(self, trainer):
        if trainer.failed:
            # Aborting on an error (e.g. NaNGuard): the in-memory state may
            # be poisoned — never let it become the latest checkpoint. The
            # background writer is still joined (bounded) so teardown never
            # races a half-written commit — but its stored error must not
            # MASK the failure that aborted the run: log it and let the
            # original exception propagate.
            logger.warning("skipping final checkpoint: training failed")
            try:
                self.manager.wait()
            except Exception:
                logger.exception(
                    "async checkpoint writer also failed during aborted run")
            return
        # final save is synchronous by contract; wait() then drains any
        # in-flight cadence commit and re-raises a stored background-save
        # error — a failed async save poisons the run here instead of
        # silently dropping a step
        self.manager.save(int(trainer.state.step), trainer.state, force=True,
                          trigger="final")
        self.manager.wait()
