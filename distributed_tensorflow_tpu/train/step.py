"""The train-step engine: one jit-compiled SPMD program per step.

This is the structural replacement for the reference's entire per-step
machinery (SURVEY.md §3.1): ``SyncReplicasOptimizer.apply_gradients``'s
per-variable ConditionalAccumulators, the sync token FIFOQueue, the chief's
QueueRunner thread, and the two gRPC round-trips per variable per step all
collapse into a single XLA-compiled function — gradients are aggregated by
collectives the compiler places on ICI, and the barrier is the collective
itself. The host does one dispatch per step (the inversion described in
SURVEY.md §3.3).

Design notes
------------
- **GSPMD, not explicit collectives**: the step is ``jax.jit``-ed over a
  mesh; input arrays carry NamedShardings (batch over (data, fsdp), params
  per the sharding rules), and XLA inserts the gradient all-reduce /
  reduce-scatter. The explicit-collective path (shard_map) is reserved for
  schedules XLA can't infer (pipeline, ring attention).
- **Gradient accumulation** is the legitimate descendant of the reference's
  ConditionalAccumulator ($TF data_flow_ops.py:1386): microbatches are
  scanned on-device in f32, no staleness protocol needed.
- **State**: a single pytree (step, params, opt_state, model_state, rng) —
  the global_step variable, PS-resident parameters, and slot variables of
  the reference, as one shardable object.
- **RNG**: the state holds one base key; each step folds in the step number,
  so resume-from-checkpoint reproduces the exact dropout stream.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh

from ..parallel import sharding as sh

# loss_fn(params, model_state, batch, rng) -> (loss, (new_model_state, aux_metrics))
LossFn = Callable[[Any, Any, Any, jax.Array], tuple[jax.Array, tuple[Any, dict]]]


@struct.dataclass
class TrainState:
    """Everything that must survive a step / a checkpoint / a preemption."""

    step: jax.Array  # i32 scalar — replaces the global_step variable
    params: Any
    opt_state: Any
    model_state: Any  # mutable collections (e.g. BatchNorm stats); {} if none
    rng: jax.Array  # base key; per-step keys are fold_in(rng, step)


#: re-export — the optax spec-inheritance logic lives at the sharding
#: seam now (parallel/sharding.py), next to every other spec producer
opt_state_specs = sh.opt_state_specs


def state_specs(state_shape: TrainState, param_specs: Any) -> TrainState:
    """PartitionSpec tree covering the whole TrainState."""
    return TrainState(
        step=sh.REPLICATED,
        params=param_specs,
        opt_state=sh.opt_state_specs(
            state_shape.opt_state, state_shape.params, param_specs),
        model_state=sh.replicated_specs(state_shape.model_state),
        rng=sh.REPLICATED,
    )


def init_train_state(
    init_fn: Callable[[jax.Array], tuple[Any, Any]],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rng: jax.Array,
    *,
    param_rules: sh.PathRules | None = None,
    param_specs: Any | None = None,
    fsdp: bool = False,
    fsdp_min_size: int = 2**14,
) -> tuple[TrainState, TrainState]:
    """Build a fully sharded TrainState without ever materializing it
    unsharded (critical when params exceed one chip's HBM).

    Returns ``(state, spec_tree)``. Replaces the reference's chief-side
    ``Scaffold``/init_op dance ($TF monitored_session.py:52): there is no
    chief — every process runs the same jit-ed init and XLA places shards.

    ``param_rules``: a sharding.PartitionRules table (strict
    match_partition_rules contract) or legacy regex path rules
    (sharding.specs_from_path_rules);
    ``param_specs``: explicit spec tree (wins over rules);
    ``fsdp``: additionally shard unmatched params via auto_fsdp_specs.
    """

    def full_init(key):
        params, model_state = init_fn(key)
        opt_state = tx.init(params)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            model_state=model_state,
            rng=key,
        )

    abstract = jax.eval_shape(full_init, rng)
    if param_specs is None:
        if param_rules is not None:
            param_specs = sh.specs_from_rules(abstract.params, param_rules)
        else:
            param_specs = sh.replicated_specs(abstract.params)
    if fsdp:
        auto = sh.auto_fsdp_specs(abstract.params, mesh, min_size=fsdp_min_size)
        param_specs = sh.merge_specs(param_specs, auto)
    specs = state_specs(abstract, param_specs)
    shardings = sh.tree_shardings(mesh, specs)
    state = jax.jit(full_init, out_shardings=shardings)(rng)
    return state, specs


@dataclasses.dataclass(frozen=True)
class StepOptions:
    grad_accum_steps: int = 1
    # Debug signals are OPT-IN: each is a full extra pass over every gradient
    # leaf per step (real HBM bandwidth on conv nets). NaNGuard works without
    # them — it reads the loss, which the host fetches anyway, and a NaN in
    # the grads poisons the loss within one step.
    compute_grad_norm: bool = False
    check_grads_finite: bool = False
    clip_grad_norm: float | None = None  # applied here, before tx
    # No-update-on-nonfinite (docs/resilience.md "Numeric anomalies"): when
    # the step's loss or any gradient leaf is non-finite, the compiled step
    # returns the OLD state bit-identically — step counter included — via a
    # device-side select over the update (apply_if_finite-style), and
    # reports a per-step ``nonfinite`` flag in its metrics. SAFETY is pure
    # device work: poisoned params never exist and donation stays legal
    # without any host check before the update. CONSUMING the flag on the
    # host (resilience/anomaly.AnomalyPolicy skip/blame/quarantine, or the
    # Trainer's fail-fast check when no policy is wired) fetches one
    # scalar per step — that read trades the dispatch-ahead overlap for
    # exactness (``step_nonfinite``). Covers both the single-batch and
    # the grad-accumulation scan paths.
    skip_nonfinite: bool = False


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    options: StepOptions = StepOptions(),
) -> Callable[[TrainState, Any], tuple[TrainState, dict]]:
    """Build the (un-jitted) train step. Wrap with ``jax.jit(...,
    donate_argnums=0)`` — the Trainer does this — so the old state's buffers
    are reused in place, the TPU analog of the reference's in-place PS
    variable updates."""

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    accum = options.grad_accum_steps

    def train_step(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
        step_rng = jax.random.fold_in(state.rng, state.step)

        if accum == 1:
            (loss, (model_state, aux)), grads = grad_fn(
                state.params, state.model_state, batch, step_rng
            )
        else:
            # Microbatch scan: mean-of-means gradient, sequential model_state
            # threading. The descendant of ConditionalAccumulator semantics
            # minus the staleness protocol (SURVEY.md §2b).
            def to_micro(x):
                b = x.shape[0]
                return x.reshape(accum, b // accum, *x.shape[1:])

            micro = jax.tree.map(to_micro, batch)
            keys = jax.random.split(step_rng, accum)

            def body(carry, xs):
                g_acc, l_acc, mstate = carry
                mb, key = xs
                (loss_i, (mstate, aux_i)), g_i = grad_fn(
                    state.params, mstate, mb, key
                )
                g_acc = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) / accum, g_acc, g_i
                )
                return (g_acc, l_acc + loss_i / accum, mstate), aux_i

            g0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
            (grads, loss, model_state), aux_stack = jax.lax.scan(
                body, (g0, jnp.zeros((), jnp.float32), state.model_state),
                (micro, keys),
            )
            aux = jax.tree.map(lambda x: x.mean(axis=0), aux_stack)

        metrics = {"loss": loss.astype(jnp.float32), **aux}

        if options.compute_grad_norm or options.clip_grad_norm:
            gnorm = optax.global_norm(grads)
            metrics["grad_norm"] = gnorm
        if options.clip_grad_norm:
            scale = jnp.minimum(1.0, options.clip_grad_norm / (gnorm + 1e-9))
            grads = jax.tree.map(lambda g: g * scale, grads)

        if options.check_grads_finite:
            # NaN guard signal, computed on-device and piggybacked on the step
            # output (SURVEY.md §5.5) — the NanTensorHook replacement. Off by
            # default: NaNGuard's loss check catches the same failures one
            # step later at zero cost.
            metrics["grads_finite"] = jnp.all(
                jnp.asarray([jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)])
            ).astype(jnp.float32)
        elif options.compute_grad_norm or options.clip_grad_norm:
            # Free same-step guard: the global norm is already computed,
            # and one non-finite gradient leaf poisons it — so its
            # finiteness IS grads-finiteness, at zero extra passes. This
            # closes the "NaNGuard fires one step late" window whenever
            # grad-norm/clipping is on (VERDICT r2 Weak #4).
            metrics["grads_finite"] = jnp.isfinite(gnorm).astype(jnp.float32)

        if options.skip_nonfinite:
            # One reduce per gradient leaf + the loss: the exact
            # apply_if_finite predicate. Computed BEFORE tx.update so the
            # flag reflects the step's inputs, not NaNs the optimizer math
            # may have laundered (Adam's eps can turn inf into finite).
            finite = [jnp.isfinite(loss)] + [
                jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)
            ]
            ok = jnp.all(jnp.stack(finite))
            metrics["nonfinite"] = 1.0 - ok.astype(jnp.float32)

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1,
            params=params,
            opt_state=opt_state,
            model_state=model_state,
            rng=state.rng,
        )
        if options.skip_nonfinite:
            # Select OLD vs NEW per leaf on device: a non-finite step is a
            # no-op — params, opt_state, model_state AND the step counter
            # stay bit-identical, so the batch is provably droppable (the
            # trajectory becomes a pure function of (seed, quarantine
            # set); data/pipeline.QuarantineFilter is the other half).
            # Leaves the candidate state shares with the old one (rng)
            # pass through untouched — jnp.where on them would choke on
            # non-numeric leaves like typed PRNG keys.
            new_state = jax.tree.map(
                lambda new, old: new if new is old else jnp.where(ok, new, old),
                new_state, state,
            )
        return new_state, metrics

    return train_step


def step_nonfinite(metrics) -> bool:
    """Host-side read of the per-step ``nonfinite`` flag a
    ``skip_nonfinite`` step piggybacks on its metrics (False when the
    flag is absent). One scalar fetch — it blocks until the step
    completes, the one place flag exactness costs the dispatch-ahead
    overlap. Every consumer (the Trainer loop's fail-fast check,
    NaNGuard, AnomalyPolicy) reads through here, so the flag's encoding
    has a single read-side contract next to its producer."""
    import numpy as np

    flag = metrics.get("nonfinite")
    return flag is not None and float(np.asarray(flag)) != 0.0


def make_eval_step(eval_fn):
    """eval_fn(params, model_state, batch) -> dict of summed metrics."""

    def eval_step(state: TrainState, batch):
        return eval_fn(state.params, state.model_state, batch)

    return eval_step


def jit_train_step(step_fn, mesh: Mesh, spec_tree: TrainState):
    """jit with explicit state shardings (batch/output shardings inferred).

    Donation makes the update in-place in HBM — without it, peak memory
    doubles (params + new params live simultaneously)."""
    state_shardings = sh.tree_shardings(mesh, spec_tree)
    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, None),
        out_shardings=(state_shardings, None),
        donate_argnums=0,
    )
