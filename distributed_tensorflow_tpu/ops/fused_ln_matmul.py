"""Pallas-TPU fused LayerNorm + matmul (+bias) — transformer hot path.

Companion to ops/fused_conv_bn.py for the transformer family: every
pre-LN block applies LayerNorm and immediately feeds a Dense matmul
(qkv, mlp_in). XLA materializes the normalized tensor between them
(write + read over [tokens, d_model]); here the matmul kernel normalizes
its input tile in VMEM instead — LayerNorm statistics are ROW-local
(mean/var over d_model, fully resident in a [bm, d] tile), so unlike
BatchNorm no cross-tile stats pass exists at all. Per LN→matmul edge
this removes the LN output write and its read(s); the backward kernels
recompute x̂ per tile and fold the coupled LayerNorm backward (row
means of dx̂ and dx̂·x̂) into the same pass that computes dx.

Reference analog: the reference's BERT ran LayerNorm as separate
CUDA/cuDNN ops around its matmuls; this is the TPU-native "native
kernel" tier (SURVEY.md §5.8 native-code policy).

Numerics: f32 statistics and accumulation, bf16 (or f32) IO; stats use
eps inside rsqrt like flax LayerNorm. Interpret mode runs the same
kernels on CPU (tests, SURVEY.md §4.2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import _tiling


def _pick_block_m(M: int, d: int, n: int) -> int:
    return _tiling.pick_block_m(M, d, n, name="fused ln_matmul")


_on_tpu = _tiling.on_tpu


def _ln(x32, gamma, beta, eps):
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    xhat = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return xhat, xhat * gamma + beta


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, g_ref, b_ref, w_ref, bias_ref, y_ref, *, eps):
    x32 = x_ref[:].astype(jnp.float32)
    _, h = _ln(x32, g_ref[:], b_ref[:], eps)
    y = jnp.dot(h.astype(x_ref.dtype), w_ref[:],
                preferred_element_type=jnp.float32)
    y_ref[:] = (y + bias_ref[:]).astype(y_ref.dtype)


def _fwd_call(x, gamma, beta, w, bias, *, eps, out_dtype, interpret):
    M, d = x.shape
    n = w.shape[1]
    bm = _pick_block_m(M, d, n)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(M // bm,),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, n), out_dtype),
        interpret=interpret,
        name="ln_matmul_fwd",
    )(x, gamma, beta, w, bias)


# ---------------------------------------------------------------------------
# Backward A: dx (+ dgamma/dbeta/dbias) streaming the M grid
# ---------------------------------------------------------------------------


def _bwd_dx_kernel(x_ref, g_ref, w_ref, dy_ref,
                   dx_ref, dg_ref, db_ref, dbias_ref, *, eps):
    x32 = x_ref[:].astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x32 - mu) * inv
    dy = dy_ref[:].astype(jnp.float32)
    # dh = dy @ w^T (contract over n)
    dh = jax.lax.dot_general(
        dy.astype(dy_ref.dtype), w_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dxhat = dh * g_ref[:]
    # coupled LayerNorm backward, all row-local
    m1 = dxhat.mean(-1, keepdims=True)
    m2 = (dxhat * xhat).mean(-1, keepdims=True)
    dx_ref[:] = ((dxhat - m1 - xhat * m2) * inv).astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)
        dbias_ref[:] = jnp.zeros_like(dbias_ref)

    dg_ref[:] += (dh * xhat).sum(0, keepdims=True)
    db_ref[:] += dh.sum(0, keepdims=True)
    dbias_ref[:] += dy.sum(0, keepdims=True)


def _bwd_dx_call(x, gamma, w, dy, *, eps, interpret):
    # beta is not an operand: dx/dgamma/dbeta/dbias are all independent
    # of it (it only shifts the forward's h, which dw alone consumes)
    M, d = x.shape
    n = w.shape[1]
    bm = _pick_block_m(M, d, n)
    dx, dg, db, dbias = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, eps=eps),
        grid=(M // bm,),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, n), lambda i: (0, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, d), x.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=interpret,
        name="ln_matmul_bwd_dx",
    )(x, gamma, w, dy)
    return dx, dg[0], db[0], dbias[0]


# ---------------------------------------------------------------------------
# Backward B: dw = h^T @ dy with a [d, bn]-tile accumulator
# ---------------------------------------------------------------------------


def _bwd_dw_kernel(x_ref, g_ref, b_ref, dy_ref, dw_ref, *, eps):
    x32 = x_ref[:].astype(jnp.float32)
    _, h = _ln(x32, g_ref[:], b_ref[:], eps)

    @pl.when(pl.program_id(1) == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    dw_ref[:] += jax.lax.dot_general(
        h.astype(x_ref.dtype), dy_ref[:],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _bwd_dw_call(x, gamma, beta, dy, *, eps, interpret):
    M, d = x.shape
    n = dy.shape[1]
    # emit_stats=True deliberately over-counts scratch by ~bm*bn*4 to
    # cover this kernel's f32 LN recompute (x32 + h), which the conv
    # model attributes to the stats path
    bm, bn = _tiling.pick_dw_tiles(
        M, d, n, in_bytes=x.dtype.itemsize, emit_stats=True,
        name="fused ln_matmul dw kernel",
    )
    return pl.pallas_call(
        functools.partial(_bwd_dw_kernel, eps=eps),
        grid=(n // bn, M // bm),  # M innermost: dw tile revisited
        in_specs=[
            pl.BlockSpec((bm, d), lambda j, i: (i, 0)),
            pl.BlockSpec((1, d), lambda j, i: (0, 0)),
            pl.BlockSpec((1, d), lambda j, i: (0, 0)),
            pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
        ],
        out_specs=pl.BlockSpec((d, bn), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((d, n), jnp.float32),
        interpret=interpret,
        name="ln_matmul_bwd_dw",
    )(x, gamma, beta, dy)


# ---------------------------------------------------------------------------
# The XLA-math backward (round-3 default — see fused_conv_bn._xla_bwd:
# same on-chip finding, the two-pass Pallas backward loses to XLA's
# fused dgrad/wgrad at bench shapes while the Pallas forward wins)
# ---------------------------------------------------------------------------


def _xla_bwd(x, gamma, beta, w, dy, *, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x32 - mu) * inv
    dy32 = dy.astype(jnp.float32)
    dh = jax.lax.dot_general(
        dy, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dxhat = dh * gamma
    m1 = dxhat.mean(-1, keepdims=True)
    m2 = (dxhat * xhat).mean(-1, keepdims=True)
    dx = ((dxhat - m1 - xhat * m2) * inv).astype(x.dtype)
    dg = (dh * xhat).sum(0, keepdims=True)
    db = dh.sum(0, keepdims=True)
    dbias = dy32.sum(0, keepdims=True)
    h = (xhat * gamma + beta).astype(x.dtype)
    dw = jax.lax.dot_general(
        h, dy, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dx, dg, db, dw, dbias


# ---------------------------------------------------------------------------
# custom_vjp composite + reference
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _make_op(eps, out_dtype, interpret, bwd_impl):
    @jax.custom_vjp
    def op(x, gamma, beta, w, bias):
        return _fwd_call(x, gamma, beta, w, bias, eps=eps,
                         out_dtype=out_dtype, interpret=interpret)

    def fwd(x, gamma, beta, w, bias):
        y = _fwd_call(x, gamma, beta, w, bias, eps=eps,
                      out_dtype=out_dtype, interpret=interpret)
        return y, (x, gamma, beta, w)

    def bwd(res, dy):
        x, gamma, beta, w = res
        dy = dy.astype(jnp.dtype(out_dtype))
        if bwd_impl == "xla":
            dx, dg, db, dw, dbias = _xla_bwd(
                x, gamma.reshape(1, -1), beta.reshape(1, -1), w, dy, eps=eps
            )
            return (dx, dg.reshape(1, -1), db.reshape(1, -1),
                    dw.astype(w.dtype), dbias.reshape(1, -1))
        dx, dg, db, dbias = _bwd_dx_call(
            x, gamma, w, dy, eps=eps, interpret=interpret
        )
        dw = _bwd_dw_call(
            x, gamma, beta, dy, eps=eps, interpret=interpret
        ).astype(w.dtype)
        # cotangent shapes match op's (1, d)/(1, n) operands; the public
        # wrapper's reshape transposes them back to the caller's [d]/[n]
        return (dx, dg.reshape(1, -1), db.reshape(1, -1), dw,
                dbias.reshape(1, -1))

    op.defvjp(fwd, bwd)
    return op


def ln_matmul(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    w: jax.Array,
    bias: jax.Array | None = None,
    *,
    eps: float = 1e-6,
    out_dtype=None,
    interpret: bool | None = None,
    bwd_impl: str | None = None,
) -> jax.Array:
    """``LayerNorm(x; gamma, beta) @ w + bias`` in one kernel.

    x: [M, d]; gamma/beta: [d] f32; w: [d, n]; bias: [n] or None.
    Returns [M, n] in ``out_dtype`` (default: x.dtype).
    """
    if interpret is None:
        interpret = not _on_tpu()
    M, d = x.shape
    n = w.shape[1]
    if bias is None:
        bias = jnp.zeros((n,), jnp.float32)
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    bwd_impl = _tiling.resolve_bwd_impl(bwd_impl)
    # reviewed: eps/interpret are keyword-only host config (python
    # float/bool), normalized for the op cache key before tracing ever
    # sees them — not device values (tools/validate_fused_tpu.py jits
    # this entry point, which is how the cross-module engine reaches it)
    op = _make_op(float(eps), out_dtype.name, bool(interpret), bwd_impl)  # dtflint: disable=host-sync-in-step
    return op(
        x,
        gamma.reshape(1, d).astype(jnp.float32),
        beta.reshape(1, d).astype(jnp.float32),
        w,
        bias.reshape(1, n).astype(jnp.float32),
    )


def ln_matmul_reference(x, gamma, beta, w, bias=None, *, eps=1e-6,
                        out_dtype=None):
    """Pure-jnp oracle with the same numerics contract."""
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    h = (x32 - mu) * jax.lax.rsqrt(var + eps) * gamma.reshape(1, -1)
    h = (h + beta.reshape(1, -1)).astype(x.dtype)
    y = jnp.dot(h, w, preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias.reshape(1, -1)
    return y.astype(out_dtype)
