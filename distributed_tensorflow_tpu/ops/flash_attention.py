"""Pallas-TPU FlashAttention-2 kernel (forward + backward, custom_vjp).

The hot op of the transformer family (models/transformer.py) and the
per-chip inner block of ring attention (parallel/ring_attention.py,
SURVEY.md §5.7). This is the framework's "native kernel" tier: where the
reference framework dropped to hand-written CUDA for its hot ops
(SURVEY.md §2b native rows), the TPU-native equivalent is a Pallas kernel
compiled to Mosaic (SURVEY.md §5.8 native-code policy).

Design (standard FlashAttention-2 tiling, adapted to TPU tiles):

- Layout [B, H, S, D]: the grid iterates (batch, head, q-block, kv-block)
  with the kv-block innermost; each kernel instance owns one
  (block_q × D) output tile held in VMEM f32 scratch across the kv sweep,
  with running max ``m`` and denominator ``l`` as (block_q × LANES)
  broadcast-tiles (TPU scratch wants 2-D lane-aligned shapes).
- The forward also emits LSE = m + log l at sublane width
  ([B,H,Sq,STAT_DIM], STAT_DIM=8 — lane-broadcasting the row stat 128-wide
  would cost 16× HBM for long sequences). The backward is two more pallas
  calls (dKV with q-block innermost; dQ with kv-block innermost), the
  FlashAttention-2 split that keeps every accumulator local to one grid
  cell (no cross-instance atomics, which TPU does not have); each
  recomputes delta = rowsum(dO·O) per tile instead of materializing it.
- Causal masking skips fully-masked kv blocks via ``pl.when`` (no MXU work
  issued), and applies the triangular mask inside diagonal blocks.
- ``kv_mask`` [B, Sk] covers padding (BERT-style); mask semantics match
  ops/attention.py (True = attend).
- On non-TPU backends ``interpret=True`` runs the same kernels through the
  Pallas interpreter — this is how CI (8 fake CPU devices, SURVEY.md §4.2)
  tests the exact kernel code path without TPU hardware.

bf16 inputs are upcast per-tile; all accumulation is f32 (online-softmax
numerics, SURVEY.md §7 "hard parts" #3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF

LANES = 128  # TPU lane width (scratch row-stat tiles)
STAT_DIM = 8  # f32 sublane width (HBM row-stat storage)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _dot(a, b, dims):
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=jnp.float32
    )


def _causal_mask(q_start, kj, block_q, block_k):
    qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return kpos <= qpos


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, mask_ref,
    o_ref, lse_ref,
    acc_ref, m_ref, l_ref,
    *, sm_scale, causal, block_q, block_k, q_offset,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)
    q_start = qi * block_q + q_offset

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        logits = _dot(q, k, ((1,), (1,))) * sm_scale  # [bq, bk]
        mask = mask_ref[0, 0].astype(jnp.bool_)[None, :]
        if causal:
            mask = mask & _causal_mask(q_start, kj, block_q, block_k)
        logits = jnp.where(mask, logits, NEG_INF)

        m_prev = m_ref[...]  # [bq, LANES] (row stat broadcast over lanes)
        l_prev = l_ref[...]
        m_cur = logits.max(axis=1)[:, None]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        # explicit zero under the mask: for fully-masked rows m stays
        # NEG_INF and exp(NEG_INF - NEG_INF) would be 1, poisoning l
        p = jnp.where(mask, jnp.exp(logits - m_new[:, :1]), 0.0)  # [bq, bk]
        correction = jnp.exp(m_prev - m_new)  # [bq, LANES]
        l_ref[...] = l_prev * correction + jnp.broadcast_to(
            p.sum(axis=1)[:, None], l_prev.shape
        )
        acc_ref[...] = acc_ref[...] * correction[:, :1] + _dot(
            p, v, ((1,), (0,))
        )
        m_ref[...] = m_new

    if causal:
        # skip kv blocks strictly above the diagonal band (no MXU work)
        pl.when(kj * block_k <= q_start + block_q - 1)(compute)
    else:
        compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[...][:, :1]  # [bq, 1]
        # all-masked rows (l==0) → zero output, lse = NEG_INF
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        lse = jnp.where(l > 0.0, m_ref[...] + jnp.log(safe_l), NEG_INF)
        lse_ref[0, 0] = lse[:, :STAT_DIM].astype(lse_ref.dtype)


def _fwd_call(
    q, k, v, kv_mask, *, sm_scale, causal, q_offset, block_q, block_k,
    interpret
):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    grid = (B, H, Sq // block_q, Sk // block_k)

    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, q_offset=q_offset,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec(
                (1, 1, block_q, STAT_DIM), lambda b, h, i, j: (b, h, i, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, STAT_DIM), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
        name="flash_attention_fwd",
    )(q, k, v, kv_mask)
    return out, lse


# ---------------------------------------------------------------------------
# Backward: dKV kernel (kv block resident, q innermost) and
#           dQ kernel (q block resident, kv innermost)
# ---------------------------------------------------------------------------


def _bwd_p_ds(q_ref, k_ref, v_ref, mask_ref, do_ref, o_ref, lse_ref,
              *, sm_scale, causal, q_start, kj, block_q, block_k):
    """Shared tile math: recompute p and ds for one (q-block, kv-block)."""
    q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
    k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)  # [bq, D]
    o = o_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, :1]  # [bq, 1]
    delta = jnp.sum(do * o, axis=1, keepdims=True)  # [bq, 1]

    logits = _dot(q, k, ((1,), (1,))) * sm_scale  # [bq, bk]
    mask = mask_ref[0, 0].astype(jnp.bool_)[None, :]
    if causal:
        mask = mask & _causal_mask(q_start, kj, block_q, block_k)
    # p = exp(logits - lse); all-masked rows have lse=NEG_INF → force 0
    p = jnp.where(mask, jnp.exp(logits - lse), 0.0)  # [bq, bk]
    dp = _dot(do, v, ((1,), (1,)))  # [bq, bk]
    ds = p * (dp - delta) * sm_scale
    return q, do, p, ds


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, o_ref, lse_ref,
    dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, sm_scale, causal, block_q, block_k, q_offset,
):
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    q_start = qi * block_q + q_offset

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        q, do, p, ds = _bwd_p_ds(
            q_ref, k_ref, v_ref, mask_ref, do_ref, o_ref, lse_ref,
            sm_scale=sm_scale, causal=causal, q_start=q_start, kj=kj,
            block_q=block_q, block_k=block_k,
        )
        dv_acc[...] += _dot(p, do, ((0,), (0,)))  # pᵀ·dO → [bk, D]
        dk_acc[...] += _dot(ds, q, ((0,), (0,)))  # dsᵀ·q → [bk, D]

    if causal:
        pl.when(kj * block_k <= q_start + block_q - 1)(compute)
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, mask_ref, do_ref, o_ref, lse_ref,
    dq_ref,
    dq_acc,
    *, sm_scale, causal, block_q, block_k, q_offset,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    nk = pl.num_programs(3)
    q_start = qi * block_q + q_offset

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute():
        k = k_ref[0, 0].astype(jnp.float32)
        _, _, _, ds = _bwd_p_ds(
            q_ref, k_ref, v_ref, mask_ref, do_ref, o_ref, lse_ref,
            sm_scale=sm_scale, causal=causal, q_start=q_start, kj=kj,
            block_q=block_q, block_k=block_k,
        )
        dq_acc[...] += _dot(ds, k, ((1,), (0,)))  # [bq, D]

    if causal:
        pl.when(kj * block_k <= q_start + block_q - 1)(compute)
    else:
        compute()

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# custom_vjp wiring
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, kv_mask, sm_scale, causal, block_q, block_k, interpret,
           q_offset):
    out, _ = _fwd_call(
        q, k, v, kv_mask,
        sm_scale=sm_scale, causal=causal, q_offset=q_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out


def _flash_fwd(q, k, v, kv_mask, sm_scale, causal, block_q, block_k,
               interpret, q_offset):
    out, lse = _fwd_call(
        q, k, v, kv_mask,
        sm_scale=sm_scale, causal=causal, q_offset=q_offset,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, q_offset,
               res, do):
    q, k, v, kv_mask, out, lse = res
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    common = dict(
        sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, q_offset=q_offset,
    )

    qspec = lambda b, h, j, i: (b, h, i, 0)  # noqa: E731
    kspec = lambda b, h, j, i: (b, h, j, 0)  # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(B, H, Sk // block_k, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), qspec),
            pl.BlockSpec((1, 1, block_k, D), kspec),
            pl.BlockSpec((1, 1, block_k, D), kspec),
            pl.BlockSpec((1, 1, block_k), lambda b, h, j, i: (b, 0, j)),
            pl.BlockSpec((1, 1, block_q, D), qspec),
            pl.BlockSpec((1, 1, block_q, D), qspec),
            pl.BlockSpec((1, 1, block_q, STAT_DIM), qspec),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), kspec),
            pl.BlockSpec((1, 1, block_k, D), kspec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
        name="flash_attention_bwd_dkv",
    )(q, k, v, kv_mask, do, out, lse)

    qspec2 = lambda b, h, i, j: (b, h, i, 0)  # noqa: E731
    kspec2 = lambda b, h, i, j: (b, h, j, 0)  # noqa: E731
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B, H, Sq // block_q, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), qspec2),
            pl.BlockSpec((1, 1, block_k, D), kspec2),
            pl.BlockSpec((1, 1, block_k, D), kspec2),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j)),
            pl.BlockSpec((1, 1, block_q, D), qspec2),
            pl.BlockSpec((1, 1, block_q, D), qspec2),
            pl.BlockSpec((1, 1, block_q, STAT_DIM), qspec2),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), qspec2),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
        name="flash_attention_bwd_dq",
    )(q, k, v, kv_mask, do, out, lse)

    return dq, dk, dv, np.zeros(kv_mask.shape, jax.dtypes.float0)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Paged decode kernel: attention straight off the block pool
# ---------------------------------------------------------------------------


def _paged_fwd_kernel(
    bt_ref,  # scalar-prefetched block table [B, MB] (unused in the body —
    #          it drives the k/v index_maps; Pallas still passes it in)
    q_ref, qpos_ref, k_ref, v_ref,
    o_ref,
    acc_ref, m_ref, l_ref,
    *, sm_scale, block_size,
):
    del bt_ref
    j = pl.program_id(2)
    nb = pl.num_programs(2)
    S = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0, 0].astype(jnp.float32)  # [S, D]
    k = k_ref[0, 0].astype(jnp.float32)  # [bs, D] — one physical block
    v = v_ref[0, 0].astype(jnp.float32)
    logits = _dot(q, k, ((1,), (1,))) * sm_scale  # [S, bs]
    kpos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (S, block_size), 1
    )
    qp = qpos_ref[0]  # [S] absolute query positions (-1 = padded row)
    mask = kpos <= qp[:, None]
    logits = jnp.where(mask, logits, NEG_INF)

    m_prev = m_ref[...]
    l_prev = l_ref[...]
    m_cur = logits.max(axis=1)[:, None]
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
    # explicit zero under the mask (see _fwd_kernel): fully-masked rows
    # keep m == NEG_INF and must not poison l with exp(0) == 1
    p = jnp.where(mask, jnp.exp(logits - m_new[:, :1]), 0.0)
    correction = jnp.exp(m_prev - m_new)
    l_ref[...] = l_prev * correction + jnp.broadcast_to(
        p.sum(axis=1)[:, None], l_prev.shape
    )
    acc_ref[...] = acc_ref[...] * correction[:, :1] + _dot(p, v, ((1,), (0,)))
    m_ref[...] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_ref[...][:, :1]
        # all-masked rows (idle slots never reach here with l == 0 — their
        # sentinel q_pos attends everything — but padded rows do)
        o_ref[0, 0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(
            o_ref.dtype
        )


def paged_flash_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_table: jax.Array,
    *,
    q_pos: jax.Array,
    sm_scale: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Block-table-aware attention for paged decode — KV blocks are read
    IN PLACE from the pool (``k_pool``/``v_pool``
    [num_blocks, H, block_size, D]); the contiguous logical view that
    ``paged_gather_kv`` materializes never exists.

    The block table is SCALAR-PREFETCHED (pltpu.PrefetchScalarGridSpec):
    the grid iterates (batch, head, logical-block) and the k/v index_maps
    read ``table[b, j]`` to aim each step's DMA at the right physical
    block — table indirection costs an index computation, not a gather.
    Masking is the paged contract: key position ``j <= q_pos`` attends;
    sentinel table entries (``>= num_blocks``) clamp onto garbage the
    mask excludes. Forward-only (decode never differentiates).

    ``interpret=None`` auto-selects: compiled on TPU, Pallas interpreter
    elsewhere (slow; tests pin numerics against the gather path). On TPU
    the query tile pads to the f32 sublane width (padded rows get
    ``q_pos = -1`` — attend nothing — and are sliced off)."""
    from ._tiling import pad_to_sublane, paged_attn_vmem_ok

    B, H, S, D = q.shape
    NB, _, bs, _ = k_pool.shape
    MB = block_table.shape[1]
    if interpret is None:
        interpret = not _on_tpu()
    if not paged_attn_vmem_ok(S, bs, D):
        raise ValueError(
            f"paged attention tile (S={S}, block_size={bs}, D={D}) "
            f"exceeds the VMEM budget; shrink block_size or head_dim"
        )
    Sp = S if interpret else pad_to_sublane(S)
    qp = q_pos.astype(jnp.int32)
    if Sp != S:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
        qp = jnp.pad(qp, ((0, 0), (0, Sp - S)), constant_values=-1)
    scale = sm_scale if sm_scale is not None else D**-0.5

    qspec = pl.BlockSpec((1, 1, Sp, D), lambda b, h, j, bt: (b, h, 0, 0))
    kvspec = pl.BlockSpec(
        (1, 1, bs, D),
        lambda b, h, j, bt: (jnp.minimum(bt[b, j], NB - 1), h, 0, 0),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H, MB),
        in_specs=[
            qspec,
            pl.BlockSpec((1, Sp), lambda b, h, j, bt: (b, 0)),
            kvspec,
            kvspec,
        ],
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((Sp, D), jnp.float32),
            pltpu.VMEM((Sp, LANES), jnp.float32),
            pltpu.VMEM((Sp, LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_fwd_kernel, sm_scale=scale, block_size=bs
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Sp, D), q.dtype),
        interpret=interpret,
        name="paged_attention_fwd",
    )(block_table.astype(jnp.int32), q, qp, k_pool, v_pool)
    return out[:, :, :S]


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """FlashAttention on TPU via Pallas. Same contract as
    ops.attention.attention_reference: q [B,H,Sq,D], k/v [B,H,Sk,D],
    kv_mask [B,Sk] bool (True = attend), returns [B,H,Sq,D] in q.dtype.
    Differentiable (custom VJP with Pallas backward kernels).

    ``interpret=None`` auto-selects: compiled on TPU, Pallas interpreter
    elsewhere (slow; tests only). Sequence lengths must be multiples of the
    block sizes (callers pad + pass kv_mask; models/transformer.py does)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    # env-tunable so on-chip sessions can sweep tile sizes without a code
    # change (DTF_FLASH_BLOCK_Q/K); 128x128 is the safe default, larger K
    # tiles cut grid overhead at long seq once measured. The env knobs are
    # process-global and read at TRACE time, so a sweep value tuned for the
    # bench shape must not break other call sites (e.g. Sq=384 under a
    # 256 block): an env block that doesn't divide falls back to the 128
    # default with a warning instead of raising — only an EXPLICIT
    # block_q/block_k argument keeps the hard divisibility error.
    import os

    from_env_q = block_q is None and "DTF_FLASH_BLOCK_Q" in os.environ
    from_env_k = block_k is None and "DTF_FLASH_BLOCK_K" in os.environ
    if block_q is None:
        block_q = int(os.environ.get("DTF_FLASH_BLOCK_Q", "128"))
    if block_k is None:
        block_k = int(os.environ.get("DTF_FLASH_BLOCK_K", "128"))
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    # fall back only when the env var was actually set AND the 128
    # default would work — otherwise let the hard error below name the
    # real problem (an unpadded sequence)
    if from_env_q and Sq % block_q and Sq % min(128, Sq) == 0:
        import warnings

        warnings.warn(
            f"DTF_FLASH_BLOCK_Q={block_q} does not divide Sq={Sq}; "
            f"falling back to 128 for this call site")
        block_q = min(128, Sq)
    if from_env_k and Sk % block_k and Sk % min(128, Sk) == 0:
        import warnings

        warnings.warn(
            f"DTF_FLASH_BLOCK_K={block_k} does not divide Sk={Sk}; "
            f"falling back to 128 for this call site")
        block_k = min(128, Sk)
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"seq lens ({Sq=}, {Sk=}) must be multiples of block sizes "
            f"({block_q=}, {block_k=}); pad and pass kv_mask"
        )
    if interpret is None:
        interpret = not _on_tpu()
    if not interpret:
        # Mosaic lane/sublane layout constraints (the interpreter has none):
        # the kv-mask block's lane dim is block_k, the q tile's sublane dim
        # is block_q. Sub-128 kv blocks would also waste the 128×128 MXU.
        if block_k % LANES and block_k != Sk:
            raise ValueError(
                f"on TPU, block_k ({block_k}) must be a multiple of {LANES} "
                f"or equal to Sk ({Sk})"
            )
        if block_q % STAT_DIM and block_q != Sq:
            raise ValueError(
                f"on TPU, block_q ({block_q}) must be a multiple of "
                f"{STAT_DIM} or equal to Sq ({Sq})"
            )
    if kv_mask is None:
        kv_mask = jnp.ones((B, 1, Sk), jnp.int32)
    else:
        # bool refs are awkward on TPU; [B,1,Sk] keeps the block 3-D with a
        # full-size middle dim (TPU tiling wants the 2nd-to-last dim full)
        kv_mask = kv_mask.astype(jnp.int32)[:, None, :]
    scale = sm_scale if sm_scale is not None else D**-0.5
    # causal alignment: last query attends the last key (self-attn; also
    # right for decode where Sq < Sk). Traced per-device offsets (sequence
    # parallelism) cannot be a static kernel param — those paths use the
    # dense position-aware fallback in parallel/ring_attention.py.
    q_offset = Sk - Sq
    return _flash(q, k, v, kv_mask, scale, causal, block_q, block_k,
                  interpret, q_offset)
