"""Sharded embedding lookup — the TPU-native embedding-parallel data plane.

Reference analog (SURVEY.md §2c 'Embedding parallel'): the Wide&Deep config
(BASELINE.json:11) kept embedding tables as sparse variables on parameter
servers; workers issued sparse gather RPCs and pushed `IndexedSlices`
gradients back through `SparseConditionalAccumulator`
($TF/python/ops/data_flow_ops.py:1478, sync path
sync_replicas_optimizer.py:286-291). The substrate's TPU answer is
`TPUEmbedding` ($TF/python/tpu/tpu_embedding_v2.py:76) backed by native
sparse cores.

TPU-native design here: tables are **mod-sharded over the ``model`` mesh
axis** (row r lives on shard ``r % n`` — mod, not contiguous range, so hot
ids spread across shards), and the lookup exchange is explicit collectives
under ``shard_map``:

- ``mod_sharded_lookup`` — ids replicated across the axis (the usual case:
  batch is sharded over data/fsdp, tables over model). Each shard gathers
  the rows it owns, zero-fills the rest, and one ``psum`` assembles full
  embeddings. The backward pass is the transpose — scatter-add into the
  local shard — which is exactly the PS sparse-gradient push, minus the RPC.
- ``batch_sharded_lookup`` — ids *sharded* over the same axis (embedding-
  parallel recommenders where the batch rides the model axis). Ids are
  all-gathered, contributions computed locally, and a ``reduce_scatter``
  returns each device only its batch slice — the same wire bytes as the
  all_to_all exchange of TPUEmbedding, with static shapes XLA can schedule.

Both are pure jnp + lax collectives: differentiable (JAX transposes
gather→scatter-add and psum→identity automatically), jittable, and
mesh-agnostic (axis size 1 degrades to a plain take).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from ..utils.compat import axis_size, shard_map

from ..parallel import mesh as mesh_lib
from ..parallel import sharding as sharding_lib


def shard_vocab(vocab_size: int, n_shards: int) -> int:
    """Rows per shard: tables are padded so every shard holds the same
    count (static shapes — SPMD programs must be shape-identical)."""
    return -(-vocab_size // n_shards)


def local_rows(table: jax.Array, shard: jax.Array, n_shards: int) -> jax.Array:
    """The mod-shard view of a replicated [V, D] table: rows
    ``shard, shard + n, shard + 2n, …`` padded to shard_vocab rows.
    Test/oracle helper; in training the table is born sharded."""
    v, d = table.shape
    rows = shard_vocab(v, n_shards)
    idx = shard + n_shards * jnp.arange(rows)
    return jnp.where(
        (idx < v)[:, None], jnp.take(table, jnp.minimum(idx, v - 1), axis=0), 0.0
    )


def _owned_lookup(ids: jax.Array, local_table: jax.Array, shard, n: int):
    """Gather rows this shard owns; zeros elsewhere. ids: any int shape."""
    owner = ids % n
    row = ids // n
    mine = (owner == shard)[..., None]
    safe = jnp.minimum(row, local_table.shape[0] - 1)
    return jnp.where(mine, jnp.take(local_table, safe, axis=0), 0.0)


def mod_sharded_lookup(
    ids: jax.Array,
    local_table: jax.Array,
    axis: str = mesh_lib.MODEL,
) -> jax.Array:
    """Inside ``shard_map``: full [*, D] embeddings from a mod-sharded table.

    ids are replicated over ``axis``; ``local_table`` is this device's
    [ceil(V/n), D] shard. One psum over ``axis`` replaces the reference's
    PS gather round-trip (§3.1: variable read = gRPC hop per step).
    """
    n = axis_size(axis)
    part = _owned_lookup(ids, local_table, lax.axis_index(axis), n)
    return lax.psum(part, axis)


def range_sharded_lookup(
    ids: jax.Array,
    local_table: jax.Array,
    axis: str = mesh_lib.MODEL,
) -> jax.Array:
    """Inside ``shard_map``: like ``mod_sharded_lookup`` but for
    *range*-sharded tables — shard s owns ids [s·rows, (s+1)·rows), which is
    exactly the layout GSPMD gives a param annotated P(axis, None). Lets a
    plain flax table param feed the explicit exchange with zero re-layout."""
    rows = local_table.shape[0]
    shard = lax.axis_index(axis)
    owner = ids // rows
    row = ids % rows
    mine = (owner == shard)[..., None]
    part = jnp.where(mine, jnp.take(local_table, row, axis=0), 0.0)
    return lax.psum(part, axis)


def batch_sharded_lookup(
    ids: jax.Array,
    local_table: jax.Array,
    axis: str = mesh_lib.MODEL,
) -> jax.Array:
    """Inside ``shard_map``: lookup where the *batch* (dim 0 of ids) is also
    sharded over ``axis``. all_gather ids → local contributions →
    reduce_scatter back to the caller's batch slice. Wire-equivalent to the
    TPUEmbedding all_to_all exchange, static-shaped."""
    n = axis_size(axis)
    all_ids = lax.all_gather(ids, axis, axis=0, tiled=True)
    part = _owned_lookup(all_ids, local_table, lax.axis_index(axis), n)
    return lax.psum_scatter(part, axis, scatter_dimension=0, tiled=True)


def make_sharded_lookup(mesh: Mesh, axis: str = mesh_lib.MODEL):
    """jit-ready f(ids, table_shards) -> embeddings over ``mesh``.

    ``table_shards`` is the [n * ceil(V/n), D] global array whose dim 0 is
    sharded over ``axis`` (shard i holds rows it owns under mod-sharding,
    i.e. the array is the concatenation of ``local_rows`` views). Batch dims
    of ``ids`` ride (data, fsdp) as usual.
    """
    bspec = P(mesh_lib.BATCH_AXES)
    out_spec = P(mesh_lib.BATCH_AXES, None)

    def fn(ids, table_shards):
        return shard_map(
            lambda i, t: mod_sharded_lookup(i, t, axis),
            mesh=mesh,
            in_specs=(bspec, P(axis, None)),
            out_specs=out_spec,
            check_vma=False,
        )(ids, table_shards)

    return fn


def make_range_sharded_lookup(mesh: Mesh, axis: str = mesh_lib.MODEL):
    """jit-ready f(ids, table) for a plain [V, D] table laid out
    P(axis, None) — the GSPMD-layout twin of ``make_sharded_lookup``. Owns
    the pad-to-divisible step so callers hand in the raw param."""
    bspec = P(mesh_lib.BATCH_AXES)
    out_spec = P(mesh_lib.BATCH_AXES, None)

    def fn(ids, table):
        n = mesh.shape[axis]
        rows = shard_vocab(table.shape[0], n)
        padded = jnp.pad(table, ((0, n * rows - table.shape[0]), (0, 0)))
        return shard_map(
            lambda i, t: range_sharded_lookup(i, t, axis),
            mesh=mesh,
            in_specs=(bspec, P(axis, None)),
            out_specs=out_spec,
            check_vma=False,
        )(ids, padded)

    return fn


def to_mod_sharded(table: jax.Array, mesh: Mesh, axis: str = mesh_lib.MODEL):
    """Re-layout a replicated [V, D] table into the mod-sharded global array
    expected by ``make_sharded_lookup`` (dim 0 = n shards × rows-per-shard),
    placed with dim 0 over ``axis`` (through the sharding seam — no
    ad-hoc NamedSharding here)."""
    n = mesh.shape[axis]
    shards = [local_rows(table, s, n) for s in range(n)]
    global_ = jnp.concatenate(shards, axis=0)
    return sharding_lib.shard_leading_dim(global_, mesh, axis)
