"""Mixture-of-Experts layer — expert parallelism over the ``expert`` mesh axis.

The reference has no MoE (SURVEY.md §2c marks EP as a new-framework
capability on the same collective substrate as Ulysses: `lax.all_to_all`
token dispatch over an `expert` mesh axis). TPU-first design:

- **Dispatch by einsum, not gather**: tokens are routed with one-hot
  dispatch/combine tensors contracted by einsums (the Mesh-TensorFlow /
  Switch-Transformer pattern). Static shapes — capacity-bounded expert
  buffers — so XLA can tile the expert FFNs on the MXU, and with the expert
  dimension sharded over the ``expert`` axis GSPMD lowers the dispatch
  einsum to exactly the all_to_all exchange of a hand-written EP backend.
- **Capacity + drop**: each expert processes at most
  ``ceil(top_k · T · capacity_factor / E)`` tokens per batch; overflow
  tokens are dropped (residual connection carries them) — lockstep SPMD
  needs shape-static buffers, the TPU analog of the reference's unbounded
  PS queues.
- **Router in f32**: routing logits/softmax stay f32 (bf16 elsewhere), the
  same precision split as attention softmax.
- **Load-balance aux loss** (Switch §2.2): E · Σ_e f_e · p̄_e, sown into
  the ``losses`` collection so loss adapters can pick it up without
  threading it through every return value.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    d_model: int = 512
    d_ff: int = 2048
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    dtype: str = "bfloat16"
    # Tokens are routed within fixed-size groups (the Mesh-TF/Switch group
    # dimension): capacity is per-group, so dispatch/combine memory is
    # O(T·group) instead of O(T²). 0 = auto (largest divisor of T ≤ 1024).
    group_size: int = 0
    # "einsum": one-hot dispatch/combine contractions (Mesh-TF/Switch) —
    #   MXU-dense, and GSPMD lowers the sharded-E einsum to the EP
    #   all_to_all; FLOPs O(G²·top_k·cf·D) per group.
    # "scatter": position-indexed scatter/gather into the expert buffers —
    #   FLOPs/memory linear in G (the sorted-dispatch style every
    #   large-scale MoE eventually needs); same routing, same drops.
    dispatch_impl: str = "einsum"


def moe_rules() -> list[tuple[str, P]]:
    """Path rules: expert dim over `expert`, FFN hidden dim over `model`
    (EP × TP compose); router stays replicated.

    Patterns anchor on the parameter *leaf* names (``w_in``/``b_in``/
    ``w_out``/``b_out``), so the rules match wherever the module is
    mounted — bare, or under any parent scope — instead of silently
    returning replicated specs when the parent isn't literally called
    'moe' (round-1 advisor finding). CAVEAT: these leaf names are not
    globally unique — do NOT apply moe_rules to a tree whose dense FFN
    weights use the same leaf names (2-D) — the 3-axis expert spec would
    mis-rank onto them. In-tree models either use flax ``mlp_in/mlp_out``
    names or build their specs directly, so there is no live collision."""
    return [
        (r"(^|/)w_in$", P(mesh_lib.EXPERT, None, mesh_lib.MODEL)),
        (r"(^|/)b_in$", P(mesh_lib.EXPERT, mesh_lib.MODEL)),
        (r"(^|/)w_out$", P(mesh_lib.EXPERT, mesh_lib.MODEL, None)),
        (r"(^|/)b_out$", P(mesh_lib.EXPERT, None)),
    ]


def expert_capacity(num_tokens: int, cfg: MoEConfig) -> int:
    return max(
        1,
        -(-int(cfg.top_k * num_tokens * cfg.capacity_factor) // cfg.num_experts),
    )


def resolve_group_size(num_tokens: int, cfg: MoEConfig) -> int:
    """Routing-group size: must divide T. Auto = largest divisor ≤ 1024."""
    if cfg.group_size > 0:
        if num_tokens % cfg.group_size != 0:
            raise ValueError(
                f"group_size={cfg.group_size} must divide tokens={num_tokens}"
            )
        return cfg.group_size
    g = min(num_tokens, 1024)
    while num_tokens % g != 0:
        g -= 1
    return g


def _greedy_slots(probs: jax.Array, capacity: int, top_k: int):
    """Shared routing decision for both dispatch impls. probs [T, E] →
    per-slot arrays (choice [k,T] int, pos [k,T] int, keep [k,T] bool,
    gate [k,T] f32) and the aux loss. Greedy per-slot: slot j sends each
    token to its j-th choice expert if that expert still has capacity
    (position = running count of tokens already routed there, across
    slots — so (expert, position) pairs are unique across ALL slots)."""
    T, E = probs.shape
    remaining = probs
    fill = jnp.zeros((E,), jnp.int32)  # tokens assigned per expert so far
    choices, positions, keeps, gates = [], [], [], []
    for _ in range(top_k):
        choice = jnp.argmax(remaining, axis=-1)  # [T]
        onehot = jax.nn.one_hot(choice, E, dtype=probs.dtype)  # [T, E]
        # position of each token in its chosen expert's buffer
        pos = fill[None, :] + (jnp.cumsum(onehot, axis=0) - onehot).astype(
            jnp.int32
        )
        my_pos = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [T]
        keep = my_pos < capacity
        gate = jnp.sum(probs * onehot, axis=-1)  # [T]
        choices.append(choice); positions.append(my_pos)
        keeps.append(keep); gates.append(gate)
        kept_oh = onehot * keep[:, None].astype(probs.dtype)
        fill = fill + jnp.sum(kept_oh, axis=0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)
    choice = jnp.stack(choices); pos = jnp.stack(positions)
    keep = jnp.stack(keeps); gate = jnp.stack(gates)
    if top_k > 1:
        # renormalize gates over the KEPT choices (top-k gates sum to 1)
        denom = jnp.sum(gate * keep, axis=0, keepdims=True)
        gate = gate / jnp.maximum(denom, 1e-9)
    # top_k == 1 keeps the RAW gate probability (Switch Transformer §2.1):
    # renormalizing would make the gate exactly 1.0 and cut the router off
    # from the main-loss gradient (round-1 advisor finding).
    # Switch load-balance loss on first-choice statistics
    first = jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=probs.dtype)
    aux = E * jnp.sum(first.mean(axis=0) * probs.mean(axis=0))
    return choice, pos, keep, gate, aux


def top_k_routing(probs: jax.Array, capacity: int, top_k: int):
    """probs [T, E] → (dispatch [T, E, C] 0/1, combine [T, E, C] weights,
    aux_loss scalar) — the one-hot ("einsum") form of :func:`_greedy_slots`."""
    T, E = probs.shape
    choice, pos, keep, gate, aux = _greedy_slots(probs, capacity, top_k)
    dispatch = jnp.zeros((T, E, capacity), probs.dtype)
    combine = jnp.zeros((T, E, capacity), probs.dtype)
    for j in range(top_k):
        d = (
            jax.nn.one_hot(choice[j], E, dtype=probs.dtype)[:, :, None]
            * jax.nn.one_hot(pos[j], capacity, dtype=probs.dtype)[:, None, :]
            * keep[j][:, None, None].astype(probs.dtype)
        )
        dispatch = dispatch + d
        combine = combine + gate[j][:, None, None] * d
    return dispatch, combine, aux


def _scatter_expert_ffn(tokens, probs, capacity, top_k, apply_ffn, dtype):
    """Linear-memory dispatch: scatter tokens into [E*C, D] expert buffers
    at their (expert, position) slot, run the FFN, gather back weighted by
    the gates. (expert, position) uniqueness across slots (see
    _greedy_slots) makes the scatter collision-free; dropped tokens target
    a sentinel row that is sliced off."""
    T, D = tokens.shape
    E = probs.shape[-1]
    choice, pos, keep, gate, aux = _greedy_slots(probs, capacity, top_k)
    flat_idx = jnp.where(keep, choice * capacity + pos, E * capacity)  # [k,T]
    buf = jnp.zeros((E * capacity + 1, D), dtype)
    for j in range(top_k):
        buf = buf.at[flat_idx[j]].add(tokens)
    expert_in = buf[:-1].reshape(E, capacity, D)
    out = apply_ffn(expert_in)  # [E, C, D]
    out_flat = jnp.concatenate(
        [out.reshape(E * capacity, D), jnp.zeros((1, D), out.dtype)], axis=0
    )
    y = jnp.zeros((T, D), dtype)
    for j in range(top_k):
        y = y + out_flat[flat_idx[j]] * (
            gate[j] * keep[j].astype(gate.dtype)
        )[:, None].astype(dtype)
    return y, aux


class MoEMLP(nn.Module):
    """Drop-in replacement for a transformer FFN block: [B, S, D] → [B, S, D].

    Expert weights live as [E, ...] arrays; `moe_rules()` shards the E dim
    over the `expert` mesh axis, so the dispatch/combine einsums become
    all_to_all exchanges under GSPMD."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        B, S, D = x.shape
        assert D == cfg.d_model, (D, cfg.d_model)
        T = B * S
        tokens = x.reshape(T, D)

        logits = nn.Dense(
            cfg.num_experts, dtype=jnp.float32, name="router",
            kernel_init=nn.initializers.normal(0.02),
        )(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        # group the token axis: capacity (and the [g, E, C] one-hots) are
        # per-group, so memory is linear in T, not quadratic
        G = resolve_group_size(T, cfg)
        n_groups = T // G
        probs_g = probs.reshape(n_groups, G, cfg.num_experts)
        C = expert_capacity(G, cfg)

        w_in = self.param(
            "w_in", nn.initializers.normal(0.02),
            (cfg.num_experts, D, cfg.d_ff), jnp.float32,
        )
        b_in = self.param(
            "b_in", nn.initializers.zeros, (cfg.num_experts, cfg.d_ff),
            jnp.float32,
        )
        w_out = self.param(
            "w_out", nn.initializers.normal(0.02),
            (cfg.num_experts, cfg.d_ff, D), jnp.float32,
        )
        b_out = self.param(
            "b_out", nn.initializers.zeros, (cfg.num_experts, D), jnp.float32,
        )
        tokens_g = tokens.reshape(n_groups, G, D).astype(dtype)

        if cfg.dispatch_impl == "einsum":
            dispatch, combine, aux = jax.vmap(
                lambda p: top_k_routing(p, C, cfg.top_k)
            )(probs_g)  # [n, G, E, C] ×2, aux [n]
            aux = aux.mean()
            # dispatch: [n,G,E,C] × [n,G,D] → expert buffers [n,E,C,D]
            expert_in = jnp.einsum("ngec,ngd->necd", dispatch.astype(dtype),
                                   tokens_g)
            h = jnp.einsum("necd,edf->necf", expert_in, w_in.astype(dtype))
            h = nn.gelu(h + b_in[None, :, None, :].astype(dtype))
            out = jnp.einsum("necf,efd->necd", h, w_out.astype(dtype))
            out = out + b_out[None, :, None, :].astype(dtype)
            # combine: [n,G,E,C] × [n,E,C,D] → [n,G,D]; dropped → zeros
            y = jnp.einsum("ngec,necd->ngd", combine.astype(dtype), out)
        elif cfg.dispatch_impl == "scatter":

            def ffn(expert_in):  # [E, C, D] → [E, C, D]
                h = jnp.einsum("ecd,edf->ecf", expert_in, w_in.astype(dtype))
                h = nn.gelu(h + b_in[:, None, :].astype(dtype))
                out = jnp.einsum("ecf,efd->ecd", h, w_out.astype(dtype))
                return out + b_out[:, None, :].astype(dtype)

            y, aux_g = jax.vmap(
                lambda t, p: _scatter_expert_ffn(
                    t, p, C, cfg.top_k, ffn, dtype
                )
            )(tokens_g, probs_g)
            aux = aux_g.mean()
        else:
            raise ValueError(f"Unknown dispatch_impl {cfg.dispatch_impl!r}")

        self.sow(
            "losses", "moe_aux", cfg.router_aux_weight * aux,
            init_fn=lambda: jnp.zeros((), jnp.float32),
            reduce_fn=lambda a, b: a + b,
        )
        return y.reshape(B, S, D)


def collect_aux_loss(variables: Any) -> jax.Array:
    """Sum every sown `losses` entry (zero if none) — call on the mutated
    collections returned by ``model.apply(..., mutable=['losses'])``."""
    losses = variables.get("losses", {}) if isinstance(variables, dict) else {}
    leaves = jax.tree.leaves(losses)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(l) for l in leaves)


def flops_per_token(cfg: MoEConfig) -> float:
    """Fwd FLOPs per token: top_k experts' FFN matmuls (router negligible)."""
    return cfg.top_k * 2.0 * 2.0 * cfg.d_model * cfg.d_ff
