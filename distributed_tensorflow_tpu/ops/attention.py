"""Attention: O(S²) reference and O(block) blockwise (online-softmax) forms.

Layout convention for this module: ``[batch, heads, seq, head_dim]``
(blocking over ``seq`` puts the two innermost dims — seq-block × head_dim —
onto the TPU's (sublane × lane) tiles; models transpose once at the
attention boundary).

``attention_reference`` is the numerics oracle. ``blockwise_attention`` is
the memory-efficient pure-JAX form (FlashAttention recurrence as a
``lax.scan`` over KV blocks) — it is the inner loop of ring attention
(parallel/ring_attention.py), the CPU fallback for the Pallas kernel
(ops/flash_attention.py), and fully differentiable by autodiff.

The reference framework has no analog — its attention-era models predate it
(SURVEY.md §5.7 "Reference: entirely absent"); this is new-framework
capability required first-class by the task spec.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax NaN-free


def _scale(q, sm_scale):
    return sm_scale if sm_scale is not None else q.shape[-1] ** -0.5


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,
    sm_scale: float | None = None,
) -> jax.Array:
    """Plain softmax(QKᵀ)V in f32. Shapes: q [B,H,Sq,D], k/v [B,H,Sk,D],
    kv_mask [B,Sk] bool (True = attend). Returns [B,H,Sq,D] in q.dtype."""
    Sq, Sk = q.shape[2], k.shape[2]
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * _scale(q, sm_scale)
    if kv_mask is not None:
        logits = jnp.where(kv_mask[:, None, None, :], logits, NEG_INF)
    if causal:
        qi = jnp.arange(Sq)[:, None] + (Sk - Sq)  # supports Sq<Sk (decode)
        ki = jnp.arange(Sk)[None, :]
        logits = jnp.where((ki <= qi)[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", probs.astype(v.dtype), v
    ).astype(q.dtype)


def append_kv(
    buf: jax.Array, new: jax.Array, start: jax.Array
) -> jax.Array:
    """Write ``new`` [B,H,S,D] into the KV ring buffer ``buf`` [B,H,M,D]
    at per-sequence offsets ``start`` [B] (the continuous-batching write
    index: each slot in the decode batch is at a different position).
    The written positions are ``start[b] .. start[b]+S-1``; callers
    guarantee ``start[b]+S <= M`` (the scheduler's max-len eviction)."""
    return jax.vmap(
        lambda cb, nb, s: jax.lax.dynamic_update_slice_in_dim(
            cb, nb.astype(cb.dtype), s, axis=1
        )
    )(buf, new, start)


def paged_append_kv(
    pool: jax.Array,
    new: jax.Array,
    block_table: jax.Array,
    pos: jax.Array,
) -> jax.Array:
    """Scatter ``new`` [B,H,S,D] into the shared block pool
    [num_blocks, H, block_size, D] through a per-sequence block table
    [B, max_blocks] (logical block index → physical block id). Token
    ``(b, s)`` at absolute position ``p = pos[b, s]`` lands in physical
    block ``block_table[b, p // block_size]`` at offset
    ``p % block_size``.

    Out-of-range routing is the padding contract: a position past the
    table (``p // block_size >= max_blocks`` — the chunk-padding
    sentinel) or a table entry ``>= num_blocks`` (the idle-slot /
    unallocated sentinel) produces an out-of-bounds scatter index, and
    the scatter drops it — padded rows and idle slots write NOTHING,
    instead of corrupting a live block."""
    NB, H, bs, D = pool.shape
    B, _, S, _ = new.shape
    MB = block_table.shape[1]
    blk = pos // bs                                   # [B,S] logical block
    off = pos % bs
    bids = jnp.where(
        blk < MB,
        jnp.take_along_axis(block_table, jnp.clip(blk, 0, MB - 1), axis=1),
        NB,  # past-the-table positions route out of bounds -> dropped
    )
    flat_new = new.transpose(0, 2, 1, 3).reshape(B * S, H, D)
    return pool.at[bids.reshape(-1), :, off.reshape(-1), :].set(
        flat_new.astype(pool.dtype), mode="drop"
    )


def paged_gather_kv(pool: jax.Array, block_table: jax.Array) -> jax.Array:
    """Reassemble the contiguous logical K (or V) view from the block
    pool: ``[num_blocks, H, block_size, D]`` gathered through
    ``block_table`` [B, max_blocks] → ``[B, H, max_blocks*block_size,
    D]``, where logical position ``p`` of sequence ``b`` is
    ``pool[block_table[b, p // bs], :, p % bs]``. Sentinel entries
    (``>= num_blocks``, the unallocated tail) clamp to the last block
    and read stale garbage — exactly the positions above the write
    frontier that ``cached_attention``'s ``j <= q_pos`` mask excludes,
    so no zeroing and no validity bitmap are needed."""
    NB, H, bs, D = pool.shape
    B, MB = block_table.shape
    g = jnp.take(pool, jnp.clip(block_table, 0, NB - 1).reshape(-1), axis=0)
    return (
        g.reshape(B, MB, H, bs, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, H, MB * bs, D)
    )


def cached_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_pos: jax.Array,
    sm_scale: float | None = None,
) -> jax.Array:
    """Masked full attention over a KV cache — the decode/prefill form.

    ``q`` [B,H,S,D] are the current step's queries at ABSOLUTE positions
    ``q_pos`` [B,S] (prefill: 0..P-1; decode: the per-sequence write
    index, S=1); ``k``/``v`` [B,H,M,D] are the full cache buffers. Key
    slot ``j`` participates iff ``j <= q_pos`` — causality and
    valid-length masking in one predicate, because the cache is filled
    contiguously from 0, so every slot at or below the newest written
    position holds a real token and everything above is stale garbage.

    This is the fallback the flash kernel can't cover: Pallas flash
    attention wants Sq a block multiple and a monotone causal frontier,
    while decode is Sq=1 against M cached keys with per-sequence offsets.
    Dense f32 softmax(QKᵀ)V matches ``attention_reference`` numerics, so
    cached decode is bit-comparable to the uncached forward."""
    M = k.shape[2]
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * _scale(q, sm_scale)
    mask = jnp.arange(M)[None, None, :] <= q_pos[:, :, None]  # [B,S,M]
    logits = jnp.where(mask[:, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", probs.astype(v.dtype), v
    ).astype(q.dtype)


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_table: jax.Array,
    *,
    q_pos: jax.Array,
    sm_scale: float | None = None,
    impl: str = "auto",
) -> jax.Array:
    """Attention over the paged KV pool, selected by ``impl`` — the serve
    decode hot path's dispatch point (docs/serving.md "Fused paged
    attention").

    ``q`` [B,H,S,D] at absolute positions ``q_pos`` [B,S];
    ``k_pool``/``v_pool`` [num_blocks, H, block_size, D];
    ``block_table`` [B, max_blocks]. Key position ``j`` participates iff
    ``j <= q_pos`` — the same single-predicate masking as
    ``cached_attention`` (sentinel table entries clamp onto garbage the
    mask excludes, so no zeroing, no validity bitmap).

    - ``"gather"`` — the PR-13 path, ``paged_gather_kv`` then
      ``cached_attention``: materializes the [B,H,MB*bs,D] logical view
      TWICE per layer per step (k and v, each a pool gather plus a
      transpose copy). Exact-parity escape hatch.
    - ``"fused"`` — one pool gather per buffer, consumed in BLOCK layout
      [B,MB,H,bs,D] by the attention einsums directly: the transpose +
      reshape copies of the gather path never materialize. Pure jittable
      XLA; any backend.
    - ``"pallas"`` — the block-table-aware Pallas kernel
      (ops/flash_attention.paged_flash_attention): block ids are
      scalar-prefetched and each grid step DMAs one physical block from
      the pool in place — the logical view never exists in HBM at all.
      Compiled on TPU, interpreter elsewhere (tests only).
    - ``"auto"`` — ``"pallas"`` on TPU, ``"fused"`` elsewhere.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "fused"
    if impl == "gather":
        return cached_attention(
            q,
            paged_gather_kv(k_pool, block_table),
            paged_gather_kv(v_pool, block_table),
            q_pos=q_pos,
            sm_scale=sm_scale,
        )
    if impl == "pallas":
        from .flash_attention import paged_flash_attention

        return paged_flash_attention(
            q, k_pool, v_pool, block_table, q_pos=q_pos, sm_scale=sm_scale
        )
    if impl != "fused":
        raise ValueError(
            f"paged attention impl must be 'auto', 'gather', 'fused' or "
            f"'pallas', got {impl!r}"
        )
    NB, H, bs, D = k_pool.shape
    B, MB = block_table.shape
    S = q.shape[2]
    ids = jnp.clip(block_table, 0, NB - 1)
    kg = jnp.take(k_pool, ids.reshape(-1), axis=0).reshape(B, MB, H, bs, D)
    vg = jnp.take(v_pool, ids.reshape(-1), axis=0).reshape(B, MB, H, bs, D)
    logits = jnp.einsum(
        "bhsd,bmhkd->bhsmk", q, kg, preferred_element_type=jnp.float32
    ) * _scale(q, sm_scale)
    kpos = jnp.arange(MB * bs).reshape(MB, bs)
    mask = kpos[None, None, None] <= q_pos[:, None, :, None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(
        logits.reshape(B, H, S, MB * bs), axis=-1
    ).reshape(logits.shape)
    out = jnp.einsum("bhsmk,bmhkd->bhsd", probs.astype(vg.dtype), vg)
    return out.astype(q.dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    kv_mask: jax.Array | None = None,
    sm_scale: float | None = None,
    block_k: int = 512,
) -> jax.Array:
    """Online-softmax attention scanned over KV blocks — O(Sq·block_k)
    activation memory instead of O(Sq·Sk).

    The recurrence (running max m, running denominator l, rescaled
    accumulator acc) is the same one the Pallas kernel implements on-chip
    and ring attention runs across chips; here it is a ``lax.scan`` that XLA
    compiles directly, so it runs on any backend and differentiates via
    autodiff (each block is rematerialized in the backward pass by the scan).
    """
    B, H, Sq, D = q.shape
    orig_sk = k.shape[2]
    scale = _scale(q, sm_scale)
    block_k = min(block_k, orig_sk)
    if orig_sk % block_k != 0:
        # pad keys to a block multiple; padded positions are masked out
        pad = block_k - orig_sk % block_k
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        base = jnp.arange(orig_sk + pad) < orig_sk
        kv_mask = (
            jnp.pad(kv_mask, ((0, 0), (0, pad))) & base[None]
            if kv_mask is not None
            else jnp.broadcast_to(base[None], (B, orig_sk + pad))
        )
    Sk = k.shape[2]
    n_blocks = Sk // block_k

    kb = jnp.moveaxis(k.reshape(B, H, n_blocks, block_k, D), 2, 0)
    vb = jnp.moveaxis(v.reshape(B, H, n_blocks, block_k, D), 2, 0)
    mb = (
        jnp.moveaxis(kv_mask.reshape(B, n_blocks, block_k), 1, 0)
        if kv_mask is not None
        else jnp.ones((n_blocks, 1, block_k), bool)
    )

    q32 = q.astype(jnp.float32)
    # causal offset aligns the last query with the last ORIGINAL key
    qpos = jnp.arange(Sq)[:, None] + (orig_sk - Sq)

    def body(carry, xs):
        acc, m, l = carry
        k_j, v_j, mask_j, j = xs
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", q32, k_j.astype(jnp.float32)
        ) * scale  # [B,H,Sq,block_k]
        mask = jnp.broadcast_to(mask_j[:, None, None, :], logits.shape)
        if causal:
            kpos = j * block_k + jnp.arange(block_k)[None, :]
            mask = mask & jnp.broadcast_to(
                (kpos <= qpos)[None, None], logits.shape
            )
        logits = jnp.where(mask, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1))
        # explicit zero under the mask: for fully-masked rows m stays
        # NEG_INF and exp(NEG_INF - NEG_INF) would be 1, poisoning l
        p = jnp.where(mask, jnp.exp(logits - m_new[..., None]), 0.0)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_j.astype(jnp.float32)
        )
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0), (kb, vb, mb, jnp.arange(n_blocks))
    )

    # l == 0 only when every key is masked for that query; emit zeros.
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)
