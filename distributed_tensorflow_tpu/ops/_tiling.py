"""Shared VMEM-budget tile selection for the fused Pallas matmul kernels
(ops/fused_conv_bn.py, ops/fused_ln_matmul.py)."""

from __future__ import annotations

import jax

VMEM_BUDGET = 10 * 1024 * 1024  # leave headroom under ~16 MB/core


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def pick_block_m(M: int, k: int, n: int, *, name: str) -> int:
    """Largest 8-aligned divisor of M whose [bm, k]/[bm, n] streaming
    tiles fit the budget; a single whole-M block for tiny/odd M. A
    block's sublane dim must be 8-aligned unless it covers the whole dim
    (then Mosaic pads the array edge itself)."""
    fits = lambda bm: (
        2 * bm * (2 * k + 2 * n) + 4 * bm * (k + n) <= VMEM_BUDGET
    )  # 2 buffers on the streamed operands + one f32 temp each
    for bm in range(min(M, 1024) // 8 * 8, 7, -8):
        if M % bm == 0 and fits(bm):
            return bm
    if fits(M):
        return M
    raise ValueError(
        f"{name}: M={M} has no 8-aligned tile under the VMEM budget for "
        f"k={k}, n={n}; make the row count divisible by a multiple of 8"
    )


def pick_block_n(k: int, n: int, *, name: str) -> int:
    """Output-column tile for the dw kernels: the [k, bn] f32 accumulator
    stays resident, so k*bn*4 is capped. bn must divide n and be
    lane-aligned (multiple of 128, or the whole dim)."""
    for bn in (n, *range(2048, 127, -128)):
        if bn > n or n % bn:
            continue
        if k * bn * 4 <= 4 * 1024 * 1024:
            return bn
    raise ValueError(
        f"{name}: n={n} has no lane-aligned tile whose [k={k}, bn] f32 "
        "accumulator fits VMEM; pad n to a multiple of 128"
    )
