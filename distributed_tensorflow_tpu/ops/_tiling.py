"""Shared VMEM-budget tile selection for the fused Pallas matmul kernels
(ops/fused_conv_bn.py, ops/fused_ln_matmul.py)."""

from __future__ import annotations

import jax

VMEM_BUDGET = 10 * 1024 * 1024  # leave headroom under ~16 MB/core


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def pad_to_sublane(n: int, sublane: int = 8) -> int:
    """Round a row count up to the f32 sublane width — the paged decode
    kernel pads its tiny query tile (S = 1, or k+1 under speculation) so
    the VMEM scratch is tile-aligned on real TPU; the padded rows carry
    ``q_pos = -1`` (attend nothing) and are sliced off."""
    return -(-n // sublane) * sublane


def paged_attn_vmem_ok(S: int, block_size: int, D: int,
                       *, lanes: int = 128) -> bool:
    """True when the paged-attention kernel's per-instance VMEM footprint
    (resident q/o/acc [S, D] tiles, m/l row stats [S, lanes], one
    double-buffered [block_size, D] k/v block pair) fits the shared
    budget. Decode shapes are tiny (S ≤ 8, D ≤ 256), so this is a
    tripwire against pathological configs, not a tile picker."""
    resident = 3 * S * D * 4 + 2 * S * lanes * 4
    stream = 2 * 2 * block_size * D * 4
    return resident + stream <= VMEM_BUDGET


def pick_block_m(M: int, k: int, n: int, *, name: str) -> int:
    """Largest 8-aligned divisor of M whose [bm, k]/[bm, n] streaming
    tiles fit the budget; a single whole-M block for tiny/odd M. A
    block's sublane dim must be 8-aligned unless it covers the whole dim
    (then Mosaic pads the array edge itself)."""
    fits = lambda bm: (
        2 * bm * (2 * k + 2 * n) + 4 * bm * (k + n) <= VMEM_BUDGET
    )  # 2 buffers on the streamed operands + one f32 temp each
    for bm in range(min(M, 1024) // 8 * 8, 7, -8):
        if M % bm == 0 and fits(bm):
            return bm
    if fits(M):
        return M
    raise ValueError(
        f"{name}: M={M} has no 8-aligned tile under the VMEM budget for "
        f"k={k}, n={n}; make the row count divisible by a multiple of 8"
    )


def _aligned_divisors(M: int, cap: int = 1024) -> list[int]:
    """8-aligned divisors of M up to ``cap`` (descending), with M itself
    as the fallback when no aligned divisor exists (Mosaic then pads the
    array edge)."""
    out = [bm for bm in range(min(M, cap) // 8 * 8, 7, -8) if M % bm == 0]
    return out or [M]


def pick_dw_tiles(M: int, cin: int, cout: int, *, in_bytes: int,
                  emit_stats: bool, name: str) -> tuple[int, int]:
    """Joint (bm, bn) for the dw kernels, with FULL per-tile VMEM
    accounting — the round-2 pickers modelled only the streamed operands
    and sized the accumulator separately, which let the bench-shape
    [12544, 512] x [12544, 2048] dw kernel allocate a 17.9 MB scoped
    stack (> the 16 MB core limit) even though each term individually
    "fit" (caught on-chip, round 3; the validator now compiles the real
    bench shapes so this class of miss cannot pass again).

    Model per (bm, bn) tile:
      - streamed, double-buffered: x [bm, cin]; y and dy [bm, bn] (y is
        streamed regardless of emit_stats — the BlockSpec always maps it)
      - resident accumulator, double-buffered across the outer-j switch:
        dw [cin, bn] f32, plus the dot-product f32 temp of the same shape
      - f32 stack scratch Mosaic materializes: g (and y when emit_stats)
        [bm, bn]; the prologue x [bm, cin] + its in-dtype cast

    Preference order: largest bn first (each bn-tile re-streams the whole
    x, so fewer column tiles = less HBM traffic), then largest bm; bm is
    kept >= 128 where possible so the row-contraction feeds the MXU full
    tiles."""
    budget = 13 * 1024 * 1024  # ~3 MB slack under the 16 MB scoped limit

    def tile_bytes(bm: int, bn: int) -> int:
        stream = 2 * (bm * cin * in_bytes + 2 * bm * bn * in_bytes)
        acc = 3 * cin * bn * 4
        scratch = ((2 if emit_stats else 1) * bm * bn * 4
                   + bm * cin * 4 + bm * cin * in_bytes)
        return stream + acc + scratch

    bms = _aligned_divisors(M)
    bns = [bn for bn in (cout, *range(2048, 127, -128))
           if bn <= cout and cout % bn == 0]
    for prefer_wide_bm in (True, False):
        for bn in bns:
            for bm in bms:
                if prefer_wide_bm and bm < min(128, M):
                    continue
                if tile_bytes(bm, bn) <= budget:
                    return bm, bn
    if len(bms) == 1 and bms[0] == M and M % 8 != 0:
        dim_hint = f"M={M} has no 8-aligned divisor <= 1024"
    elif 3 * cin * 128 * 4 > budget:
        # even the narrowest lane-aligned bn can't fit the [cin, bn]
        # f32 accumulator — the problem is cin, not cout
        dim_hint = f"cin={cin} is too wide for a resident f32 accumulator"
    else:
        dim_hint = f"cout={cout} may need padding to a multiple of 128"
    raise ValueError(
        f"{name}: no (bm, bn) tile for M={M}, cin={cin}, cout={cout} "
        f"fits the VMEM budget ({dim_hint})"
    )


def pick_single_pass_bm(M: int, cin: int, cout: int, *, in_bytes: int,
                        emit_stats: bool) -> int | None:
    """Row tile for the SINGLE-PASS backward kernel (dx + dscale/dshift +
    dw in one sweep over x/y/dy), or None when the shape cannot fit.

    Motivation (round-3 on-chip): the two-pass Pallas backward streams
    x/y/dy twice and measured 0.40-0.87x of XLA's fused backward; one
    pass streams them once — structurally less HBM traffic than either.
    The catch is VMEM: the whole [cin, cout] f32 dw accumulator (plus
    its dot-product temp and the w operand) must stay resident alongside
    the streamed tiles, so this only works for the narrower layer
    shapes; which shapes qualify depends on dtype — in bf16 most
    batch-256 ResNet-50 1x1s fit, in f32 the widest (512<->2048) do not.
    This function IS the authority; never assume per-shape behavior
    without calling it. Returns the largest 8-aligned bm >= 64 that fits
    a conservative model; None means "use the two-pass kernels".

    Model per tile: double-buffered streams (x, y, dy in; dx out);
    resident w [cin, cout] + dw accumulator and dot temp (f32);
    f32 scratch for g (and y when emit_stats), dh, x32, plus the
    prologue temps (xn, relu mask, h — counted unconditionally, the
    round-3 OOM was exactly an unmodeled-scratch miss) and the in-dtype
    casts of h and g.
    """
    budget = 13 * 1024 * 1024
    resident = (cin * cout * in_bytes          # w
                + 2 * cin * cout * 4)          # dw accumulator + dot temp

    def tile_bytes(bm: int) -> int:
        stream = 2 * (2 * bm * cin * in_bytes + 2 * bm * cout * in_bytes)
        scratch = ((2 if emit_stats else 1) * bm * cout * 4
                   + 2 * bm * cin * 4
                   + 3 * bm * cin * 4            # prologue xn/live/h f32
                   + bm * cin * in_bytes + bm * cout * in_bytes)
        return resident + stream + scratch

    for bm in _aligned_divisors(M, cap=512):
        if bm >= 64 and tile_bytes(bm) <= budget:
            return bm
    return None


# (M, cin, cout) shapes where the Pallas-backward Mosaic compile (or its
# first execution) has been OBSERVED to stall >10 min on the real v5e —
# round-3 session A: bench_fused_kernels grad at s3_conv1 rc=124 with the
# pick_dw_tiles tiling. Populated strictly from on-chip evidence; remove
# an entry when a later session shows it compiles+runs sanely (the
# validator's VALIDATE_PALLAS_BWD sweep sets DTF_FUSED_BWD_FORCE=1 and
# times every shape precisely to produce that evidence).
PALLAS_BWD_KNOWN_SLOW: set[tuple[int, int, int]] = {
    (12544, 2048, 512),  # s3_conv1, batch-256 ResNet-50
}


def pallas_bwd_known_slow(M: int, cin: int, cout: int) -> bool:
    """True when DTF_FUSED_BWD=pallas should refuse this shape (known
    pathological compile) — overridable with DTF_FUSED_BWD_FORCE=1 for
    measurement runs."""
    import os

    if os.environ.get("DTF_FUSED_BWD_FORCE") == "1":
        return False
    return (M, cin, cout) in PALLAS_BWD_KNOWN_SLOW


def resolve_bwd_impl(bwd_impl: str | None) -> str:
    """The fused composites' backward selection policy (one home for the
    env default so the two op families cannot drift): explicit argument
    wins, else ``DTF_FUSED_BWD``, else the measured-faster "xla" path
    (round-3 on-chip microbenches, PERF_NOTES.md)."""
    import os

    impl = bwd_impl or os.environ.get("DTF_FUSED_BWD", "xla")
    if impl not in ("xla", "pallas"):
        raise ValueError(f"bwd_impl must be 'xla' or 'pallas', got {impl!r}")
    return impl
