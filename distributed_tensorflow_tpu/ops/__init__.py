"""TPU-native hot-op library (Pallas kernels + pure-JAX references).

The reference framework's hot ops lived in hand-written C++/CUDA kernels
behind the TF op registry (SURVEY.md §2b — NCCL allreduce, accumulator and
queue kernels); on TPU the data-plane equivalents are XLA-lowered collectives
plus Pallas kernels for the ops XLA cannot fuse optimally (SURVEY.md §5.8
"native-code policy"). This package holds those kernels and their pure-JAX
reference implementations (the oracle every kernel is tested against).
"""

from .attention import (  # noqa: F401
    attention_reference,
    blockwise_attention,
)
from .flash_attention import flash_attention  # noqa: F401
