"""Pallas-TPU fused 1x1-conv + BatchNorm kernels (ResNet hot path).

Why this exists (PERF_NOTES.md profile): ResNet-50 training on TPU is
HBM-bandwidth-bound, and ~2/3 of the step is BatchNorm-adjacent
elementwise/reduce passes over the widest activations — XLA cannot fuse
the BN statistics pass or the normalize pass into its conv custom-calls.
2/3 of ResNet-50's convs are 1x1 (= matmuls over [B*H*W, Cin]), so this
module fuses, into one Pallas matmul kernel:

- **prologue**: per-Cin affine ``x*scale + shift`` (+ ReLU) — i.e. the
  BatchNorm-apply of the *previous* BN — so the matmul reads the RAW
  previous conv output and the normalized tensor is never materialized;
- **epilogue**: per-Cout column ``sum``/``sumsq`` of the output — the
  statistics pass of the *next* BN — so the stats never re-read the
  output from HBM.

The backward is two more Pallas kernels over the same tiles (dx +
prologue-param reductions with the M-grid resident; dw with a
[Cin, bn]-tile accumulator), each recomputing the prologue from the raw
input in VMEM instead of re-reading a materialized normalized tensor.

Reference analog: the reference's BN ran as cuDNN
BatchNormalization{Forward,Backward}Training kernels fused with
activations (a GPU-library capability the TF substrate reached via
``fused_batch_norm``, $TF/python/ops/nn_impl.py:1631); this is the
TPU-native equivalent at the "native kernel" tier (SURVEY.md §5.8
native-code policy), shaped by the MXU/VMEM layout instead.

Numerics: inputs/outputs bf16 (or f32), all accumulation f32. The
epilogue computes stats on the *quantized* (output-dtype) values so they
match exactly what an unfused consumer would read back from HBM. On
non-TPU backends ``interpret=True`` runs the same kernels through the
Pallas interpreter (CI on fake CPU devices, SURVEY.md §4.2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401

from . import _tiling


def _pick_block_m(M: int, cin: int, cout: int) -> int:
    return _tiling.pick_block_m(M, cin, cout, name="fused conv1x1 kernel")


_on_tpu = _tiling.on_tpu


# ---------------------------------------------------------------------------
# Forward: y = (relu(x*scale+shift)) @ w  [+ column sum/sumsq of y]
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, w_ref, scale_ref, shift_ref, y_ref, sum_ref, ssq_ref,
                *, prologue, relu, emit_stats):
    x = x_ref[:].astype(jnp.float32)
    if prologue:
        x = x * scale_ref[:] + shift_ref[:]
        if relu:
            x = jnp.maximum(x, 0.0)
    h = x.astype(x_ref.dtype)
    y = jnp.dot(h, w_ref[:], preferred_element_type=jnp.float32)
    yq = y.astype(y_ref.dtype)
    y_ref[:] = yq
    if emit_stats:
        st = yq.astype(jnp.float32)

        @pl.when(pl.program_id(0) == 0)
        def _():
            sum_ref[:] = jnp.zeros_like(sum_ref)
            ssq_ref[:] = jnp.zeros_like(ssq_ref)

        sum_ref[:] += st.sum(0, keepdims=True)
        ssq_ref[:] += (st * st).sum(0, keepdims=True)


def _fwd_call(x, w, scale, shift, *, prologue, relu, emit_stats, out_dtype,
              interpret):
    M, cin = x.shape
    cout = w.shape[1]
    bm = _pick_block_m(M, cin, cout)
    kernel = functools.partial(
        _fwd_kernel, prologue=prologue, relu=relu, emit_stats=emit_stats,
    )
    y, s, ssq = pl.pallas_call(
        kernel,
        grid=(M // bm,),
        in_specs=[
            pl.BlockSpec((bm, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, cout), lambda i: (i, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, cout), out_dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        interpret=interpret,
        name="conv1x1_bn_fwd",
    )(x, w, scale, shift)
    return y, s[0], ssq[0]


# ---------------------------------------------------------------------------
# Backward A: dx (+ dscale/dshift) with the M-grid streaming
# ---------------------------------------------------------------------------


def _bwd_dx_kernel(*refs, prologue, relu, emit_stats):
    if prologue:
        (x_ref, y_ref, dy_ref, w_ref, scale_ref, shift_ref,
         dsum_ref, dssq_ref, dx_ref, dscale_ref, dshift_ref) = refs
    else:
        # no prologue: x/scale/shift are neither read nor streamed
        (y_ref, dy_ref, w_ref, dsum_ref, dssq_ref, dx_ref) = refs
    g = dy_ref[:].astype(jnp.float32)
    if emit_stats:
        # stats outputs' cotangents fold back into the output gradient:
        # d/dy [sum_c, ssq_c] = [1, 2y]
        y = y_ref[:].astype(jnp.float32)
        g = g + dsum_ref[:] + 2.0 * y * dssq_ref[:]
    # dh = g @ w^T  (contract over cout)
    dh = jax.lax.dot_general(
        g.astype(dy_ref.dtype), w_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if prologue:
        x = x_ref[:].astype(jnp.float32)
        xn = x * scale_ref[:] + shift_ref[:]
        if relu:
            live = (xn > 0.0).astype(jnp.float32)
            dh = dh * live
        dx_ref[:] = (dh * scale_ref[:]).astype(dx_ref.dtype)

        @pl.when(pl.program_id(0) == 0)
        def _():
            dscale_ref[:] = jnp.zeros_like(dscale_ref)
            dshift_ref[:] = jnp.zeros_like(dshift_ref)

        dscale_ref[:] += (dh * x).sum(0, keepdims=True)
        dshift_ref[:] += dh.sum(0, keepdims=True)
    else:
        dx_ref[:] = dh.astype(dx_ref.dtype)


def _bwd_dx_call(x, y, dy, w, scale, shift, dsum, dssq, *, prologue, relu,
                 emit_stats, interpret):
    M, cin = x.shape
    cout = w.shape[1]
    bm = _pick_block_m(M, cin, cout)
    kernel = functools.partial(
        _bwd_dx_kernel, prologue=prologue, relu=relu, emit_stats=emit_stats,
    )
    row = lambda bq, cq: pl.BlockSpec((bq, cq), lambda i: (i, 0))
    const = lambda r, cq: pl.BlockSpec((r, cq), lambda i: (0, 0))
    in_specs = [row(bm, cout), row(bm, cout), const(cin, cout),
                const(1, cout), const(1, cout)]
    inputs = [y, dy, w, dsum, dssq]
    out_specs = [row(bm, cin)]
    out_shape = [jax.ShapeDtypeStruct((M, cin), x.dtype)]
    if prologue:
        in_specs = [row(bm, cin)] + in_specs[:3] + [
            const(1, cin), const(1, cin)] + in_specs[3:]
        inputs = [x, y, dy, w, scale, shift, dsum, dssq]
        out_specs += [const(1, cin), const(1, cin)]
        out_shape += [jax.ShapeDtypeStruct((1, cin), jnp.float32)] * 2
    out = pl.pallas_call(
        kernel,
        grid=(M // bm,),  # _pick_block_m guarantees bm | M (or bm == M)
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
        name="conv1x1_bn_bwd_dx",
    )(*inputs)
    if prologue:
        dx, dscale, dshift = out
        return dx, dscale[0], dshift[0]
    (dx,) = out
    return dx, None, None  # no-prologue zero cotangents built by bwd()


# ---------------------------------------------------------------------------
# Backward B: dw = prologue(x)^T @ g, [cin, bn]-tile accumulator
# ---------------------------------------------------------------------------


def _bwd_dw_kernel(x_ref, y_ref, dy_ref, scale_ref, shift_ref,
                   dsum_ref, dssq_ref, dw_ref,
                   *, prologue, relu, emit_stats):
    g = dy_ref[:].astype(jnp.float32)
    if emit_stats:
        y = y_ref[:].astype(jnp.float32)
        g = g + dsum_ref[:] + 2.0 * y * dssq_ref[:]
    x = x_ref[:].astype(jnp.float32)
    if prologue:
        x = x * scale_ref[:] + shift_ref[:]
        if relu:
            x = jnp.maximum(x, 0.0)
    h = x.astype(x_ref.dtype)

    @pl.when(pl.program_id(1) == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    # h^T @ g (contract over the bm rows)
    dw_ref[:] += jax.lax.dot_general(
        h, g.astype(dy_ref.dtype),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _bwd_dw_call(x, y, dy, scale, shift, dsum, dssq, *, prologue, relu,
                 emit_stats, interpret):
    M, cin = x.shape
    cout = dy.shape[1]
    bm, bn = _tiling.pick_dw_tiles(
        M, cin, cout, in_bytes=x.dtype.itemsize, emit_stats=emit_stats,
        name="fused conv1x1 dw kernel",
    )
    kernel = functools.partial(
        _bwd_dw_kernel, prologue=prologue, relu=relu, emit_stats=emit_stats,
    )
    dw = pl.pallas_call(
        kernel,
        grid=(cout // bn, M // bm),  # M innermost: dw tile revisited
        in_specs=[
            pl.BlockSpec((bm, cin), lambda j, i: (i, 0)),
            pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
            pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
            pl.BlockSpec((1, cin), lambda j, i: (0, 0)),
            pl.BlockSpec((1, cin), lambda j, i: (0, 0)),
            pl.BlockSpec((1, bn), lambda j, i: (0, j)),
            pl.BlockSpec((1, bn), lambda j, i: (0, j)),
        ],
        out_specs=pl.BlockSpec((cin, bn), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((cin, cout), jnp.float32),
        interpret=interpret,
        name="conv1x1_bn_bwd_dw",
    )(x, y, dy, scale, shift, dsum, dssq)
    return dw


# ---------------------------------------------------------------------------
# Backward B': single-pass dx + dscale/dshift + dw (one sweep over
# x/y/dy — structurally half the HBM traffic of the two-pass pair; used
# by bwd_impl="pallas" whenever the whole [cin, cout] f32 dw accumulator
# fits VMEM, see _tiling.pick_single_pass_bm)
# ---------------------------------------------------------------------------


def _bwd_single_kernel(*refs, prologue, relu, emit_stats):
    if prologue:
        (x_ref, y_ref, dy_ref, w_ref, scale_ref, shift_ref,
         dsum_ref, dssq_ref,
         dx_ref, dw_ref, dscale_ref, dshift_ref) = refs
    else:
        (x_ref, y_ref, dy_ref, w_ref, dsum_ref, dssq_ref,
         dx_ref, dw_ref) = refs
    g = dy_ref[:].astype(jnp.float32)
    if emit_stats:
        y = y_ref[:].astype(jnp.float32)
        g = g + dsum_ref[:] + 2.0 * y * dssq_ref[:]
    gq = g.astype(dy_ref.dtype)
    dh = jax.lax.dot_general(
        gq, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(0) == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        if prologue:
            dscale_ref[:] = jnp.zeros_like(dscale_ref)
            dshift_ref[:] = jnp.zeros_like(dshift_ref)

    x = x_ref[:].astype(jnp.float32)
    if prologue:
        xn = x * scale_ref[:] + shift_ref[:]
        if relu:
            live = (xn > 0.0).astype(jnp.float32)
            dh = dh * live
            h = jnp.maximum(xn, 0.0)
        else:
            h = xn
        dx_ref[:] = (dh * scale_ref[:]).astype(dx_ref.dtype)
        dscale_ref[:] += (dh * x).sum(0, keepdims=True)
        dshift_ref[:] += dh.sum(0, keepdims=True)
    else:
        h = x
        dx_ref[:] = dh.astype(dx_ref.dtype)
    dw_ref[:] += jax.lax.dot_general(
        h.astype(x_ref.dtype), gq,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _bwd_single_call(x, y, dy, w, scale, shift, dsum, dssq, bm, *,
                     prologue, relu, emit_stats, interpret):
    M, cin = x.shape
    cout = w.shape[1]
    kernel = functools.partial(
        _bwd_single_kernel, prologue=prologue, relu=relu,
        emit_stats=emit_stats,
    )
    row = lambda bq, cq: pl.BlockSpec((bq, cq), lambda i: (i, 0))
    const = lambda r, cq: pl.BlockSpec((r, cq), lambda i: (0, 0))
    in_specs = [row(bm, cin), row(bm, cout), row(bm, cout),
                const(cin, cout)]
    inputs = [x, y, dy, w]
    if prologue:
        in_specs += [const(1, cin), const(1, cin)]
        inputs += [scale, shift]
    in_specs += [const(1, cout), const(1, cout)]
    inputs += [dsum, dssq]
    out_specs = [row(bm, cin), const(cin, cout)]
    out_shape = [jax.ShapeDtypeStruct((M, cin), x.dtype),
                 jax.ShapeDtypeStruct((cin, cout), jnp.float32)]
    if prologue:
        out_specs += [const(1, cin), const(1, cin)]
        out_shape += [jax.ShapeDtypeStruct((1, cin), jnp.float32)] * 2
    out = pl.pallas_call(
        kernel,
        grid=(M // bm,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
        name="conv1x1_bn_bwd_fused",
    )(*inputs)
    if prologue:
        dx, dw, dscale, dshift = out
        return dx, dw, dscale[0], dshift[0]
    dx, dw = out
    return dx, dw, None, None


# ---------------------------------------------------------------------------
# Backward C: the XLA-math backward (round-3 default)
# ---------------------------------------------------------------------------


def _xla_bwd(x, y, dy, w, scale, shift, dsum, dssq, *, prologue, relu,
             emit_stats):
    """Same math as the two Pallas backward kernels, in plain jnp.

    Round-3 on-chip microbenches (artifacts/onchip_r3/microbench_*.log):
    the Pallas FORWARD beats the unfused XLA sequence 1.0-2.5x at every
    batch-256 ResNet shape, but the two-kernel Pallas backward re-streams
    x/y/dy once per kernel (2 full passes) and loses to XLA's fused
    backward at every shape (0.40-0.87x). So the composite keeps the
    Pallas forward and defaults the VJP to this XLA path, which the
    compiler fuses into dgrad/wgrad epilogues; the Pallas backward
    kernels stay selectable (DTF_FUSED_BWD=pallas) for future tiles."""
    g = dy.astype(jnp.float32)
    if emit_stats:
        g = g + dsum + 2.0 * y.astype(jnp.float32) * dssq
    gq = g.astype(y.dtype)
    dh = jax.lax.dot_general(
        gq, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if prologue:
        x32 = x.astype(jnp.float32)
        xn = x32 * scale + shift
        if relu:
            dh = dh * (xn > 0.0).astype(jnp.float32)
        dx = (dh * scale).astype(x.dtype)
        dscale = (dh * x32).sum(0, keepdims=True)
        dshift = dh.sum(0, keepdims=True)
        h = jnp.maximum(xn, 0.0) if relu else xn
        hq = h.astype(x.dtype)
    else:
        dx = dh.astype(x.dtype)
        dscale = dshift = None
        hq = x
    dw = jax.lax.dot_general(
        hq, gq, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dx, dw, dscale, dshift


# ---------------------------------------------------------------------------
# custom_vjp composite
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _make_op(prologue, relu, emit_stats, out_dtype, interpret, bwd_impl):
    @jax.custom_vjp
    def op(x, w, scale, shift):
        y, s, ssq = _fwd_call(
            x, w, scale, shift, prologue=prologue, relu=relu,
            emit_stats=emit_stats, out_dtype=out_dtype, interpret=interpret,
        )
        return (y, s, ssq) if emit_stats else y

    def fwd(x, w, scale, shift):
        y, s, ssq = _fwd_call(
            x, w, scale, shift, prologue=prologue, relu=relu,
            emit_stats=emit_stats, out_dtype=out_dtype, interpret=interpret,
        )
        out = (y, s, ssq) if emit_stats else y
        return out, (x, y, w, scale, shift)

    def bwd(res, ct):
        x, y, w, scale, shift = res
        if emit_stats:
            dy, dsum, dssq = ct
            dsum = dsum.reshape(1, -1).astype(jnp.float32)
            dssq = dssq.reshape(1, -1).astype(jnp.float32)
        else:
            dy = ct
            cout = w.shape[1]
            dsum = jnp.zeros((1, cout), jnp.float32)
            dssq = jnp.zeros((1, cout), jnp.float32)
        dy = dy.astype(y.dtype)
        use_xla = bwd_impl == "xla"
        if not use_xla and _tiling.pallas_bwd_known_slow(
                x.shape[0], x.shape[1], w.shape[1]):
            # landmine guard (VERDICT r3 weak #4): this shape stalled
            # >10 min in the Pallas-backward path on the real chip;
            # fall back to the measured-faster XLA backward rather than
            # hang whoever flipped DTF_FUSED_BWD=pallas. Set
            # DTF_FUSED_BWD_FORCE=1 to measure it anyway.
            import warnings

            warnings.warn(
                f"conv1x1_bn pallas backward at shape (M={x.shape[0]}, "
                f"cin={x.shape[1]}, cout={w.shape[1]}) is known to stall "
                "Mosaic compilation (round-3 on-chip evidence); using the "
                "XLA backward for this shape. DTF_FUSED_BWD_FORCE=1 "
                "overrides.")
            use_xla = True
        if use_xla:
            dx, dw, dscale, dshift = _xla_bwd(
                x, y, dy, w, scale, shift, dsum, dssq, prologue=prologue,
                relu=relu, emit_stats=emit_stats,
            )
            dw = dw.astype(w.dtype)
        else:
            bm1 = _tiling.pick_single_pass_bm(
                x.shape[0], x.shape[1], w.shape[1],
                in_bytes=x.dtype.itemsize, emit_stats=emit_stats,
            )
            if bm1 is not None:
                dx, dw, dscale, dshift = _bwd_single_call(
                    x, y, dy, w, scale, shift, dsum, dssq, bm1,
                    prologue=prologue, relu=relu, emit_stats=emit_stats,
                    interpret=interpret,
                )
                dw = dw.astype(w.dtype)
            else:
                dx, dscale, dshift = _bwd_dx_call(
                    x, y, dy, w, scale, shift, dsum, dssq,
                    prologue=prologue, relu=relu, emit_stats=emit_stats,
                    interpret=interpret,
                )
                dw = _bwd_dw_call(
                    x, y, dy, scale, shift, dsum, dssq, prologue=prologue,
                    relu=relu, emit_stats=emit_stats, interpret=interpret,
                ).astype(w.dtype)
        if prologue:
            return dx, dw, dscale.reshape(scale.shape), dshift.reshape(shift.shape)
        return dx, dw, jnp.zeros_like(scale), jnp.zeros_like(shift)

    op.defvjp(fwd, bwd)
    return op


def conv1x1_bn_act(
    x: jax.Array,
    w: jax.Array,
    scale: jax.Array | None = None,
    shift: jax.Array | None = None,
    *,
    relu: bool = True,
    emit_stats: bool = True,
    out_dtype=None,
    interpret: bool | None = None,
    bwd_impl: str | None = None,
):
    """Fused ``[M, Cin] @ [Cin, Cout]`` with optional BN-apply prologue and
    stats epilogue.

    x: [M, Cin] (bf16/f32) — the RAW previous conv output (pre-BN).
    w: [Cin, Cout].
    scale/shift: per-Cin f32 — the folded BN affine
        (see :func:`bn_scale_shift`); ``None`` disables the prologue
        (``relu`` is then ignored).
    emit_stats: also return ``(col_sum, col_sumsq)`` of the output, each
        [Cout] f32 — feed :func:`moments_from_sums` for the next BN.
    Returns ``y`` or ``(y, col_sum, col_sumsq)``.
    """
    if interpret is None:
        interpret = not _on_tpu()
    prologue = scale is not None
    if not prologue:
        cin = x.shape[1]
        scale = jnp.ones((1, cin), jnp.float32)
        shift = jnp.zeros((1, cin), jnp.float32)
    else:
        scale = scale.reshape(1, -1).astype(jnp.float32)
        shift = shift.reshape(1, -1).astype(jnp.float32)
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    bwd_impl = _tiling.resolve_bwd_impl(bwd_impl)
    op = _make_op(prologue, relu, emit_stats, out_dtype.name, bool(interpret),
                  bwd_impl)
    return op(x, w, scale, shift)


# ---------------------------------------------------------------------------
# Tiny [C]-sized helpers (plain XLA; negligible traffic)
# ---------------------------------------------------------------------------


def moments_from_sums(col_sum, col_sumsq, count):
    """Column sums -> (mean, biased variance), f32."""
    mean = col_sum / count
    var = jnp.maximum(col_sumsq / count - mean * mean, 0.0)
    return mean, var


def bn_scale_shift(mean, var, gamma, beta, eps):
    """Fold BN(mean, var, gamma, beta) into a per-channel affine
    ``x*scale + shift``."""
    scale = gamma * jax.lax.rsqrt(var + eps)
    return scale, beta - mean * scale


def conv1x1_bn_act_reference(x, w, scale=None, shift=None, *, relu=True,
                             emit_stats=True, out_dtype=None):
    """Pure-jnp oracle with the same numerics contract (stats computed on
    the quantized output)."""
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    h = x.astype(jnp.float32)
    if scale is not None:
        h = h * scale.reshape(1, -1) + shift.reshape(1, -1)
        if relu:
            h = jnp.maximum(h, 0.0)
    h = h.astype(x.dtype)
    y = jnp.dot(h, w, preferred_element_type=jnp.float32).astype(out_dtype)
    if not emit_stats:
        return y
    st = y.astype(jnp.float32)
    return y, st.sum(0), (st * st).sum(0)
