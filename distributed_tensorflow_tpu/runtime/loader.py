"""Record-file loader: native C++ pipeline with a bit-identical fallback.

The host-side input pipeline tier beneath data/pipeline.Prefetcher — the
native descendant of the reference's FIFOQueue + QueueRunner machinery
($TF/python/ops/data_flow_ops.py:774; queue_runner_impl.py:34): worker
threads assemble shuffled, shard-disjoint batches from an mmap'd file of
fixed-size records and hand them over a bounded ordered queue.

Format: a flat binary file of N records × ``record_bytes`` each; the
caller supplies ``decode(raw_uint8_batch) -> batch dict`` (vectorized
numpy — e.g. split image/label bytes and cast).

Determinism: epoch e's order is Fisher–Yates under SplitMix64 with seed
``seed + e`` — the same bits in C++ (native/dtf_runtime.cpp) and here, so
the native and fallback paths produce identical streams, and resume at
batch k is exact on either path.
"""

from __future__ import annotations

import ctypes
from typing import Callable, Iterator

import numpy as np

from . import native

_M64 = (1 << 64) - 1


def _splitmix64(state: int) -> tuple[int, int]:
    state = (state + 0x9E3779B97F4A7C15) & _M64
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return state, (z ^ (z >> 31)) & _M64


def epoch_permutation(n: int, seed: int) -> np.ndarray:
    """Python mirror of the native Fisher–Yates (parity-tested)."""
    out = np.arange(n, dtype=np.int64)
    s = seed & _M64
    for i in range(n - 1, 0, -1):
        s, r = _splitmix64(s)
        j = r % (i + 1)
        out[i], out[j] = out[j], out[i]
    return out


class RecordFileLoader:
    """Iterate batches of raw records as [batch_records, record_bytes]
    uint8 arrays (decoded via ``decode`` if given).

    ``shard``/``n_shards`` slice each epoch's shuffled order stride-wise
    (disjoint across hosts — the `Dataset.shard` analog); ``start_batch``
    fast-forwards for checkpoint resume.
    """

    def __init__(
        self,
        path: str,
        record_bytes: int,
        batch_records: int,
        *,
        seed: int = 0,
        shard: int = 0,
        n_shards: int = 1,
        n_threads: int = 4,
        depth: int | None = None,  # None = n_threads (one in-flight per worker)
        decode: Callable[[np.ndarray], object] | None = None,
        start_batch: int = 0,
        num_batches: int | None = None,
        use_native: bool | None = None,  # None = auto
    ):
        self.path = path
        self.record_bytes = record_bytes
        self.batch_records = batch_records
        self.seed = seed
        self.shard = shard
        self.n_shards = n_shards
        self.n_threads = n_threads
        self.depth = n_threads if depth is None else depth
        self.decode = decode
        self.start_batch = start_batch
        self.num_batches = num_batches
        self.use_native = (
            native.available() if use_native is None else use_native
        )

        # fallback path state (also used for metadata)
        self._mm = np.memmap(path, dtype=np.uint8, mode="r")
        self.n_records = self._mm.size // record_bytes
        self.batches_per_epoch = (self.n_records // n_shards) // batch_records
        if self.batches_per_epoch < 1:
            raise ValueError(
                f"{path}: {self.n_records} records can't fill one batch of "
                f"{batch_records} over {n_shards} shard(s)"
            )
        self._perm_epoch = -1
        self._perm: np.ndarray | None = None

    # -- shared index math (mirrors Loader::batch_indices) -----------------

    def batch_indices(self, bi: int) -> np.ndarray:
        epoch, pos = divmod(bi, self.batches_per_epoch)
        if epoch != self._perm_epoch:
            self._perm = epoch_permutation(self.n_records, self.seed + epoch)
            self._perm_epoch = epoch
        k = (pos * self.batch_records + np.arange(self.batch_records)) \
            * self.n_shards + self.shard
        return self._perm[k]

    # -- iteration ---------------------------------------------------------

    def _iter_native(self) -> Iterator[np.ndarray]:
        lib = native.load_library()
        h = lib.dtf_loader_create(
            self.path.encode(), self.record_bytes, self.batch_records,
            self.n_threads, self.depth, self.seed, self.shard, self.n_shards,
            self.start_batch,
        )
        if not h:
            raise OSError(f"native loader failed to open {self.path}")
        try:
            nbytes = self.batch_records * self.record_bytes
            i = 0
            while self.num_batches is None or i < self.num_batches:
                b = lib.dtf_loader_next(h)
                if not b:
                    return
                buf = np.ctypeslib.as_array(
                    lib.dtf_batch_data(b), shape=(nbytes,)
                ).copy()
                lib.dtf_loader_release(h, b)
                yield buf.reshape(self.batch_records, self.record_bytes)
                i += 1
        finally:
            lib.dtf_loader_destroy(h)

    def _iter_python(self) -> Iterator[np.ndarray]:
        i = 0
        bi = self.start_batch
        while self.num_batches is None or i < self.num_batches:
            idx = self.batch_indices(bi)
            # fancy indexing already copies out of the memmap; asarray just
            # normalizes the subclass without a second memcpy
            yield np.asarray(
                self._mm.reshape(self.n_records, self.record_bytes)[idx]
            )
            bi += 1
            i += 1

    def __iter__(self):
        it = self._iter_native() if self.use_native else self._iter_python()
        if self.decode is None:
            return it
        return (self.decode(raw) for raw in it)
