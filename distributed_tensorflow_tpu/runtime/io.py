"""Checksummed atomic payload IO — the Saver-IO-kernel analog.

Format (shared with native/dtf_runtime.cpp): payload bytes followed by a
20-byte trailer [magic "DTFCKPT1"][u64 LE length][u32 LE zlib-CRC32].
Writes go to <path>.tmp then fsync + rename, so a crash mid-write never
clobbers an existing good shard (the reference Saver's discipline,
$TF/python/training/saver.py:642 → C++ IO kernels). Native C++ path when
the library is built; byte-identical Python fallback otherwise.
"""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np

from . import native

_MAGIC = b"DTFCKPT1"


def write_payload(path: str, data: bytes | np.ndarray) -> None:
    buf = np.ascontiguousarray(
        np.frombuffer(data, np.uint8) if isinstance(data, bytes)
        else data.view(np.uint8).reshape(-1)
    )
    lib = native.load_library()
    if lib is not None:
        rc = lib.dtf_write_file(
            path.encode(), buf.ctypes.data, buf.size
        )
        if rc != 0:
            raise OSError(f"native write to {path} failed (rc={rc})")
        return
    tmp = path + ".tmp"
    view = memoryview(buf)  # zero-copy: crc32 and write take buffers
    trailer = _MAGIC + struct.pack("<QI", buf.size,
                                   zlib.crc32(view) & 0xFFFFFFFF)
    with open(tmp, "wb") as f:
        f.write(view)
        f.write(trailer)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_payload(path: str) -> bytes:
    """Read + CRC-verify a payload; raises on truncation/corruption."""
    lib = native.load_library()
    if lib is not None:
        size = lib.dtf_read_file(path.encode(), None, 0)
        if size < 0:
            raise OSError(f"{path}: invalid payload (rc={size})")
        out = np.empty(size, np.uint8)
        rc = lib.dtf_read_file(path.encode(), out.ctypes.data, size)
        if rc == -3:
            raise OSError(f"{path}: CRC mismatch (corrupt shard)")
        if rc < 0:
            raise OSError(f"{path}: read failed (rc={rc})")
        return out.tobytes()
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < 20 or raw[-20:-12] != _MAGIC:
        raise OSError(f"{path}: missing/invalid trailer")
    length, crc = struct.unpack("<QI", raw[-12:])
    payload = raw[:-20]
    if length != len(payload):
        raise OSError(f"{path}: length mismatch")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise OSError(f"{path}: CRC mismatch (corrupt shard)")
    return payload
