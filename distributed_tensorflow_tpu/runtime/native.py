"""Build + load the native runtime library (ctypes).

Policy: compile on first use with g++ (-O3, no external deps), cache the
.so beside the source, degrade silently to the Python fallbacks if a
toolchain isn't present. The C ABI is small and stable — see
native/dtf_runtime.cpp for the contract.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "dtf_runtime.cpp")
_BUILD_DIR = os.path.join(_REPO, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libdtf_runtime.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.dtf_loader_create.restype = c.c_void_p
    lib.dtf_loader_create.argtypes = [
        c.c_char_p, c.c_int64, c.c_int64, c.c_int, c.c_int, c.c_uint64,
        c.c_int64, c.c_int64, c.c_int64,
    ]
    lib.dtf_loader_batches_per_epoch.restype = c.c_int64
    lib.dtf_loader_batches_per_epoch.argtypes = [c.c_void_p]
    lib.dtf_loader_n_records.restype = c.c_int64
    lib.dtf_loader_n_records.argtypes = [c.c_void_p]
    lib.dtf_loader_next.restype = c.c_void_p
    lib.dtf_loader_next.argtypes = [c.c_void_p]
    lib.dtf_batch_data.restype = c.POINTER(c.c_uint8)
    lib.dtf_batch_data.argtypes = [c.c_void_p]
    lib.dtf_batch_index.restype = c.c_int64
    lib.dtf_batch_index.argtypes = [c.c_void_p]
    lib.dtf_loader_release.argtypes = [c.c_void_p, c.c_void_p]
    lib.dtf_loader_destroy.argtypes = [c.c_void_p]
    lib.dtf_loader_batch_indices.argtypes = [
        c.c_void_p, c.c_int64, c.POINTER(c.c_int64),
    ]
    lib.dtf_epoch_permutation.argtypes = [
        c.c_int64, c.c_uint64, c.POINTER(c.c_int64),
    ]
    lib.dtf_write_file.restype = c.c_int
    lib.dtf_write_file.argtypes = [c.c_char_p, c.c_void_p, c.c_int64]
    lib.dtf_read_file.restype = c.c_int64
    lib.dtf_read_file.argtypes = [c.c_char_p, c.c_void_p, c.c_int64]
    lib.dtf_crc32.restype = c.c_uint32
    lib.dtf_crc32.argtypes = [c.c_void_p, c.c_int64]
    return lib


def _build() -> str | None:
    if not os.path.exists(_SRC):
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # per-process tmp name: concurrent first-use builds (multi-process jax,
    # pytest-xdist) each write their own file; os.replace is atomic, last
    # writer wins with a complete library either way
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, _SO)
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native runtime build failed (%s); using Python "
                       "fallbacks", e)
        return None
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _SO


def load_library() -> ctypes.CDLL | None:
    """The loaded native library, building it if needed; None if
    unavailable (callers must fall back)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            lib = _configure(ctypes.CDLL(so))
            # sanity-probe a pure function; a corrupt/stale .so fails here
            # (AttributeError when a symbol is missing from an old build),
            # and deleting it makes the next process rebuild cleanly
            if lib.dtf_crc32(b"123456789", 9) != 0xCBF43926:
                raise OSError("crc self-test failed")
            _lib = lib
        except (OSError, AttributeError) as e:
            logger.warning("native runtime load failed (%s); rebuilding "
                           "next run", e)
            try:
                os.unlink(so)
            except OSError:
                pass
            _lib = None
        return _lib


def available() -> bool:
    return load_library() is not None
