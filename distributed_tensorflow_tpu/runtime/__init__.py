"""Native host runtime bindings (C++ core in native/dtf_runtime.cpp).

The reference's host data plane was C++ behind Python wrappers (SURVEY.md
§2b: FIFOQueue/accumulator kernels, QueueRunner, Saver IO kernels). This
package is the TPU-native equivalent: a compiled record loader and
checksummed checkpoint IO, bound via ctypes (no pybind11 in the image),
with bit-identical pure-Python fallbacks so nothing hard-depends on a
toolchain at run time.
"""

from .native import available, load_library  # noqa: F401
from .loader import RecordFileLoader, epoch_permutation  # noqa: F401
from .io import read_payload, write_payload  # noqa: F401
