"""Bridges flax modules to the train engine's loss-fn contract.

The reference's model fns were raw-TF builder functions wired into the
harness by `replica_device_setter` scope (SURVEY.md §2a 'Model fns' row);
here a model is a flax Module plus a loss adapter, and placement is the
sharding rules' job, fully decoupled from model code.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax


def classification_loss_fn(
    model, *, weight_decay: float = 0.0, label_smoothing: float = 0.0
) -> Callable:
    """loss_fn(params, model_state, batch, rng) for models whose apply
    returns logits. Handles mutable collections (BatchNorm batch_stats —
    which under GSPMD jit become cross-replica-synced BN for free, since
    the batch-axis mean is computed over the sharded global batch) and
    dropout rngs. Batch: {"image"|"x": ..., "label": int}."""

    def loss_fn(params, model_state, batch, rng):
        x = batch.get("image", batch.get("x"))
        labels = batch["label"]
        variables = {"params": params, **model_state}
        mutable = list(model_state.keys())
        out = model.apply(
            variables, x, train=True,
            mutable=mutable if mutable else False,
            rngs={"dropout": rng},
        )
        logits, new_model_state = out if mutable else (out, model_state)
        if label_smoothing > 0:
            num_classes = logits.shape[-1]
            onehot = optax.smooth_labels(
                jax.nn.one_hot(labels, num_classes), label_smoothing
            )
            loss = optax.softmax_cross_entropy(logits.astype(jnp.float32), onehot).mean()
        else:
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()
        if weight_decay > 0:
            l2 = sum(
                jnp.sum(p.astype(jnp.float32) ** 2)
                for p in jax.tree.leaves(params)
                if p.ndim > 1  # kernels only, not biases/scales
            )
            loss = loss + weight_decay * 0.5 * l2
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, (new_model_state, {"accuracy": acc})

    return loss_fn


def classification_eval_fn(model) -> Callable:
    """eval_fn(params, model_state, batch) -> summed correct/count/loss —
    summed (not averaged) so sharded eval shards aggregate exactly."""

    def eval_fn(params, model_state, batch):
        x = batch.get("image", batch.get("x"))
        labels = batch["label"]
        variables = {"params": params, **model_state}
        logits = model.apply(variables, x, train=False, mutable=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).sum()
        correct = jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        # top-5: the reference's standard companion to the top-1 gate
        # (ImageNet reporting convention); k clamps for tiny test heads
        k = min(5, logits.shape[-1])
        _, topk = jax.lax.top_k(logits.astype(jnp.float32), k)
        top5 = jnp.sum(
            jnp.any(topk == labels[:, None], axis=-1).astype(jnp.float32)
        )
        count = jnp.asarray(labels.shape[0], jnp.float32)
        return {"loss_sum": loss, "correct": correct,
                "top5_correct": top5, "count": count}

    return eval_fn


def make_init_fn(model, input_shape, dtype=jnp.float32) -> Callable:
    """init_fn(rng) -> (params, model_state) for init_train_state."""

    def init_fn(rng):
        dummy = jnp.zeros((1, *input_shape), dtype)
        variables = model.init({"params": rng, "dropout": rng}, dummy, train=False)
        variables = dict(variables)
        params = variables.pop("params")
        return params, variables

    return init_fn


def param_count(params: Any) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
