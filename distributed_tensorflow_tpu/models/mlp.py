"""MNIST MLP — BASELINE.json:7 workload 1 (reference: raw-TF dense layers
under replica_device_setter scope, SURVEY.md §2a). bf16-friendly: matmuls in
``dtype``, params in f32."""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    hidden_sizes: tuple = (512, 512)
    num_classes: int = 10
    dropout_rate: float = 0.0
    dtype: str = "float32"  # compute dtype; params stay float32


class MLP(nn.Module):
    cfg: MLPConfig

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        dtype = jnp.dtype(self.cfg.dtype)
        x = x.reshape(x.shape[0], -1).astype(dtype)
        for i, h in enumerate(self.cfg.hidden_sizes):
            x = nn.Dense(h, dtype=dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
            if self.cfg.dropout_rate > 0:
                x = nn.Dropout(self.cfg.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.cfg.num_classes, dtype=dtype, name="head")(x)


def flops_per_example(cfg: MLPConfig, input_dim: int = 784) -> float:
    """Forward FLOPs (framework contract: fwd-only, see utils/flops.py)."""
    dims = [input_dim, *cfg.hidden_sizes, cfg.num_classes]
    return sum(2.0 * a * b for a, b in zip(dims, dims[1:]))
